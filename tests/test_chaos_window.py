"""Window-protocol recovery under chaos (round-9 pipelined append
windows): a deterministic scenario asserting that depth>1 lanes survive
a mid-window follower crash/restart with a truncated durable tail —
zero lost acks, no match regression, and the ``windowed_rewinds`` /
``lane_resets`` recovery counters actually move.

The schedule (``window_crash``, ratis_tpu.chaos.scenarios): slow the
victim follower so sequenced frames pile onto its lanes, crash it with
frames in flight, truncate its durable log tail on disk, restart.  The
sender side must re-cut its lanes fresh (``lane_resets``: the crashed
receiver's sequence space is gone), the first post-restart append must
come back INCONSISTENCY and rewind through the windowed path
(``rewinds`` / ``windowed_rewinds``: >1 unacked frame dropped by the
epoch bump), and the recording oracle must show every acked write applied
exactly once on every replica once the follower has caught back up.
"""

import asyncio

import pytest

from ratis_tpu.chaos.cluster import ChaosCluster
from ratis_tpu.chaos.scenario import run_scenario
from ratis_tpu.chaos.scenarios import build_scenario

SEED = 9


def _window_metrics(cluster: ChaosCluster) -> dict:
    out = {"rewinds": 0, "windowed_rewinds": 0, "lane_resets": 0}
    for s in cluster.servers.values():
        for k in out:
            out[k] += s.replication.metrics.get(k, 0)
    return out


@pytest.mark.chaos
def test_depth_gt1_lanes_survive_midwindow_crash_with_truncated_tail(
        tmp_path):
    async def main():
        # defaults carry the round-9 window protocol: sweep=1,
        # coalescing on, window-depth 4 (sequenced lanes); durable
        # storage so the restart genuinely loses its tail on disk
        cluster = ChaosCluster(3, 1, storage_root=str(tmp_path), seed=SEED)
        await cluster.start()
        try:
            assert cluster.servers[
                cluster.all_peer_ids()[0]].replication.window_depth > 1, \
                "test requires the pipelined (depth>1) window protocol"
            before = _window_metrics(cluster)
            sc = build_scenario(
                "window_crash", SEED,
                {"convergence_s": 30.0, "recovery_s": 60.0,
                 "min_acked": 20, "durable": True, "truncate_tail": 3})
            res = await run_scenario(cluster, sc)
            # zero lost acks + exactly-once + replica agreement are the
            # engine's own SLO gate
            assert res.passed, (
                f"[seed {SEED}] window_crash failed: {res.error}\n"
                f"journal: {res.journal}")
            assert res.checks["lost"] == 0 and res.checks["dupes"] == 0

            after = _window_metrics(cluster)
            delta = {k: after[k] - before[k] for k in after}
            # the crash mid-window forces a lane re-cut (the receiver's
            # sequence space died with it)...
            assert delta["lane_resets"] >= 1, \
                f"[seed {SEED}] no lane reset recorded: {delta}"
            # ...and the truncated tail forces INCONSISTENCY rewinds,
            # at least one taken with >1 frame of the group in flight
            # (the windowed rewind path, not a full window reset)
            assert delta["rewinds"] >= 1, \
                f"[seed {SEED}] no rewind recorded: {delta}"
            assert delta["windowed_rewinds"] >= 1, \
                f"[seed {SEED}] no WINDOWED rewind recorded: {delta}"

            # no match regression once healed: every follower's match
            # converged to the leader's last index (a stale/over-advanced
            # match after the truncate would strand it below)
            leader = await cluster.wait_for_leader()
            last = leader.state.log.next_index - 1
            for pid, f in leader.leader_ctx.followers.items():
                assert f.match_index == last, (
                    f"[seed {SEED}] follower {pid} match "
                    f"{f.match_index} != leader last {last}")
        finally:
            await cluster.close()

    asyncio.run(main())
