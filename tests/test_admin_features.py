"""Commit-info piggybacking, live property reconfiguration, and the
membership console demo (reference CommitInfoCache / Reconfigurable surface /
examples.membership.server.Console)."""

import asyncio

import pytest

from minicluster import MiniCluster, free_port, run_with_new_cluster
from ratis_tpu.conf import RaftServerConfigKeys
from ratis_tpu.conf.reconfiguration import ReconfigurationException


def test_commit_infos_on_replies():
    """Every client reply carries the cluster-wide commit picture
    (reference RaftClientReply.getCommitInfos / CommitInfoCache)."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        for _ in range(3):
            reply = await cluster.send_write()
            assert reply.success
        reply = await cluster.send_write()
        infos = {str(c.server): c.commit_index for c in reply.commit_infos}
        peers = {str(p.id) for p in cluster.group.peers}
        assert set(infos) == peers, infos
        # the leader's own entry reflects the just-committed write
        assert max(infos.values()) >= reply.log_index
        # follower entries are fresh within a heartbeat round
        await asyncio.sleep(0.3)
        reply = await cluster.send_write()
        infos = {str(c.server): c.commit_index for c in reply.commit_infos}
        assert all(v >= 1 for v in infos.values()), infos

    run_with_new_cluster(3, body)


def test_live_reconfiguration():
    """Runtime-tunable keys apply to live divisions; unknown keys are
    rejected (reference Reconfigurable/ReconfigurationException)."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        srv = next(iter(cluster.servers.values()))
        div = next(iter(srv.divisions.values()))
        K = RaftServerConfigKeys
        assert div._slowness_timeout_s != 5.0
        await srv.reconfiguration.reconfigure(
            K.Rpc.SLOWNESS_TIMEOUT_KEY, "5s")
        assert div._slowness_timeout_s == 5.0
        await srv.reconfiguration.reconfigure(
            K.Snapshot.AUTO_TRIGGER_THRESHOLD_KEY, "77")
        assert div._snapshot_threshold == 77
        assert K.Rpc.SLOWNESS_TIMEOUT_KEY \
            in srv.reconfiguration.reconfigurable_properties()
        with pytest.raises(ReconfigurationException):
            await srv.reconfiguration.reconfigure(
                "raft.server.storage.dir", "/tmp/nope")

    run_with_new_cluster(3, body)


def test_membership_console_script():
    """The membership demo end to end: show/incr/query plus add/remove
    changing the live configuration (reference Console.java:29)."""
    from ratis_tpu.tools.membership_console import run_script

    ports = [free_port() for _ in range(4)]
    initial, extra = ports[:3], ports[3]

    async def main():
        out = await run_script(initial, [
            "show",
            "incr", "incr",
            "query",
            f"add {extra}",
            "show",
            "incr",
            f"remove {initial[0]}",
            "show",
            "query",
        ])
        assert "cluster peers:" in out[0] and out[0].count("p") >= 3
        assert out[3] == "counter = 2"
        assert str(extra) in out[4]
        assert f"p{extra}" in out[5]
        assert out[6] == "counter = 3"
        assert f"p{initial[0]}" not in out[8]
        assert out[9] == "counter = 3"

    asyncio.run(main())


def test_leader_auto_yields_to_higher_priority_peer():
    """Raising a follower's priority via setConfiguration moves leadership
    to it automatically (reference checkPeersForYieldingLeader:1058) — no
    explicit transferLeadership call."""
    import dataclasses

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        assert (await cluster.send_write()).success
        target = next(d for d in cluster.divisions() if not d.is_leader())
        tid = target.member_id.peer_id
        new_peers = [dataclasses.replace(p, priority=(5 if p.id == tid else 0))
                     for p in cluster.group.peers]
        async with cluster.new_client() as client:
            reply = await client.admin().set_configuration(new_peers)
            assert reply.success, reply.exception
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            leaders = [d for d in cluster.divisions() if d.is_leader()]
            if leaders and leaders[-1].member_id.peer_id == tid \
                    and len(leaders) == 1:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(
                f"leadership did not yield to {tid}; roles: "
                f"{[(str(d.member_id), d.role.name) for d in cluster.divisions()]}")
        # cluster still serves writes under the new leader
        assert (await cluster.send_write()).success

    run_with_new_cluster(3, body)
