"""Cluster suites with the batched engine ENGAGED on every tick
(scalar_fallback_threshold=0): the TPU-native execution mode running the
same scenarios the scalar-fallback suites cover, plus the multi-raft axis
itself — many groups on one server trio with concurrent writes, elections,
and kill/restart (reference RaftServerProxy.java:89-188 multi-group hosting,
MiniRaftCluster.runWithNewCluster harness).
"""

import asyncio

import pytest

from minicluster import (MiniCluster, batched_properties,
                         run_with_new_cluster)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


def run_batched(num_servers, test, **kwargs):
    kwargs.setdefault("properties", batched_properties())
    run_with_new_cluster(num_servers, test, **kwargs)


def test_batched_write_replicate_apply():
    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        for i in range(1, 8):
            reply = await cluster.send_write()
            assert reply.success
            assert reply.message.content == str(i).encode()
        # every tick went through the jitted kernel
        engines = [s.engine for s in cluster.servers.values()]
        assert all(e.metrics["batched_dispatches"] > 0 for e in engines)
        # every non-idle tick went through the jitted kernel (no scalar
        # fallback tick ever ran; idle ticks may skip the dispatch)
        assert all(e.metrics["ticks"] == e.metrics["batched_dispatches"]
                   + e.metrics["idle_skips"] for e in engines)
        last = cluster.leaders()[0].state.log.get_last_committed_index()
        await cluster.wait_applied(last)
        for d in cluster.divisions():
            assert d.state_machine.counter == 7

    run_batched(3, body)


def test_batched_leader_kill_reelection():
    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        assert (await cluster.send_write()).success
        await cluster.kill_server(leader.member_id.peer_id)
        new_leader = await cluster.wait_for_leader()
        assert new_leader.member_id != leader.member_id
        reply = await cluster.send_write()
        assert reply.success
        assert reply.message.content == b"2"

    run_batched(3, body)


def test_batched_reconfiguration_add_peers():
    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for _ in range(3):
                assert (await client.io().send(b"INCREMENT")).success
            new_peers = [RaftPeer(RaftPeerId.value_of(f"y{i}"),
                                  address=f"sim:y{i}") for i in range(2)]
            for p in new_peers:
                await cluster.add_new_server(p)
            current = list(cluster.group.peers)
            reply = await client.admin().set_configuration(
                current + new_peers)
            assert reply.success, reply.exception
            assert (await client.io().send(b"INCREMENT")).success
            # all 5 members converge
            await asyncio.sleep(0)
            for s in cluster.servers.values():
                d = s.divisions.get(cluster.group.group_id)
                if d is not None:
                    assert len(d.state.configuration.conf.peers) == 5

    run_batched(3, body)


def _make_sibling_group(base: RaftGroup) -> RaftGroup:
    return RaftGroup.value_of(RaftGroupId.random_id(), base.peers)


async def _wait_group_leader(cluster: MiniCluster, group_id,
                             timeout: float = 20.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        leaders = [s.divisions[group_id] for s in cluster.servers.values()
                   if group_id in s.divisions
                   and s.divisions[group_id].is_leader()]
        if leaders:
            top = max(leaders, key=lambda d: d.state.current_term)
            if all(d.state.current_term < top.state.current_term
                   for d in leaders if d is not top):
                return top
        await asyncio.sleep(0.02)
    raise TimeoutError(f"no leader for group {group_id} after {timeout}s")


def test_64_groups_concurrent_writes_and_restart():
    """The multi-raft axis in anger: 64 groups on one 3-server trio, all
    ticked by ONE engine per server through the batched kernel; concurrent
    writes across every group, then a server kill + writes + restart +
    catch-up (reference RaftServerProxy multi-group + ServerRestartTests)."""

    N_GROUPS = 64
    WRITES_PER_GROUP = 3

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        groups = [cluster.group]
        for _ in range(N_GROUPS - 1):
            g = _make_sibling_group(cluster.group)
            for s in cluster.servers.values():
                await s.group_add(g)
            groups.append(g)

        # engine hosts all 64 slots per server
        for s in cluster.servers.values():
            assert len(s.engine.state.active) == N_GROUPS

        await asyncio.gather(*(
            _wait_group_leader(cluster, g.group_id) for g in groups))

        async def write_group(g: RaftGroup, n: int):
            for _ in range(n):
                reply = await cluster.send(b"INCREMENT",
                                           group_id=g.group_id,
                                           timeout=30.0)
                assert reply.success
        await asyncio.gather(*(
            write_group(g, WRITES_PER_GROUP) for g in groups))

        engines = [s.engine for s in cluster.servers.values()]
        assert all(e.metrics["batched_dispatches"] > 0 for e in engines)

        # kill one server: every group keeps a 2/3 majority
        victim = next(iter(cluster.servers))
        await cluster.kill_server(victim)
        await asyncio.gather(*(
            write_group(g, 1) for g in groups[:8]))

        # restart: the victim re-hosts ALL groups from scratch (memory logs
        # are volatile, so it rejoins via normal append catch-up)
        server = await cluster.restart_server(victim)
        for g in groups[1:]:
            await server.group_add(g)

        async def caught_up():
            for g in groups[:8]:
                d = server.divisions.get(g.group_id)
                lead = await _wait_group_leader(cluster, g.group_id)
                if d is None or \
                        d.applied_index < lead.state.log.get_last_committed_index():
                    return False
            return True
        deadline = asyncio.get_event_loop().time() + 20.0
        while asyncio.get_event_loop().time() < deadline:
            if await caught_up():
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("restarted server did not catch up")

        # spot-check convergence on a written group
        g = groups[3]
        lead = await _wait_group_leader(cluster, g.group_id)
        assert lead.state_machine.counter >= WRITES_PER_GROUP

    run_batched(3, body)


def test_data_path_coalescing_across_groups():
    """Entry-append RPC volume is O(server pairs), not O(groups): many
    groups' pipelined batches toward one peer fold into single
    AppendEnvelopes (VERDICT r2 item 1 — the data-path extension of
    heartbeat coalescing)."""

    N_GROUPS = 8

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        groups = [cluster.group]
        for _ in range(N_GROUPS - 1):
            g = _make_sibling_group(cluster.group)
            for s in cluster.servers.values():
                await s.group_add(g)
            groups.append(g)
        await asyncio.gather(*(
            _wait_group_leader(cluster, g.group_id) for g in groups))
        for s in cluster.servers.values():
            assert s.replication.coalescing
            s.replication.metrics["envelopes"] = 0
            s.replication.metrics["items"] = 0

        # concurrent writes on every group: batches bound for the same
        # destination server land in shared envelopes
        async def write_group(g):
            for _ in range(4):
                reply = await cluster.send(b"INCREMENT", group_id=g.group_id,
                                           timeout=30.0)
                assert reply.success
        await asyncio.gather(*(write_group(g) for g in groups))

        envs = sum(s.replication.metrics["envelopes"]
                   for s in cluster.servers.values())
        items = sum(s.replication.metrics["items"]
                    for s in cluster.servers.values())
        assert envs > 0
        assert items > envs, (items, envs)  # real folding happened

        # correctness unaffected: counters converged on the leaders
        for g in groups:
            lead = await _wait_group_leader(cluster, g.group_id)
            last = lead.state.log.get_last_committed_index()
            deadline = asyncio.get_event_loop().time() + 10.0
            while (lead.applied_index < last
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.01)
            assert lead.state_machine.counter >= 4

    run_batched(3, body)


def test_coalescing_disabled_unary_fallback():
    """With data-path coalescing off (the benchmark's reference-cost-shape
    mode) replication still flows — one unary RPC per batch."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        for s in cluster.servers.values():
            assert not s.replication.coalescing
        for i in range(1, 5):
            reply = await cluster.send_write()
            assert reply.success
            assert reply.message.content == str(i).encode()

    props = batched_properties()
    props.set("raft.server.log.appender.coalescing.enabled", "false")
    run_batched(3, body, properties=props)


def test_heartbeat_coalescing_across_groups():
    """Idle heartbeat RPC volume is O(server pairs), not O(groups): many
    groups' heartbeats toward one peer fold into single envelopes."""

    N_GROUPS = 8

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        groups = [cluster.group]
        for _ in range(N_GROUPS - 1):
            g = _make_sibling_group(cluster.group)
            for s in cluster.servers.values():
                await s.group_add(g)
            groups.append(g)
        await asyncio.gather(*(
            _wait_group_leader(cluster, g.group_id) for g in groups))
        # sanity: the opt-in flag reached the servers
        assert all(s.heartbeat_coalescing for s in cluster.servers.values())
        # let a few heartbeat intervals pass while idle
        await asyncio.sleep(0.6)
        batches = sum(s.heartbeats.metrics["batches"]
                      for s in cluster.servers.values())
        hbs = sum(s.heartbeats.metrics["heartbeats"]
                  for s in cluster.servers.values())
        assert batches > 0
        assert hbs > batches, (hbs, batches)  # real folding happened
        # correctness unaffected: writes commit on every group
        for g in groups[:3]:
            reply = await cluster.send(b"INCREMENT", group_id=g.group_id,
                                       timeout=30.0)
            assert reply.success

    from minicluster import batched_properties
    props = batched_properties()
    props.set("raft.tpu.heartbeat.coalescing.enabled", "true")  # opt in
    run_batched(3, body, properties=props)


def test_bulk_heartbeat_busy_skip_no_hol_blocking():
    """A division whose append lock is held replies BULK_HB_BUSY without
    stalling the rest of the envelope's items (head-of-line-blocking fix):
    other divisions' items are served inline, and the busy division's
    election deadline is safe because the lock-holding append resets it."""

    async def body(cluster: MiniCluster):
        from ratis_tpu.protocol.raftrpc import (BULK_HB_BUSY, BULK_HB_OK,
                                                BulkHeartbeat)
        await cluster.wait_for_leader()
        # two groups on the same servers: add a sibling group
        import uuid as _uuid

        from ratis_tpu.protocol.group import RaftGroup
        from ratis_tpu.protocol.ids import RaftGroupId
        g2 = RaftGroup.value_of(RaftGroupId.random_id(),
                                list(cluster.group.peers))
        for s in cluster.servers.values():
            await s.group_add(g2)
        # pick a follower server and craft a 2-item bulk heartbeat from the
        # leader of group 1 while group-2's append lock is HELD
        leader = await cluster.wait_for_leader()
        lid = leader.member_id.peer_id
        follower_srv = next(s for s in cluster.servers.values()
                            if s.peer_id != lid)
        d1 = follower_srv.divisions[cluster.group.group_id]
        d2 = follower_srv.divisions[g2.group_id]
        async with d2._append_lock:  # simulate an in-flight slow append
            items = (
                (cluster.group.group_id.to_bytes(),
                 d1.state.current_term, -1, -1),
                (g2.group_id.to_bytes(), d2.state.current_term, -1, -1),
            )
            reply = await follower_srv._handle_bulk_heartbeat(
                BulkHeartbeat(lid, follower_srv.peer_id, items))
        codes = [item[0] for item in reply.items]
        assert codes[0] == BULK_HB_OK, reply.items
        assert codes[1] == BULK_HB_BUSY, reply.items

    run_with_new_cluster(3, body)
