"""Substrate unit tests: config, ids, value types, retry, lifecycle, codecs."""

import pytest

from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys, parse_size
from ratis_tpu.protocol import (ClientId, Message, RaftGroup, RaftGroupId,
                                RaftPeer, RaftPeerId, RaftPeerRole, TermIndex)
from ratis_tpu.protocol.exceptions import (NotLeaderException,
                                           NotReplicatedException,
                                           exception_from_wire,
                                           exception_to_wire)
from ratis_tpu.protocol.logentry import (LogEntry, LogEntryKind,
                                         make_config_entry,
                                         make_metadata_entry,
                                         make_transaction_entry)
from ratis_tpu.protocol.raftrpc import (AppendEntriesReply,
                                        AppendEntriesRequest, AppendResult,
                                        RaftRpcHeader, RequestVoteRequest,
                                        decode_rpc, encode_rpc)
from ratis_tpu.protocol.requests import (RaftClientReply, RaftClientRequest,
                                         ReplicationLevel,
                                         watch_request_type)
from ratis_tpu.retry import (ClientRetryEvent, ExponentialBackoffRetry,
                             MultipleLinearRandomRetry, RetryPolicies)
from ratis_tpu.util import LifeCycle, LifeCycleState, TimeDuration
from ratis_tpu.util.lifecycle import IllegalLifeCycleTransition


class TestTimeDuration:
    def test_parse_units(self):
        assert TimeDuration.valueOf("150ms").seconds == pytest.approx(0.15)
        assert TimeDuration.valueOf("3s").seconds == 3
        assert TimeDuration.valueOf("2min").seconds == 120
        assert TimeDuration.valueOf("1h").seconds == 3600
        assert TimeDuration.valueOf(0.5).seconds == 0.5

    def test_ordering_arithmetic(self):
        a, b = TimeDuration.valueOf("100ms"), TimeDuration.valueOf("1s")
        assert a < b
        assert b.multiply(2).seconds == 2
        assert b.subtract(a).seconds == pytest.approx(0.9)

    def test_bad_parse(self):
        with pytest.raises(ValueError):
            TimeDuration.valueOf("abc")


class TestRaftProperties:
    def test_typed_getters(self):
        p = RaftProperties()
        p.set_int("a.b", 42)
        p.set_boolean("flag", True)
        p.set("dur", "250ms")
        p.set("size", "4MB")
        assert p.get_int("a.b", 0) == 42
        assert p.get_boolean("flag", False)
        assert p.get_time_duration("dur", "1s").to_ms() == 250
        assert p.get_size("size", 0) == 4 << 20
        assert p.get_int("missing", 7) == 7

    def test_variable_substitution(self):
        p = RaftProperties()
        p.set("base", "/data")
        p.set("raft.server.storage.dir", "${base}/ratis")
        assert p.get("raft.server.storage.dir") == "/data/ratis"

    def test_size_parse(self):
        assert parse_size("64KB") == 64 << 10
        assert parse_size("1gb") == 1 << 30
        assert parse_size(123) == 123

    def test_config_keys(self):
        p = RaftProperties()
        assert RaftServerConfigKeys.Rpc.timeout_min(p).to_ms() == 150
        RaftServerConfigKeys.Rpc.set_timeout(p, "10ms", "20ms")
        assert RaftServerConfigKeys.Rpc.timeout_max(p).to_ms() == 20
        assert RaftServerConfigKeys.Log.segment_size_max(p) == 8 << 20


class TestIds:
    def test_uuid_roundtrip(self):
        g = RaftGroupId.random_id()
        assert RaftGroupId.value_of(g.to_bytes()) == g
        assert not g.is_empty()
        assert RaftGroupId.empty_id().is_empty()

    def test_peer_id(self):
        p = RaftPeerId.value_of("s0")
        assert p == RaftPeerId.value_of(b"s0")
        assert str(p) == "s0"

    def test_group(self):
        peers = tuple(RaftPeer(RaftPeerId.value_of(f"s{i}")) for i in range(3))
        g = RaftGroup.value_of(RaftGroupId.random_id(), peers)
        assert g.get_peer(RaftPeerId.value_of("s1")) == peers[1]
        assert g.get_peer(RaftPeerId.value_of("nope")) is None
        assert RaftGroup.from_dict(g.to_dict()) == g

    def test_peer_roundtrip(self):
        p = RaftPeer(RaftPeerId.value_of("x"), address="h:1", priority=2,
                     startup_role=RaftPeerRole.LISTENER)
        assert RaftPeer.from_dict(p.to_dict()) == p
        assert p.is_listener()


class TestLogEntryCodec:
    def test_transaction_roundtrip(self):
        e = make_transaction_entry(3, 17, ClientId.random_id(), 5, b"payload",
                                   sm_data=b"smdata")
        e2 = LogEntry.from_bytes(e.to_bytes())
        assert e2 == e
        assert e2.term_index() == TermIndex(3, 17)

    def test_sm_data_excluded_from_storage_bytes(self):
        e = make_transaction_entry(1, 1, ClientId.random_id(), 1, b"d", b"big" * 100)
        stored = LogEntry.from_bytes(e.to_bytes(include_sm_data=False))
        assert stored.smlog.sm_data is None
        assert stored.smlog.log_data == b"d"

    def test_config_roundtrip(self):
        peers = [RaftPeer(RaftPeerId.value_of(f"s{i}"), priority=i) for i in range(3)]
        e = make_config_entry(2, 9, peers, old_peers=peers[:2])
        e2 = LogEntry.from_bytes(e.to_bytes())
        assert e2.conf.peers == tuple(peers)
        assert e2.conf.old_peers == tuple(peers[:2])
        assert e2.is_config()

    def test_metadata(self):
        e = make_metadata_entry(1, 4, 99)
        assert LogEntry.from_bytes(e.to_bytes()).commit_index == 99


class TestRpcCodec:
    def _header(self):
        return RaftRpcHeader(RaftPeerId.value_of("a"), RaftPeerId.value_of("b"),
                             RaftGroupId.random_id(), 7)

    def test_vote_roundtrip(self):
        r = RequestVoteRequest(self._header(), 5, TermIndex(4, 10), pre_vote=True)
        r2 = decode_rpc(encode_rpc(r))
        assert r2 == r

    def test_append_roundtrip(self):
        entries = tuple(make_transaction_entry(2, i, ClientId.random_id(), i, b"x")
                        for i in range(3))
        r = AppendEntriesRequest(self._header(), 2, TermIndex(1, 4), entries, 3)
        r2 = decode_rpc(encode_rpc(r))
        assert r2.entries == entries
        assert r2.previous == TermIndex(1, 4)

    def test_append_reply_roundtrip(self):
        rep = AppendEntriesReply(self._header(), 2, AppendResult.INCONSISTENCY,
                                 5, 3, 4, is_heartbeat=True)
        assert decode_rpc(encode_rpc(rep)) == rep


class TestClientRequestCodec:
    def test_write_roundtrip(self):
        req = RaftClientRequest(ClientId.random_id(), RaftPeerId.value_of("s0"),
                                RaftGroupId.random_id(), 11,
                                Message.value_of("hello"))
        req2 = RaftClientRequest.from_bytes(req.to_bytes())
        assert req2 == req
        assert req2.is_write()

    def test_watch_roundtrip(self):
        req = RaftClientRequest(
            ClientId.random_id(), RaftPeerId.value_of("s0"),
            RaftGroupId.random_id(), 12,
            type=watch_request_type(100, ReplicationLevel.ALL_COMMITTED))
        req2 = RaftClientRequest.from_bytes(req.to_bytes())
        assert req2.type.watch_index == 100
        assert req2.type.watch_replication == ReplicationLevel.ALL_COMMITTED

    def test_reply_with_exception(self):
        req = RaftClientRequest(ClientId.random_id(), RaftPeerId.value_of("s0"),
                                RaftGroupId.random_id(), 1)
        leader = RaftPeer(RaftPeerId.value_of("s2"), "h:2")
        reply = RaftClientReply.failure_reply(
            req, NotLeaderException(suggested_leader=leader, peers=(leader,)))
        reply2 = RaftClientReply.from_bytes(reply.to_bytes())
        assert not reply2.success
        nle = reply2.get_not_leader_exception()
        assert nle is not None and nle.suggested_leader == leader


class TestExceptionWire:
    def test_not_replicated(self):
        e = NotReplicatedException(3, ReplicationLevel.MAJORITY_COMMITTED, 55)
        e2 = exception_from_wire(exception_to_wire(e))
        assert isinstance(e2, NotReplicatedException)
        assert e2.log_index == 55
        assert e2.replication == ReplicationLevel.MAJORITY_COMMITTED

    def test_unknown_type_degrades_to_base(self):
        from ratis_tpu.protocol.exceptions import RaftException
        e2 = exception_from_wire({"type": "Bogus", "msg": "m"})
        assert type(e2) is RaftException


class TestRetryPolicies:
    def test_limited(self):
        p = RetryPolicies.retry_up_to_maximum_count_with_fixed_sleep(3, "10ms")
        assert p.handle_attempt_failure(ClientRetryEvent(2)).should_retry
        assert not p.handle_attempt_failure(ClientRetryEvent(3)).should_retry

    def test_exponential_backoff_capped(self):
        p = ExponentialBackoffRetry(TimeDuration.millis(1), TimeDuration.millis(8))
        a = p.handle_attempt_failure(ClientRetryEvent(20))
        assert a.should_retry and a.sleep_time.to_ms() <= 8

    def test_multiple_linear(self):
        p = MultipleLinearRandomRetry.parse_comma_separated("1ms,2, 5ms,1")
        assert p.handle_attempt_failure(ClientRetryEvent(0)).should_retry
        assert p.handle_attempt_failure(ClientRetryEvent(2)).should_retry
        assert not p.handle_attempt_failure(ClientRetryEvent(3)).should_retry


class TestLifeCycle:
    def test_normal_path(self):
        lc = LifeCycle("x")
        lc.transition(LifeCycleState.STARTING)
        lc.transition(LifeCycleState.RUNNING)
        assert lc.get_current_state().is_running()
        assert lc.check_state_and_close(lambda: None)
        assert lc.get_current_state() == LifeCycleState.CLOSED
        assert not lc.check_state_and_close(lambda: None)

    def test_illegal_transition(self):
        lc = LifeCycle("x")
        with pytest.raises(IllegalLifeCycleTransition):
            lc.transition(LifeCycleState.RUNNING)

    def test_start_failure_goes_to_exception(self):
        lc = LifeCycle("x")
        with pytest.raises(RuntimeError, match="boom"):
            lc.start_and_transition(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        assert lc.get_current_state() == LifeCycleState.EXCEPTION


class TestSlidingWindow:
    def test_server_strict_ordering_under_concurrency(self):
        import asyncio
        from ratis_tpu.util.sliding_window import SlidingWindowServer

        async def main():
            done = []

            async def process(r):
                if r == 0:
                    await asyncio.sleep(0.02)
                done.append(r)

            w = SlidingWindowServer(process)
            t0 = asyncio.create_task(w.receive(0, True, 0))
            await asyncio.sleep(0.005)
            t1 = asyncio.create_task(w.receive(1, False, 1))
            await asyncio.gather(t0, t1)
            return done

        assert asyncio.run(main()) == [0, 1]

    def test_server_failover_drops_stale_pending(self):
        import asyncio
        from ratis_tpu.util.sliding_window import SlidingWindowServer

        async def main():
            done = []

            async def process(r):
                done.append(r)

            w = SlidingWindowServer(process)
            await w.receive(2, True, 2)
            await w.receive(5, False, 5)  # parked, waiting for 3..4
            await w.receive(7, True, 7)   # failover: new first
            return done, w.pending_count()

        done, pending = asyncio.run(main())
        assert done == [2, 7] and pending == 0

    def test_client_window(self):
        from ratis_tpu.util.sliding_window import SlidingWindowClient
        c = SlidingWindowClient()
        reqs = [c.submit_new_request(lambda seq: seq) for _ in range(3)]
        assert reqs == [0, 1, 2] and c.is_first(0)
        c.receive_reply(0)
        assert c.is_first(1) and c.size() == 2
        c.receive_reply(2)
        assert c.pending_requests() == [1]


class TestExceptionWireDefaults:
    def test_attr_bearing_exceptions_roundtrip_clean(self):
        from ratis_tpu.protocol.exceptions import (ChecksumException,
                                                   LeaderNotReadyException,
                                                   RaftRetryFailureException)
        e = exception_from_wire(exception_to_wire(LeaderNotReadyException("m1@g")))
        assert str(e) == "m1@g is in LEADER state but not ready yet"
        assert e.member_id is None
        e2 = exception_from_wire(exception_to_wire(
            RaftRetryFailureException(None, 5, "P")))
        assert str(e2) == "Failed None for 5 attempts with P"
        e3 = exception_from_wire(exception_to_wire(ChecksumException("bad", 9)))
        assert isinstance(e3, ChecksumException) and e3.position == -1


class TestLifeCycleReferenceGraph:
    def test_new_closes_directly(self):
        lc = LifeCycle("x")
        assert lc.check_state_and_close(lambda: None)
        assert lc.get_current_state() == LifeCycleState.CLOSED

    def test_starting_back_to_new_allowed(self):
        lc = LifeCycle("x")
        lc.transition(LifeCycleState.STARTING)
        lc.transition(LifeCycleState.NEW)  # reference-legal start-failure retry
        assert lc.get_current_state() == LifeCycleState.NEW

    def test_starting_to_paused_rejected(self):
        lc = LifeCycle("x")
        lc.transition(LifeCycleState.STARTING)
        with pytest.raises(IllegalLifeCycleTransition):
            lc.transition(LifeCycleState.PAUSED)


def test_parse_size_unknown_unit_is_value_error():
    with pytest.raises(ValueError, match="unknown size unit"):
        parse_size("64KiB")


def test_dataclass_constants_are_classvars():
    import dataclasses
    from ratis_tpu.util.timeduration import TimeDuration as TD
    assert [f.name for f in dataclasses.fields(TermIndex)] == ["term", "index"]
    assert [f.name for f in dataclasses.fields(Message)] == ["content"]
    assert [f.name for f in dataclasses.fields(TD)] == ["seconds"]
    assert TermIndex(1, 2) > TermIndex.INITIAL_VALUE
