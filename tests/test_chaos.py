"""Randomized fault injection under load, ported onto the chaos scenario
engine (ratis_tpu.chaos; reference analogs RaftExceptionBaseTest, the
kill/restart suites over simulated RPC, and the leader-election churn
tests).

The old in-test nemesis loop is now the ``randomized_nemesis`` SCENARIO:
a deterministic schedule derived from the seed, so a failing run is
replayable bit-for-bit (``python -m ratis_tpu.tools.chaos_replay``) and
every assertion carries the seed.  The old loop's kill arm also only
fired when ``len(cluster.servers) == 3`` — silently no-opping crash
coverage at every other cluster size; the scenario builder kills at any
size (asserted below).

Invariants after healing (the engine's standing SLOs):

1. every ACKED write is applied exactly once on every live replica,
2. all replicas applied the same sequence,
3. un-acked writes appear at most once,
4. re-election converges within the scenario bound.
"""

import asyncio

import pytest

from ratis_tpu.chaos.campaign import run_campaign
from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties
from ratis_tpu.chaos.scenario import run_scenario
from ratis_tpu.chaos.scenarios import build_scenario

NEMESIS_CFG = {"convergence_s": 30.0, "recovery_s": 60.0,
               "duration_s": 5.0, "writers": 4, "min_acked": 20}


async def _run_nemesis(cluster: ChaosCluster, seed: int,
                       duration_s: float = 5.0) -> None:
    scenario = build_scenario("randomized_nemesis", seed,
                              dict(NEMESIS_CFG, duration_s=duration_s))
    result = await run_scenario(cluster, scenario)
    assert result.passed, (
        f"[seed {seed}] nemesis scenario failed: {result.error}\n"
        f"journal: {result.journal}")
    assert result.acked > 20, (
        f"[seed {seed}] chaos run acked only {result.acked} writes")


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [11, 23])
def test_chaos_writes_survive_faults(seed):
    async def main():
        cluster = ChaosCluster(3, 1)
        await cluster.start()
        try:
            await _run_nemesis(cluster, seed)
        finally:
            await cluster.close()

    asyncio.run(main())


@pytest.mark.chaos
def test_chaos_batched_engine():
    """Same nemesis with the jitted batched engine on every tick."""

    async def main():
        p = chaos_properties(1, seed=7)
        p.set("raft.tpu.engine.scalar-fallback-threshold", "0")
        cluster = ChaosCluster(3, 1, properties=p, seed=7)
        await cluster.start()
        try:
            await _run_nemesis(cluster, seed=7, duration_s=4.0)
            for s in cluster.servers.values():
                assert s.engine.metrics["batched_dispatches"] > 0, \
                    "[seed 7] batched engine never dispatched"
        finally:
            await cluster.close()

    asyncio.run(main())


def test_nemesis_kills_at_every_cluster_size():
    """The old nemesis silently skipped its kill arm off 3 servers; the
    scenario builder must schedule kills for 5- and 7-server configs too
    (checked across a seed window — the arm fires with p=0.4/round)."""
    for servers in (3, 5, 7):
        kills = 0
        for seed in range(8):
            sc = build_scenario("randomized_nemesis", seed,
                                {"servers": servers, "duration_s": 6.0})
            kills += sum(1 for s in sc.steps if s.op == "kill")
            # every kill pairs with a restart (quorum is probed, never
            # destroyed) and targets a concrete server index
            assert sum(1 for s in sc.steps if s.op == "kill") == \
                sum(1 for s in sc.steps if s.op == "restart"), \
                f"[seed {seed}] unbalanced kill/restart at {servers} servers"
            for s in sc.steps:
                if s.op == "kill":
                    idx = int(s.target.split(":")[1])
                    assert 0 <= idx < servers, \
                        f"[seed {seed}] kill target {s.target} out of range"
        assert kills > 0, f"no kill steps across seeds at {servers} servers"


@pytest.mark.chaos
def test_shared_log_tail_loss_scenario():
    """Round-12 shared log plane: the interleaved-tail-loss scenario on a
    multi-group cluster running raft.tpu.log.shared — one chopped shard
    tail rewinds several groups at once; zero acked writes lost and the
    counter oracle stays exactly-once."""

    async def main(tmp: str):
        p = chaos_properties(8, seed=31)
        p.set("raft.tpu.log.shared", "1")
        cluster = ChaosCluster(3, 8, properties=p, sm="counter",
                               storage_root=tmp, seed=31)
        await cluster.start()
        try:
            cfg = {"servers": 3, "groups": 8, "writers": 4,
                   "active_groups": 8, "durable": True, "sm": "counter",
                   "convergence_s": 30.0, "recovery_s": 60.0,
                   "min_acked": 20}
            scenario = build_scenario("shared_log_tail_loss", 31, cfg)
            result = await run_scenario(cluster, scenario)
            assert result.passed, (
                f"[seed 31] shared tail-loss failed: {result.error}\n"
                f"journal: {result.journal}")
            assert result.acked > 20
        finally:
            await cluster.close()

    import tempfile
    with tempfile.TemporaryDirectory(prefix="ratis-chaos-sh-") as tmp:
        asyncio.run(main(tmp))


@pytest.mark.chaos
@pytest.mark.mesh
def test_chaos_campaign_subset_mesh():
    """PR-18 gate: a campaign subset with the MESH engine armed
    (raft.tpu.engine.mesh-devices=2 on the virtual CPU fleet) — faults
    bite the slice-routed packed-ack path, divisions pin to their crc32
    slice, and the exactly-once counter oracle must still hold."""

    async def main():
        p = chaos_properties(8, seed=19)
        p.set("raft.tpu.engine.mesh-devices", "2")
        p.set("raft.tpu.engine.scalar-fallback-threshold", "0")
        cluster = ChaosCluster(3, 8, properties=p, sm="counter", seed=19)
        await cluster.start()
        try:
            for s in cluster.servers.values():
                assert s.engine.mesh is not None
                assert s.engine.state.n_slices == 2
            cfg = {"servers": 3, "groups": 8, "writers": 4,
                   "active_groups": 8, "sm": "counter",
                   "convergence_s": 30.0, "recovery_s": 60.0,
                   "min_acked": 20}
            for name in ("partition_leader", "crash_restart_leader"):
                scenario = build_scenario(name, 19, cfg)
                result = await run_scenario(cluster, scenario)
                assert result.passed, (
                    f"[seed 19] mesh campaign {name} failed: "
                    f"{result.error}\njournal: {result.journal}")
            # the engines actually dispatched through the sliced kernel
            for s in cluster.servers.values():
                assert s.engine.metrics["fast_ticks"] > 0, \
                    "[seed 19] mesh engine never ran the fast path"
        finally:
            await cluster.close()

    asyncio.run(main())


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_campaign_long():
    """The long randomized campaign: every standing scenario plus the
    durable slow-disk fault, on one cluster, counter-oracle invariants —
    the full chaos gate at a mid-size multi-group shape."""

    async def main(tmp: str) -> dict:
        return await run_campaign(
            num_servers=3, num_groups=64, seed=23, sm="counter",
            storage_root=tmp, writers=4, active_groups=16,
            convergence_s=45.0, recovery_s=90.0,
            extra_config={"min_acked": 20, "duration_s": 6.0})

    import tempfile
    with tempfile.TemporaryDirectory(prefix="ratis-chaos-") as tmp:
        out = asyncio.run(main(tmp))
    failed = {n: e for n, e in out["scenarios"].items()
              if not e["passed"]}
    assert not failed, (
        f"[seed 23] campaign scenarios failed: "
        f"{ {n: e.get('error') for n, e in failed.items()} }")
    assert out["passed"] == out["total"] >= 7
    assert out["fault_events"] > 0 and out["recovered_events"] > 0
