"""Randomized fault injection under load (the reference's closest analogs:
RaftExceptionBaseTest, TestRaftWithSimulatedRpc kill/restart suites, and the
leader-election churn tests — folded into one linearizability-style check).

Writers drive uniquely-tagged appends through the full client path while the
cluster suffers random partitions, leader kills, and restarts.  After
healing, the invariants are:

1. every ACKED write is applied exactly once on every live replica
   (retry-cache dedupe across failover means client retries must not
   double-apply),
2. all replicas applied the same sequence (state-machine determinism),
3. un-acked writes appear at most once (a timed-out attempt may still have
   committed — that's Raft; it must not appear twice).
"""

import asyncio
import random

import pytest

from minicluster import MiniCluster, fast_properties
from statemachines import RecordingStateMachine


async def _chaos(cluster: MiniCluster, seed: int, duration_s: float,
                 n_writers: int) -> None:
    rng = random.Random(seed)
    acked: list[bytes] = []
    stop = asyncio.Event()

    async def writer(wid: int):
        i = 0
        async with cluster.new_client() as client:
            while not stop.is_set():
                payload = f"w{wid}-{i}".encode()
                i += 1
                try:
                    reply = await asyncio.wait_for(
                        client.io().send(payload), 8.0)
                    if reply.success:
                        acked.append(payload)
                except Exception:
                    pass  # un-acked: may or may not have committed
                await asyncio.sleep(rng.uniform(0, 0.02))

    async def nemesis():
        end = asyncio.get_event_loop().time() + duration_s
        while asyncio.get_event_loop().time() < end:
            await asyncio.sleep(rng.uniform(0.3, 0.8))
            ids = list(cluster.servers)
            if not ids:
                continue
            fault = rng.random()
            if fault < 0.4 and len(cluster.servers) == 3:
                # kill any one server, restart it shortly after
                victim = rng.choice(ids)
                await cluster.kill_server(victim)
                await asyncio.sleep(rng.uniform(0.3, 0.9))
                await cluster.restart_server(victim)
            elif fault < 0.8:
                # partition one node away, then heal
                victim = rng.choice(ids)
                others = [x for x in ids if x != victim]
                cluster.network.partition([victim], others)
                await asyncio.sleep(rng.uniform(0.3, 0.9))
                cluster.network.unblock_all()
            else:
                # transient asymmetric blackhole
                a, b = rng.sample(ids, 2)
                cluster.network.block(a, b)
                await asyncio.sleep(rng.uniform(0.2, 0.5))
                cluster.network.unblock_all()

    writers = [asyncio.create_task(writer(w)) for w in range(n_writers)]
    await nemesis()
    stop.set()
    await asyncio.gather(*writers, return_exceptions=True)
    cluster.network.unblock_all()

    # heal: let replication and apply quiesce (generous: under the forced-
    # batched CI mode a first-tick jit compile can stall recovery)
    leader = await cluster.wait_for_leader(timeout=40.0)
    last = leader.state.log.get_last_committed_index()
    await cluster.wait_applied(last, timeout=45.0)

    seqs = {str(d.member_id): list(d.state_machine.applied)
            for d in cluster.divisions()}
    # 2) replica agreement
    first = next(iter(seqs.values()))
    for member, seq in seqs.items():
        assert seq == first, (
            f"replica divergence at {member}: {len(seq)} vs {len(first)}")
    counts = {p: first.count(p) for p in set(first)}
    # 3) nothing applied twice
    dupes = {p: c for p, c in counts.items() if c > 1}
    assert not dupes, f"duplicated applies: {dupes}"
    # 1) every acked write applied exactly once
    missing = [p for p in acked if counts.get(p, 0) != 1]
    assert not missing, f"lost acked writes: {missing[:10]}"
    assert len(acked) > 20, f"chaos run acked only {len(acked)} writes"


@pytest.mark.parametrize("seed", [11, 23])
def test_chaos_writes_survive_faults(seed):
    async def main():
        cluster = MiniCluster(3, properties=fast_properties(),
                              sm_factory=RecordingStateMachine)
        await cluster.start()
        try:
            await cluster.wait_for_leader()
            await _chaos(cluster, seed=seed, duration_s=6.0, n_writers=4)
        finally:
            cluster.network.unblock_all()
            await cluster.close()

    asyncio.run(main())


def test_chaos_batched_engine(monkeypatch):
    """Same chaos with the jitted batched engine on every tick."""

    async def main():
        from minicluster import batched_properties
        cluster = MiniCluster(3, properties=batched_properties(),
                              sm_factory=RecordingStateMachine)
        await cluster.start()
        try:
            await cluster.wait_for_leader()
            await _chaos(cluster, seed=7, duration_s=5.0, n_writers=3)
            for s in cluster.servers.values():
                assert s.engine.metrics["batched_dispatches"] > 0
        finally:
            cluster.network.unblock_all()
            await cluster.close()

    asyncio.run(main())
