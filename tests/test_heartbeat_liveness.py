"""Heartbeat due-ness keys on CONFIRMED follower contact (round-5
deposition-storm fix): a queued/backed-off data send must not suppress
the compact heartbeat while the follower hears silence, and hibernation
wake's force-due marker must emit on the next sweep.  Exercised against
a REAL leader's appender objects (full wiring, no mocks)."""

import asyncio
import time

from minicluster import MiniCluster, batched_properties, run_with_new_cluster


async def _leader_appender(cluster: MiniCluster):
    leader = await cluster.wait_for_leader()
    for _ in range(200):
        if leader.leader_ctx and leader.leader_ctx.appenders:
            return leader, next(iter(leader.leader_ctx.appenders.values()))
        await asyncio.sleep(0.02)
    raise TimeoutError("no appenders")


def test_heartbeat_emits_despite_backoff_and_queued_sends():
    async def body(cluster: MiniCluster):
        leader, a = await _leader_appender(cluster)
        assert (await cluster.send_write()).success
        now = time.monotonic()
        hb = a.heartbeat_interval_s
        # follower silent past the interval, data path recently QUEUED a
        # send and is in error backoff — the exact shape that deposed
        # thousands of healthy leaders before the fix
        a.follower.last_rpc_response_s = now - 10 * hb
        a._last_send_s = now - 0.5 * hb   # recent queue-time stamp
        a._backoff_until = now + 10 * hb  # send-error backoff engaged
        item = a.heartbeat_item(now)
        assert item is not None, \
            "backoff/queued-send suppressed the heartbeat (deposition bug)"

    run_with_new_cluster(3, body, properties=batched_properties())


def test_heartbeat_suppressed_while_follower_demonstrably_fresh():
    async def body(cluster: MiniCluster):
        leader, a = await _leader_appender(cluster)
        now = time.monotonic()
        hb = a.heartbeat_interval_s
        a.follower.last_rpc_response_s = now - 0.1 * hb  # fresh reply
        a._last_send_s = now - 2 * hb
        assert a.heartbeat_item(now) is None

    run_with_new_cluster(3, body, properties=batched_properties())


def test_heartbeat_rate_cap_two_attempts_per_interval():
    async def body(cluster: MiniCluster):
        leader, a = await _leader_appender(cluster)
        now = time.monotonic()
        hb = a.heartbeat_interval_s
        # unresponsive follower, but we JUST emitted: capped
        a.follower.last_rpc_response_s = now - 10 * hb
        a._last_send_s = now - 0.2 * hb
        assert a.heartbeat_item(now) is None
        # past the half-interval cap: due again (second attempt)
        a._last_send_s = now - 0.5 * hb
        assert a.heartbeat_item(now) is not None

    run_with_new_cluster(3, body, properties=batched_properties())


def test_wake_force_due_marker_emits_immediately():
    async def body(cluster: MiniCluster):
        leader, a = await _leader_appender(cluster)
        now = time.monotonic()
        # hibernation wake sets _last_send_s = 0.0 ("next sweep
        # heartbeats immediately") and refreshes the reply clock for
        # slowness bookkeeping — the marker must override freshness
        a.follower.last_rpc_response_s = now
        a._last_send_s = 0.0
        assert a.heartbeat_item(now) is not None

    run_with_new_cluster(3, body, properties=batched_properties())


def test_stream_dial_gate_paces_per_address():
    from ratis_tpu.transport.grpc import _StreamDialGate
    g = _StreamDialGate()
    assert g.may_dial("a:1")
    assert not g.may_dial("a:1")  # within the pacing window
    assert g.may_dial("b:2")      # other addresses unaffected
    g._last["a:1"] = time.monotonic() - _StreamDialGate.WINDOW_S - 0.01
    assert g.may_dial("a:1")      # window elapsed
