"""Event-loop pause monitor (reference JvmPauseMonitor.java:38,145 wired at
RaftServerProxy.java:243): a stalled loop is detected and leaderships are
abdicated instead of lingering heartbeat-less."""

import asyncio
import time

from minicluster import MiniCluster, fast_properties, run_with_new_cluster


def test_pause_detected_and_leader_steps_down():
    async def body(cluster: MiniCluster):
        from ratis_tpu.server.pause_monitor import PauseMonitor
        leader = await cluster.wait_for_leader()
        assert (await cluster.send_write()).success
        srv = cluster.servers[leader.member_id.peer_id]
        assert srv.pause_monitor is not None
        # Give the monitor a lower threshold than the engine's staleness
        # sweep so the abdication deterministically comes from the monitor
        # (in production either path may win the race — same outcome).
        await srv.pause_monitor.close()
        srv.pause_monitor = PauseMonitor(srv, stepdown_s=0.7)
        srv.pause_monitor.start()
        srv.engine.leadership_timeout_ms = 60_000
        await asyncio.sleep(0.05)
        # Stall the entire event loop the way a synchronous compile or
        # GIL-holding native call would.
        time.sleep(1.2)
        # Let the monitor run its check: poll instead of a fixed sleep —
        # under full-suite load the resumed loop can take a while to drain
        # its ready-callback backlog before the monitor task runs.
        deadline = asyncio.get_event_loop().time() + 5.0
        while asyncio.get_event_loop().time() < deadline:
            if srv.pause_monitor.stepdown_count >= 1:
                break
            await asyncio.sleep(0.05)
        assert srv.pause_monitor.pause_count > 0
        # stepdown_count >= 1 proves the abdication happened; the division
        # may legitimately win re-election immediately afterwards, so do
        # NOT assert on is_leader() here.
        assert srv.pause_monitor.stepdown_count >= 1
        # detections land in the server registry, not just the log:
        # numPauses counter + longestPauseMs gauge (and the scrape
        # renders them as ratis_server_numPauses_total / longestPauseMs)
        snap = srv.pause_monitor.registry.snapshot()
        assert snap["numPauses"] == srv.pause_monitor.pause_count
        assert snap["numPauses"] >= 1
        assert snap["longestPauseMs"] >= 500.0  # the 1.2s stall, in ms
        assert snap["numStepDowns"] == srv.pause_monitor.stepdown_count
        # the cluster recovers: a (possibly new) leader serves writes
        await cluster.wait_for_leader()
        assert (await cluster.send_write()).success

    run_with_new_cluster(3, body)


def test_short_pauses_do_not_abdicate():
    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        time.sleep(0.6)  # above warn, below the 1s step-down floor
        await asyncio.sleep(0.2)
        lead_monitor = cluster.servers[
            leader.member_id.peer_id].pause_monitor
        assert lead_monitor.stepdown_count == 0

    run_with_new_cluster(3, body)
