"""Shared multi-group log plane tests.

Coverage for ratis_tpu/server/log/shared.py: multi-group interleaving with
one fsync per drain sweep, tombstone-based rewind (shared bytes are never
rewritten), exact purge + sealed-segment compaction, the one-pass boot
scan (torn tails, tombstones, purge markers), and randomized equivalence
against the per-group segmented store on the RaftLog observables.
"""

import asyncio
import os
import random

import pytest

from ratis_tpu.protocol.exceptions import ChecksumException
from ratis_tpu.protocol.ids import ClientId
from ratis_tpu.protocol.logentry import make_transaction_entry
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.server.log.segmented import (MAGIC, LogWorker,
                                            SegmentedRaftLog, read_records)
from ratis_tpu.server.log.shared import (SharedGroupLog, SharedLogStore,
                                         shard_dir)
from tests.minicluster import MiniCluster, fast_properties

GID_A = b"A" * 16
GID_B = b"B" * 16
GID_C = b"C" * 16


def entry(term, index, size=8):
    return make_transaction_entry(term, index, ClientId.random_id(), index,
                                  b"x" * size)


def run(coro):
    return asyncio.run(coro)


def make_store(path, wname, **kw):
    kw.setdefault("name", f"store-{wname}")
    return SharedLogStore(path, LogWorker(wname), **kw)


class TestSharedStoreBasics:
    def test_multi_group_append_close_reopen(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "w1")
            logs = [SharedGroupLog(f"g{i}", gid, store)
                    for i, gid in enumerate((GID_A, GID_B, GID_C))]
            for lg in logs:
                await lg.open()
            for i in range(20):
                for t, lg in enumerate(logs):
                    await lg.append_entry(entry(t + 1, i))
            for lg in logs:
                assert lg.flush_index == 19
                await lg.close()

            store2 = make_store(tmp_path, "w2")
            logs2 = [SharedGroupLog(f"g{i}", gid, store2)
                     for i, gid in enumerate((GID_A, GID_B, GID_C))]
            for t, lg in enumerate(logs2):
                await lg.open()
                assert lg.next_index == 20
                assert lg.flush_index == 19
                assert lg.get(7).term == t + 1
                assert lg.get_term_index(19) == TermIndex(t + 1, 19)
            for lg in logs2:
                await lg.close()

        run(body())

    def test_one_fsync_per_sweep(self, tmp_path):
        """The point of the shared plane: a burst of appends across many
        groups costs one fsync per worker drain, not one per group."""

        async def body():
            store = make_store(tmp_path, "wf")
            logs = [SharedGroupLog(f"g{i}", bytes([i]) * 16, store)
                    for i in range(16)]
            for lg in logs:
                await lg.open()
            for rnd in range(5):
                waits = [lg.append_entry(entry(1, rnd), wait_flush=True)
                         for lg in logs]
                await asyncio.gather(*waits)
            w = store.worker
            syncs = w.registry_metrics.sync_count.count
            batches = w.metrics["batched"]
            writes = w.metrics["writes"]
            assert writes == 16 * 5
            assert syncs == batches  # exactly one file fsynced per drain
            assert syncs <= 10  # gather batches whole sweeps together
            for lg in logs:
                await lg.close()

        run(body())

    def test_segment_roll_and_recovery(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "wr", segment_size_max=512)
            lg = SharedGroupLog("g", GID_A, store)
            await lg.open()
            for i in range(40):
                await lg.append_entry(entry(1, i, size=32))
            await lg.close()
            names = sorted(p.name for p in tmp_path.iterdir())
            sealed = [n for n in names if n.startswith("shared_")
                      and "inprogress" not in n]
            assert len(sealed) >= 2, names

            store2 = make_store(tmp_path, "wr2")
            lg2 = SharedGroupLog("g", GID_A, store2)
            await lg2.open()
            assert lg2.next_index == 40
            assert all(lg2.get(i) is not None for i in range(40))
            await lg2.close()

        run(body())

    def test_rewind_is_logical_shared_bytes_never_rewritten(self, tmp_path):
        """Follower rewind appends a tombstone; the interleaved file only
        grows, so other groups' records are never rewritten."""

        async def body():
            store = make_store(tmp_path, "wt")
            la = SharedGroupLog("ga", GID_A, store)
            lb = SharedGroupLog("gb", GID_B, store)
            await la.open()
            await lb.open()
            for i in range(10):
                await la.append_entry(entry(1, i))
                await lb.append_entry(entry(1, i))
            open_seg = next(p for p in tmp_path.iterdir()
                            if p.name.startswith("shared_inprogress_"))
            size_before = open_seg.stat().st_size
            await la.truncate(4)
            assert open_seg.stat().st_size > size_before  # grew, not shrank
            assert la.next_index == 4
            for i in range(4, 8):
                await la.append_entry(entry(2, i))
            # B untouched by A's rewind
            assert lb.next_index == 10 and lb.get(9).term == 1
            await la.close()
            await lb.close()

            store2 = make_store(tmp_path, "wt2")
            la2 = SharedGroupLog("ga", GID_A, store2)
            lb2 = SharedGroupLog("gb", GID_B, store2)
            await la2.open()
            await lb2.open()
            assert la2.next_index == 8
            assert la2.get(3).term == 1 and la2.get(5).term == 2
            assert lb2.next_index == 10
            await la2.close()
            await lb2.close()

        run(body())

    def test_torn_final_record_truncated_on_boot_scan(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "wc")
            la = SharedGroupLog("ga", GID_A, store)
            lb = SharedGroupLog("gb", GID_B, store)
            await la.open()
            await lb.open()
            for i in range(5):
                await la.append_entry(entry(1, i))
                await lb.append_entry(entry(1, i))
            await la.append_entry(entry(1, 5))  # the record we will tear
            await la.close()
            await lb.close()
            open_seg = next(p for p in tmp_path.iterdir()
                            if p.name.startswith("shared_inprogress_"))
            with open(open_seg, "r+b") as f:
                f.truncate(open_seg.stat().st_size - 3)  # torn mid-record

            store2 = make_store(tmp_path, "wc2")
            la2 = SharedGroupLog("ga", GID_A, store2)
            lb2 = SharedGroupLog("gb", GID_B, store2)
            await la2.open()
            await lb2.open()
            assert la2.next_index == 5  # torn tail dropped for its owner...
            assert lb2.next_index == 5  # ...other groups fully intact
            await la2.append_entry(entry(1, 5))
            assert la2.next_index == 6
            await la2.close()
            await lb2.close()

        run(body())

    def test_corrupt_sealed_segment_raises(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "ws", segment_size_max=256)
            lg = SharedGroupLog("g", GID_A, store)
            await lg.open()
            for i in range(30):
                await lg.append_entry(entry(1, i, size=32))
            await lg.close()
            sealed = sorted(p for p in tmp_path.iterdir()
                            if p.name.startswith("shared_")
                            and "inprogress" not in p.name)[0]
            with open(sealed, "r+b") as f:
                f.truncate(sealed.stat().st_size - 3)

            store2 = make_store(tmp_path, "ws2")
            lg2 = SharedGroupLog("g", GID_A, store2)
            with pytest.raises(ChecksumException):
                await lg2.open()

        run(body())

    def test_snapshot_boundary_round_trip(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "wb")
            lg = SharedGroupLog("g", GID_A, store)
            await lg.open()
            lg.set_snapshot_boundary(TermIndex(2, 100))
            assert lg.next_index == 101
            assert lg.start_index == 101
            assert lg.get_last_entry_term_index() == TermIndex(2, 100)
            await lg.append_entry(entry(2, 101))
            await lg.close()

            store2 = make_store(tmp_path, "wb2")
            lg2 = SharedGroupLog("g", GID_A, store2)
            await lg2.open()
            assert lg2.start_index == 101
            assert lg2.get(101) is not None
            await lg2.close()

        run(body())

    def test_eviction_reads_through_file(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "we")
            lg = SharedGroupLog("g", GID_A, store)
            await lg.open()
            for i in range(30):
                await lg.append_entry(entry(1, i, size=64))
            n = lg.evict_cache(29)
            assert n == 30
            misses0 = lg.metrics.cache_miss_count.count
            for i in range(30):
                e = lg.get(i)
                assert e is not None and e.index == i
            assert lg.metrics.cache_miss_count.count == misses0 + 30
            await lg.close()

        run(body())


class TestCompaction:
    def test_purge_triggers_compaction_and_reclaims(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "wp", segment_size_max=2048,
                               compaction_dead_ratio=0.3)
            la = SharedGroupLog("ga", GID_A, store)
            lb = SharedGroupLog("gb", GID_B, store)
            await la.open()
            await lb.open()
            for i in range(60):
                await la.append_entry(entry(1, i, size=64))
                await lb.append_entry(entry(1, i, size=64))
            sealed_before = dict(store._sizes)
            assert sealed_before  # several sealed segments
            await la.purge(49)
            assert la.start_index == 50
            for _ in range(50):
                if store._compact_task is None or store._compact_task.done():
                    break
                await asyncio.sleep(0.02)
            if store._compact_task is not None:
                await store._compact_task
            reclaimed = store.metrics.compaction_reclaimed.count
            assert reclaimed > 0
            # survivors still served, from compacted files included
            la.evict_cache(60)
            lb.evict_cache(60)
            assert all(la.get(i) is not None for i in range(50, 60))
            assert all(lb.get(i) is not None for i in range(60))
            await la.close()
            await lb.close()

            # and the rewritten segment sequence recovers cleanly
            store2 = make_store(tmp_path, "wp2")
            la2 = SharedGroupLog("ga", GID_A, store2)
            lb2 = SharedGroupLog("gb", GID_B, store2)
            await la2.open()
            await lb2.open()
            assert la2.start_index == 50 and la2.next_index == 60
            assert lb2.start_index == 0 and lb2.next_index == 60
            assert lb2.get(5).index == 5
            await la2.close()
            await lb2.close()

        run(body())

    def test_compaction_under_concurrent_appends(self, tmp_path):
        async def body():
            store = make_store(tmp_path, "wcc", segment_size_max=1024,
                               compaction_dead_ratio=0.3)
            la = SharedGroupLog("ga", GID_A, store)
            lb = SharedGroupLog("gb", GID_B, store)
            await la.open()
            await lb.open()
            for i in range(40):
                await la.append_entry(entry(1, i, size=48))
                await lb.append_entry(entry(1, i, size=48))

            stop = asyncio.Event()

            async def writer():
                i = 40
                while not stop.is_set():
                    await lb.append_entry(entry(1, i, size=48))
                    i += 1
                    await asyncio.sleep(0)
                return i

            task = asyncio.create_task(writer())
            await la.purge(35)  # makes sealed segments mostly dead
            for _ in range(100):
                if store._compact_task is not None \
                        and store._compact_task.done():
                    break
                await asyncio.sleep(0.01)
            stop.set()
            last_b = await task
            if store._compact_task is not None:
                await store._compact_task
            assert store.metrics.compaction_count.count >= 1
            assert all(la.get(i) is not None for i in range(36, 40))
            assert all(lb.get(i) is not None for i in range(last_b))
            await la.close()
            await lb.close()

            store2 = make_store(tmp_path, "wcc2")
            lb2 = SharedGroupLog("gb", GID_B, store2)
            la2 = SharedGroupLog("ga", GID_A, store2)
            await lb2.open()
            await la2.open()
            assert lb2.next_index == last_b
            assert la2.start_index == 36 and la2.next_index == 40
            await lb2.close()
            await la2.close()

        run(body())


class TestEquivalence:
    """Randomized append/rewind/purge sequences replayed through BOTH
    stores must expose identical RaftLog observables.  (Purge is the one
    legal divergence: the per-group store purges at segment granularity,
    the shared store purges exactly — so shared's start_index may run
    ahead of segmented's and reads compare only above the higher.)"""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_randomized_observable_equivalence(self, tmp_path, seed):
        async def body():
            rng = random.Random(seed)
            store = make_store(tmp_path / "shared", f"weq{seed}",
                               segment_size_max=1024)
            pairs = []
            for i, gid in enumerate((GID_A, GID_B)):
                seg = SegmentedRaftLog(
                    f"seg{i}", tmp_path / f"pg{i}",
                    worker=LogWorker(f"weqpg{seed}{i}"), segment_size_max=1024)
                sh = SharedGroupLog(f"sh{i}", gid, store)
                await seg.open()
                await sh.open()
                pairs.append((seg, sh))

            term = 1
            for step in range(120):
                seg, sh = pairs[rng.randrange(len(pairs))]
                op = rng.random()
                nxt = sh.next_index
                if op < 0.70 or nxt == 0:
                    e = entry(term, nxt, size=rng.choice((8, 40, 120)))
                    await seg.append_entry(e, wait_flush=True)
                    await sh.append_entry(e, wait_flush=True)
                elif op < 0.85:
                    term += 1
                    cut = rng.randrange(max(sh.start_index, 1), nxt + 1)
                    if cut < nxt:
                        await seg.truncate(cut)
                        await sh.truncate(cut)
                elif nxt > sh.start_index:
                    cut = rng.randrange(sh.start_index, nxt)
                    await seg.purge(cut)
                    await sh.purge(cut)
                assert sh.next_index == seg.next_index
                assert sh.flush_index == seg.flush_index

            def check(seg, sh):
                assert sh.next_index == seg.next_index
                assert sh.flush_index == seg.flush_index
                assert sh.start_index >= seg.start_index
                lo = max(sh.start_index, seg.start_index)
                for i in range(lo, sh.next_index):
                    es, eh = seg.get(i), sh.get(i)
                    assert es is not None and eh is not None, i
                    assert es.term == eh.term and es.index == eh.index
                    assert seg.get_term_index(i) == sh.get_term_index(i)
                tis, tih = (seg.get_last_entry_term_index(),
                            sh.get_last_entry_term_index())
                assert (tis is None) == (tih is None)
                if tis is not None:
                    assert tis == tih

            for seg, sh in pairs:
                check(seg, sh)
                await seg.close()
                await sh.close()

            # both recover to the same observables
            store2 = make_store(tmp_path / "shared", f"weq{seed}b",
                               segment_size_max=1024)
            for i, gid in enumerate((GID_A, GID_B)):
                seg = SegmentedRaftLog(
                    f"seg{i}", tmp_path / f"pg{i}",
                    worker=LogWorker(f"weqpg{seed}{i}b"),
                    segment_size_max=1024)
                sh = SharedGroupLog(f"sh{i}", gid, store2)
                await seg.open()
                await sh.open()
                check(seg, sh)
                await seg.close()
                await sh.close()

        run(body())


class TestSharedDurableCluster:
    def _props(self):
        from ratis_tpu.conf import RaftServerConfigKeys
        p = fast_properties()
        RaftServerConfigKeys.Log.set_use_memory(p, False)
        RaftServerConfigKeys.TpuLog.set_shared(p, True)
        return p

    def test_full_cluster_restart_preserves_state(self, tmp_path):
        async def body():
            cluster = MiniCluster(3, properties=self._props(),
                                  storage_root=str(tmp_path))
            await cluster.start()
            try:
                await cluster.wait_for_leader()
                for _ in range(5):
                    assert (await cluster.send_write()).success
                # the interleaved store is in use, per-shard under the root
                # (the server roots storage at <dir>/<peer_id>, and the
                # cluster's dir is already <tmp>/<peer_id>)
                some_root = next(iter(cluster.servers))
                assert shard_dir(
                    f"{tmp_path}/{some_root}/{some_root}", 0).exists()
                for pid in list(cluster.servers):
                    await cluster.kill_server(pid)
                for pid in list(cluster._stopped):
                    await cluster.restart_server(pid)
                await cluster.wait_for_leader()
                reply = await cluster.send_read()
                assert reply.message.content == b"5"
                assert (await cluster.send_write()).message.content == b"6"
            finally:
                await cluster.close()

        run(body())

    def test_follower_crash_recovers_from_shared_scan(self, tmp_path):
        async def body():
            cluster = MiniCluster(3, properties=self._props(),
                                  storage_root=str(tmp_path))
            await cluster.start()
            try:
                await cluster.wait_for_leader()
                follower = next(d for d in cluster.divisions()
                                if not d.is_leader())
                fid = follower.member_id.peer_id
                await cluster.kill_server(fid)
                for _ in range(10):
                    assert (await cluster.send_write()).success
                await cluster.restart_server(fid)
                new_div = cluster.servers[fid].divisions[
                    cluster.group.group_id]
                last = (await cluster.wait_for_leader()).state.log \
                    .get_last_committed_index()
                await cluster.wait_applied(last, divisions=[new_div],
                                           timeout=20.0)
                assert new_div.state_machine.counter == 10
            finally:
                await cluster.close()

        run(body())

    def test_unset_key_keeps_per_group_layout(self, tmp_path):
        """raft.tpu.log.shared unset → per-group segment files, no
        _sharedlog directory anywhere (bit-for-bit today's store)."""

        async def body():
            cluster = MiniCluster(3, storage_root=str(tmp_path))
            await cluster.start()
            try:
                await cluster.wait_for_leader()
                for _ in range(3):
                    assert (await cluster.send_write()).success
                assert not list(tmp_path.glob("*/*/_sharedlog"))
                gid = cluster.group.group_id
                per_group = list(
                    tmp_path.glob(f"*/*/{gid.uuid}/current/log_*"))
                assert per_group
            finally:
                await cluster.close()

        run(body())

        async def body_shared():
            cluster = MiniCluster(3, properties=self._props(),
                                  storage_root=str(tmp_path / "sh"))
            await cluster.start()
            try:
                await cluster.wait_for_leader()
                for _ in range(3):
                    assert (await cluster.send_write()).success
                assert list(
                    (tmp_path / "sh").glob("*/*/_sharedlog/shard-*"))
                gid = cluster.group.group_id
                assert not list((tmp_path / "sh")
                                .glob(f"*/*/{gid.uuid}/current/log_*"))
            finally:
                await cluster.close()

        run(body_shared())
