"""Server-level heap discipline (raft.tpu.gc.*, ratis_tpu.util.gcdiscipline):
tuned thresholds at start, one deliberate collect+freeze once the group set
settles, restoration on close.  The production answer to the measured 52s
gen-2 pause over a 10k-group heap (the bench previously hacked this
per-run; reference analog for the failure class: JvmPauseMonitor.java:38)."""

import asyncio
import gc
import time

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId
from ratis_tpu.util import gcdiscipline


def _gc_properties(freeze_idle: str = "300ms"):
    p = fast_properties()
    p.set(RaftServerConfigKeys.Gc.DISCIPLINE_KEY, "true")
    p.set(RaftServerConfigKeys.Gc.FREEZE_IDLE_KEY, freeze_idle)
    return p


def test_janitor_seals_after_group_burst_and_restores_on_close():
    saved = gc.get_threshold()
    frozen_before = gc.get_freeze_count()

    async def body(cluster: MiniCluster):
        # discipline thresholds are live while the server runs
        assert gc.get_threshold() == (700, 1000, 1000)
        # a burst of group adds, then idle: the janitor must seal
        server = next(iter(cluster.servers.values()))
        for _ in range(32):
            g = RaftGroup.value_of(RaftGroupId.random_id(),
                                   cluster.group.peers)
            await asyncio.gather(*(s.group_add(g)
                                   for s in cluster.servers.values()))
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if gc.get_freeze_count() > frozen_before:
                break
            await asyncio.sleep(0.05)
        assert gc.get_freeze_count() > frozen_before, \
            "janitor never sealed the heap after the group burst"
        # the sealed fleet is out of the collector: a forced full
        # collection now walks only the post-seal frontier, and must come
        # in far under the pause-monitor warn threshold that a whole-heap
        # pass at scale would blow
        t0 = time.monotonic()
        gc.collect()
        assert time.monotonic() - t0 < 0.5
        # the imperative knob exists for harnesses that cannot wait idle
        assert server.seal_heap() >= 0.0

    try:
        run_with_new_cluster(3, body, properties=_gc_properties())
        # last disciplined server closed: thresholds restored
        assert gc.get_threshold() == saved
    finally:
        gc.set_threshold(*saved)
        gc.unfreeze()


def test_refreeze_interval_reseals_on_cadence():
    """Steady-state re-freeze (raft.tpu.gc.refreeze-interval): the janitor
    seals repeatedly on the cadence even with NO group mutations, moving
    load-accreted live objects out of the collector's walks."""
    saved = gc.get_threshold()
    frozen_before = gc.get_freeze_count()

    async def body(cluster: MiniCluster):
        start = gcdiscipline.seal_count
        deadline = asyncio.get_event_loop().time() + 8.0
        while asyncio.get_event_loop().time() < deadline:
            if gcdiscipline.seal_count >= start + 2:
                break  # REPEATED seals observed, not just the first
            await asyncio.sleep(0.1)
        assert gcdiscipline.seal_count >= start + 2, \
            "janitor did not keep re-sealing on the cadence"
        assert gc.get_freeze_count() > frozen_before

    p = _gc_properties(freeze_idle="0s")  # idle-seal OFF: cadence only
    p.set(RaftServerConfigKeys.Gc.REFREEZE_INTERVAL_KEY, "300ms")
    try:
        run_with_new_cluster(3, body, properties=p)
    finally:
        gc.set_threshold(*saved)
        gc.unfreeze()


def test_discipline_off_leaves_gc_alone():
    saved = gc.get_threshold()

    async def body(cluster: MiniCluster):
        assert gc.get_threshold() == saved
        for s in cluster.servers.values():
            assert s._gc_task is None

    run_with_new_cluster(3, body, properties=fast_properties())
