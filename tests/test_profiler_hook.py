"""XLA profiler hook (raft.tpu.engine.profile-dir, SURVEY §5 tracing):
the engine wraps its run in a jax.profiler trace with one named step per
tick, written for TensorBoard/xprof."""

import asyncio
import glob

from minicluster import MiniCluster, batched_properties, run_with_new_cluster
from ratis_tpu.conf.keys import RaftServerConfigKeys


def test_profile_dir_produces_xla_trace(tmp_path):
    trace_dir = str(tmp_path / "prof")

    async def body(cluster: MiniCluster):
        from ratis_tpu.engine.engine import QuorumEngine
        assert QuorumEngine._profiling_owner is not None, \
            "no engine took profiler ownership"
        assert (await cluster.send_write()).success
        await asyncio.sleep(0.2)  # a few ticks inside the trace

    p = batched_properties()
    p.set(RaftServerConfigKeys.Engine.PROFILE_DIR_KEY, trace_dir)
    run_with_new_cluster(3, body, properties=p)

    # stop_trace (at server close) materializes the xplane dump
    dumps = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    assert dumps, f"no xplane trace written under {trace_dir}"

    from ratis_tpu.engine.engine import QuorumEngine
    assert QuorumEngine._profiling_owner is None, "ownership not released"
