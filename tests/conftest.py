"""Test environment: force an 8-virtual-device CPU platform BEFORE jax import,
so multi-chip sharding paths are exercised without TPU hardware."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest


@pytest.fixture(autouse=True)
def _clear_injections():
    yield
    from ratis_tpu.util import injection
    injection.clear()
