"""Test environment: force an 8-virtual-device CPU platform.

The ambient environment registers a remote-TPU PJRT plugin ("axon") in every
interpreter via sitecustomize, and that plugin's backend-init dials a tunnel
(and claims the single real TPU) — unusable and unwanted for unit tests.
Because sitecustomize already imported jax, env vars like JAX_PLATFORMS were
snapshotted at interpreter start; the reliable switch is to (1) drop the axon
backend factory before first backend init and (2) set the platform through
jax.config.  XLA_FLAGS is still read at cpu-backend init, so the virtual
8-device fleet can be requested here.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "mp: spawns a real multi-process cluster (slower)")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")


@pytest.fixture(autouse=True)
def _clear_injections():
    yield
    from ratis_tpu.util import injection
    injection.clear()
