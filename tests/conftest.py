"""Test environment: force an 8-virtual-device CPU platform.

The ambient environment registers a remote-TPU PJRT plugin ("axon") in every
interpreter via sitecustomize, and that plugin's backend-init dials a tunnel
(and claims the single real TPU) — unusable and unwanted for unit tests.
Because sitecustomize already imported jax, env vars like JAX_PLATFORMS were
snapshotted at interpreter start; the reliable switch is to (1) drop the axon
backend factory before first backend init and (2) set the platform through
jax.config.  XLA_FLAGS is still read at cpu-backend init, so the virtual
8-device fleet can be requested here.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "mp: spawns a real multi-process cluster (slower)")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-scenario gate "
                   "(ratis_tpu.chaos); fast scenarios run in tier-1, the "
                   "long campaign also carries `slow`")
    config.addinivalue_line(
        "markers", "mesh: needs the multi-(virtual-)device fleet "
                   "(XLA_FLAGS --xla_force_host_platform_device_count=8, "
                   "set in-process above); tier-1 — mesh-vs-single-device "
                   "bit-identity is a correctness gate, not a perf rung")


def pytest_collection_modifyitems(config, items):
    """`mesh` tests assert their device fleet up front: if the in-process
    XLA flag was lost (stale interpreter, ambient override), fail loudly
    at the marked tests instead of skipping the bit-identity gate."""
    if not any(item.get_closest_marker("mesh") for item in items):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    assert "xla_force_host_platform_device_count" in flags, \
        "mesh marker requires the conftest-set XLA_FLAGS device fleet"


@pytest.fixture(autouse=True)
def _clear_injections():
    yield
    from ratis_tpu.chaos.link import link_faults
    from ratis_tpu.util import injection
    injection.clear()
    link_faults().heal_all()


# ------------------------------------------------------------ task hygiene
#
# The PeerSender/LogAppender inflight-task bookkeeping grows with the
# round-9 append windows: a leak there (a task created but never awaited,
# cancelled, or tracked through close()) would silently accumulate across
# a long-lived server.  Every test therefore asserts that cluster teardown
# left no lingering asyncio task behind: after ``asyncio.run`` returns,
# any task that is still pending on a CLOSED loop can never run again —
# a definite leak.  Tasks whose cancellation was at least REQUESTED
# (``cancel()`` called, loop gone before it could unwind) are tolerated:
# they were tracked and asked to die; the loop's death froze them.

_reported_leaks = None  # lazy WeakSet: a leak fails exactly one test


def _pending_leaked_tasks() -> list:
    import asyncio.tasks as _tasks
    global _reported_leaks
    if _reported_leaks is None:
        import weakref
        _reported_leaks = weakref.WeakSet()
    leaked = []
    for t in list(getattr(_tasks, "_all_tasks", ())):
        try:
            if t.done() or not t.get_loop().is_closed():
                continue
            if getattr(t, "_must_cancel", False):
                continue  # cancel() was requested; the loop died first
            if t in _reported_leaks:
                continue  # already failed an earlier test for this task
        except Exception:
            continue
        _reported_leaks.add(t)
        leaked.append(t)
    return leaked


@pytest.fixture(autouse=True)
def _no_lingering_tasks():
    yield
    leaked = _pending_leaked_tasks()
    if leaked:
        names = []
        for t in leaked:
            try:
                names.append(t.get_coro().__qualname__)
            except Exception:
                names.append(repr(t))
        pytest.fail(
            f"{len(leaked)} asyncio task(s) leaked past cluster teardown "
            f"(pending on a closed loop, never cancelled): {names}")
