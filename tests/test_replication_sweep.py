"""Round-8 replication-plane batching (raft.tpu.replication.*).

Covers the sweep discipline's three contracts: the batch-off configuration
(sweep=0) still serves the full write path through the legacy per-request
code, the batched configuration produces the same commits, and the
scheduling-hops-per-commit metric — the fan-out collapse's standing
artifact — drops at least 2x on the in-process 64-group sim rung.  The
hops assertion is deterministic by construction (counter arithmetic, no
timing): the legacy commit->reply chain counts exactly two scheduling
operations per committed ordered write (pending-future + ordered-window
resolutions), while the waterline fan-out counts at most one batch pass
per committed entry.
"""

import asyncio

import pytest


def _drive_ordered(cluster, writes_per_group: int, pipeline: int):
    """Drive every group with `pipeline` concurrent ordered writes per
    round through the real RaftClient OrderedApi (slider seqNums), so the
    legacy path exercises both hops of its reply chain."""
    from ratis_tpu.client import RaftClient

    async def one_group(g):
        client = (RaftClient.builder()
                  .set_raft_group(g)
                  .set_transport(cluster.factory.new_client_transport(
                      cluster.properties))
                  .set_properties(cluster.properties)
                  .build())
        try:
            io = client.io()
            for _ in range(writes_per_group):
                replies = await asyncio.gather(
                    *(io.send(b"INCREMENT") for _ in range(pipeline)))
                assert all(r.success for r in replies)
        finally:
            await client.close()

    return asyncio.gather(*(one_group(g) for g in cluster.groups))


async def _measured_rung(sweep: bool, groups: int = 64) -> dict:
    """One in-process sim rung (scalar engine: no jit warmup cost) with the
    replication sweep on/off; returns the measured hops-per-commit."""
    from ratis_tpu.metrics import hops as hops_mod
    from ratis_tpu.tools.bench_cluster import BenchCluster

    cluster = BenchCluster(
        groups, num_servers=3, batched=False, transport="sim",
        extra_props={
            "raft.tpu.replication.sweep": "1" if sweep else "0",
            "raft.tpu.replication.reply-fanout": "1" if sweep else "0",
        })
    await cluster.start()
    try:
        engines = [s.engine for s in cluster.servers]
        assert all(s.replication_sweep == sweep for s in cluster.servers)
        hops_mod.reset()
        commits0 = sum(e.metrics["commit_advances"] for e in engines)
        await _drive_ordered(cluster, writes_per_group=2, pipeline=4)
        commits = sum(e.metrics["commit_advances"]
                      for e in engines) - commits0
        assert commits >= groups * 2 * 4 * 0.9, "rung lost commits"
        snap = hops_mod.snapshot()
        return {
            "commits": commits,
            "hops": snap,
            "reply_hpc": hops_mod.reply_plane_hops() / max(1, commits),
        }
    finally:
        await cluster.close()


@pytest.mark.parametrize("sweep", [False, True])
def test_rung_completes_both_modes(sweep):
    """sweep=0 must reproduce a fully working per-request path; sweep=1
    must commit the identical workload."""
    out = asyncio.run(_measured_rung(sweep, groups=8))
    assert out["commits"] >= 8 * 2 * 4 * 0.9


def test_hops_per_commit_drops_2x_on_64group_sim_rung():
    """The acceptance bar: reply-plane scheduling hops per commit drop
    >= 2x with the sweep + fan-out collapse on the 64-group sim rung."""

    async def body():
        legacy = await _measured_rung(False)
        swept = await _measured_rung(True)
        return legacy, swept

    legacy, swept = asyncio.run(body())
    # legacy: pending-future + ordered-window task wakeups per commit;
    # batch passes must not appear (fan-out disabled)
    assert legacy["hops"]["reply_batch"] == 0
    assert legacy["reply_hpc"] >= 1.9, legacy
    # swept: the per-request wakeup chain is gone; deliveries run inside
    # synchronous waterline passes (reply_batch counts passes for batch-
    # size observability, not hops) and the sim transport needs no flush
    # arm, so the scheduled reply plane is (near) empty
    assert swept["hops"]["reply_future"] == 0, swept
    assert swept["hops"]["reply_window"] == 0, swept
    assert swept["hops"]["reply_batch"] > 0, swept
    assert swept["reply_hpc"] <= 0.5, swept
    assert legacy["reply_hpc"] >= 2 * max(swept["reply_hpc"], 0.25), \
        (legacy, swept)


def test_sweep_mode_has_no_standing_sender_tasks():
    """Sweep-mode PeerSenders are drained by scheduler passes, not by a
    per-sender flush-loop task (the per-appender wake->collect->schedule
    shape the sweep replaces); legacy senders keep the standing task."""
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def body(sweep: bool) -> list:
        cluster = BenchCluster(
            4, num_servers=3, batched=False, transport="sim",
            extra_props={"raft.tpu.replication.sweep":
                         "1" if sweep else "0"})
        await cluster.start()
        try:
            await _drive_ordered(cluster, writes_per_group=1, pipeline=2)
            senders = [s2 for srv in cluster.servers
                       for s2 in srv.replication._senders.values()]
            assert senders, "load produced no senders"
            return [s2._task for s2 in senders]
        finally:
            await cluster.close()

    assert all(t is None for t in asyncio.run(body(True)))
    assert all(t is not None for t in asyncio.run(body(False)))
