"""Round-8 replication-plane batching (raft.tpu.replication.*).

Covers the sweep discipline's three contracts: the batch-off configuration
(sweep=0) still serves the full write path through the legacy per-request
code, the batched configuration produces the same commits, and the
scheduling-hops-per-commit metric — the fan-out collapse's standing
artifact — drops at least 2x on the in-process 64-group sim rung.  The
hops assertion is deterministic by construction (counter arithmetic, no
timing): the legacy commit->reply chain counts exactly two scheduling
operations per committed ordered write (pending-future + ordered-window
resolutions), while the waterline fan-out counts at most one batch pass
per committed entry.
"""

import asyncio

import pytest


def _drive_ordered(cluster, writes_per_group: int, pipeline: int):
    """Drive every group with `pipeline` concurrent ordered writes per
    round through the real RaftClient OrderedApi (slider seqNums), so the
    legacy path exercises both hops of its reply chain."""
    from ratis_tpu.client import RaftClient

    async def one_group(g):
        client = (RaftClient.builder()
                  .set_raft_group(g)
                  .set_transport(cluster.factory.new_client_transport(
                      cluster.properties))
                  .set_properties(cluster.properties)
                  .build())
        try:
            io = client.io()
            for _ in range(writes_per_group):
                replies = await asyncio.gather(
                    *(io.send(b"INCREMENT") for _ in range(pipeline)))
                assert all(r.success for r in replies)
        finally:
            await client.close()

    return asyncio.gather(*(one_group(g) for g in cluster.groups))


async def _measured_rung(sweep: bool, groups: int = 64) -> dict:
    """One in-process sim rung (scalar engine: no jit warmup cost) with the
    replication sweep on/off; returns the measured hops-per-commit."""
    from ratis_tpu.metrics import hops as hops_mod
    from ratis_tpu.tools.bench_cluster import BenchCluster

    cluster = BenchCluster(
        groups, num_servers=3, batched=False, transport="sim",
        extra_props={
            "raft.tpu.replication.sweep": "1" if sweep else "0",
            "raft.tpu.replication.reply-fanout": "1" if sweep else "0",
        })
    await cluster.start()
    try:
        engines = [s.engine for s in cluster.servers]
        assert all(s.replication_sweep == sweep for s in cluster.servers)
        hops_mod.reset()
        commits0 = sum(e.metrics["commit_advances"] for e in engines)
        await _drive_ordered(cluster, writes_per_group=2, pipeline=4)
        commits = sum(e.metrics["commit_advances"]
                      for e in engines) - commits0
        assert commits >= groups * 2 * 4 * 0.9, "rung lost commits"
        snap = hops_mod.snapshot()
        return {
            "commits": commits,
            "hops": snap,
            "reply_hpc": hops_mod.reply_plane_hops() / max(1, commits),
        }
    finally:
        await cluster.close()


@pytest.mark.parametrize("sweep", [False, True])
def test_rung_completes_both_modes(sweep):
    """sweep=0 must reproduce a fully working per-request path; sweep=1
    must commit the identical workload."""
    out = asyncio.run(_measured_rung(sweep, groups=8))
    assert out["commits"] >= 8 * 2 * 4 * 0.9


def test_hops_per_commit_drops_2x_on_64group_sim_rung():
    """The acceptance bar: reply-plane scheduling hops per commit drop
    >= 2x with the sweep + fan-out collapse on the 64-group sim rung."""

    async def body():
        legacy = await _measured_rung(False)
        swept = await _measured_rung(True)
        return legacy, swept

    legacy, swept = asyncio.run(body())
    # legacy: pending-future + ordered-window task wakeups per commit;
    # batch passes must not appear (fan-out disabled)
    assert legacy["hops"]["reply_batch"] == 0
    assert legacy["reply_hpc"] >= 1.9, legacy
    # swept: the per-request wakeup chain is gone; deliveries run inside
    # synchronous waterline passes (reply_batch counts passes for batch-
    # size observability, not hops) and the sim transport needs no flush
    # arm, so the scheduled reply plane is (near) empty
    assert swept["hops"]["reply_future"] == 0, swept
    assert swept["hops"]["reply_window"] == 0, swept
    assert swept["hops"]["reply_batch"] > 0, swept
    assert swept["reply_hpc"] <= 0.5, swept
    assert legacy["reply_hpc"] >= 2 * max(swept["reply_hpc"], 0.25), \
        (legacy, swept)


def test_sweep_mode_has_no_standing_sender_tasks():
    """Sweep-mode PeerSenders are drained by scheduler passes, not by a
    per-sender flush-loop task (the per-appender wake->collect->schedule
    shape the sweep replaces); legacy senders keep the standing task."""
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def body(sweep: bool) -> list:
        cluster = BenchCluster(
            4, num_servers=3, batched=False, transport="sim",
            extra_props={"raft.tpu.replication.sweep":
                         "1" if sweep else "0"})
        await cluster.start()
        try:
            await _drive_ordered(cluster, writes_per_group=1, pipeline=2)
            senders = [s2 for srv in cluster.servers
                       for s2 in srv.replication._senders.values()]
            assert senders, "load produced no senders"
            return [s2._task for s2 in senders]
        finally:
            await cluster.close()

    assert all(t is None for t in asyncio.run(body(True)))
    assert all(t is not None for t in asyncio.run(body(False)))


# ------------------------- round 9: sequenced append windows -------------

def _install_chaos(network, *, drop_p: float = 0.0, dup_p: float = 0.0,
                   delay_p: float = 0.0, seed: int = 0) -> dict:
    """Wrap a SimulatedNetwork's server-RPC delivery with randomized
    reorder/drop/duplicate injection on SEQUENCED append frames only (the
    round-9 lane protocol's surface).  A random pre-delivery sleep bypasses
    the hub's per-link FIFO clock, so later frames genuinely overtake
    earlier ones."""
    import random

    from ratis_tpu.protocol.exceptions import TimeoutIOException
    from ratis_tpu.protocol.raftrpc import AppendEnvelope

    rng = random.Random(seed)
    orig = network.deliver_server_rpc
    stats = {"dropped": 0, "duplicated": 0, "delayed": 0, "frames": 0}

    async def chaotic(src, dst, msg):
        if isinstance(msg, AppendEnvelope) and msg.seq >= 0:
            stats["frames"] += 1
            r = rng.random()
            if r < drop_p:
                stats["dropped"] += 1
                raise TimeoutIOException("chaos: dropped lane frame")
            if r < drop_p + dup_p:
                stats["duplicated"] += 1
                reply = await orig(src, dst, msg)
                try:
                    await orig(src, dst, msg)  # duplicate delivery
                except Exception:
                    pass
                return reply
            if r < drop_p + dup_p + delay_p:
                stats["delayed"] += 1
                await asyncio.sleep(rng.uniform(0.0, 0.01))
        return await orig(src, dst, msg)

    network.deliver_server_rpc = chaotic
    return stats


async def _windowed_chaos_rung(depth: int, groups: int = 8,
                               writes: int = 3, pipeline: int = 4,
                               **chaos) -> dict:
    """Drive ordered writes through a sim cluster running the sequenced
    window protocol under injected frame chaos; returns counters plus the
    per-group final SM values (exactly-once evidence)."""
    from ratis_tpu.client import RaftClient
    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.tools.bench_cluster import BenchCluster

    regressions = []
    orig_regress = QuorumEngine.regress_match

    def counting_regress(self, slot, peer_slot, match_index):
        regressions.append((slot, peer_slot, match_index))
        return orig_regress(self, slot, peer_slot, match_index)

    QuorumEngine.regress_match = counting_regress
    # batched=False keeps the scalar engine (no jit warmup) but pins the
    # pre-sweep baseline paths — re-enable the sweep + coalescing the
    # window protocol rides on top of
    cluster = BenchCluster(
        groups, num_servers=3, batched=False, transport="sim",
        extra_props={
            "raft.tpu.replication.window-depth": str(depth),
            "raft.tpu.replication.sweep": "1",
            "raft.server.log.appender.coalescing.enabled": "true",
        })
    try:
        await cluster.start()
        stats = _install_chaos(cluster.network, **chaos)

        async def one_group(g):
            client = (RaftClient.builder()
                      .set_raft_group(g)
                      .set_transport(cluster.factory.new_client_transport(
                          cluster.properties))
                      .set_properties(cluster.properties)
                      .build())
            try:
                io = client.io()
                for _ in range(writes):
                    replies = await asyncio.gather(
                        *(io.send(b"INCREMENT") for _ in range(pipeline)))
                    assert all(r.success for r in replies), \
                        "lost ack under frame chaos"
                r = await io.send_read_only(b"GET")
                return int(r.message.content)
            finally:
                await client.close()

        values = await asyncio.gather(*(one_group(g)
                                        for g in cluster.groups))
        metrics = dict(cluster.servers[0].replication.metrics)
        lane_metrics = [dict(s.lane_metrics) for s in cluster.servers]
        return {"values": values, "stats": stats, "metrics": metrics,
                "lane_metrics": lane_metrics, "regressions": regressions}
    finally:
        QuorumEngine.regress_match = orig_regress
        await cluster.close()


def test_window_zero_loss_under_reorder_drop_duplicate():
    """Randomized reorder/drop/duplicate injection over the sim transport:
    every ack arrives, every group's state machine lands at EXACTLY
    writes*pipeline (no lost, duplicated, or reordered commit), and the
    INCONSISTENCY guard never regresses a match index (no volatile-log
    restart happened, so any regression would be a protocol bug)."""
    out = asyncio.run(_windowed_chaos_rung(
        4, drop_p=0.05, dup_p=0.05, delay_p=0.25, seed=7))
    assert out["values"] == [3 * 4] * 8, out["values"]
    assert out["stats"]["frames"] > 0, "chaos never saw a sequenced frame"
    assert out["metrics"]["seq_frames"] > 0, \
        "window protocol was not engaged"
    assert out["regressions"] == [], \
        f"chaos regressed match indexes: {out['regressions']}"


def test_window_rewind_storm_keeps_match_monotonic():
    """Rewind storm: a high drop rate forces lane resets and windowed
    rewinds while frames stay pipelined; the storm must neither lose a
    commit nor ever resurrect/regress a match index (the request-capped
    SUCCESS rule and the flush-before-non-SUCCESS ordering guard hold
    under pipelining)."""
    out = asyncio.run(_windowed_chaos_rung(
        16, groups=6, writes=3, pipeline=4, drop_p=0.2, delay_p=0.2,
        seed=11))
    assert out["values"] == [3 * 4] * 6, out["values"]
    assert out["stats"]["dropped"] > 0, "storm never dropped a frame"
    # dropped sequenced frames surface as lane resets (sender re-cuts)
    assert out["metrics"]["lane_resets"] > 0, out["metrics"]
    assert out["regressions"] == [], \
        f"rewind storm regressed match indexes: {out['regressions']}"


def test_depth1_is_bit_identical_to_legacy():
    """window-depth=1 is the deterministic fallback: frames go out
    UNSEQUENCED with wire bytes identical to the pre-window protocol, the
    one-frame-per-group latch holds (seq_frames stays 0), and the rung
    commits the identical workload."""
    import msgpack

    from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
    from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest,
                                            AppendEnvelope, RaftRpcHeader,
                                            _encode, decode_rpc)
    from ratis_tpu.protocol.termindex import TermIndex

    reqs = tuple(
        AppendEntriesRequest(
            RaftRpcHeader(RaftPeerId.value_of("s0"),
                          RaftPeerId.value_of(f"s{i}"),
                          RaftGroupId.random_id(), 3),
            2, TermIndex(1, 4), (), 5, False, ())
        for i in (1, 2))
    # depth-1 frame (default lane/seq): bytes must equal the legacy
    # single-key envelope encoding exactly
    legacy = msgpack.packb(
        {"_": "env_req", "b": {"i": [r.to_dict() for r in reqs]}},
        use_bin_type=True)
    assert _encode(AppendEnvelope(reqs)) == legacy
    # sequenced frame: fast path must stay bit-compatible with the
    # generic packer and round-trip lane/seq
    env = AppendEnvelope(reqs, lane=(7 << 32) | 9, seq=3)
    fast = _encode(env)
    assert fast == msgpack.packb({"_": "env_req", "b": env.to_dict()},
                                 use_bin_type=True)
    back = decode_rpc(fast)
    assert (back.lane, back.seq) == (env.lane, env.seq)

    out1 = asyncio.run(_windowed_chaos_rung(1, groups=4, writes=2,
                                            pipeline=4))
    outd = asyncio.run(_windowed_chaos_rung(4, groups=4, writes=2,
                                            pipeline=4))
    # identical committed workload either depth
    assert out1["values"] == [2 * 4] * 4
    assert outd["values"] == [2 * 4] * 4
    # depth 1: zero sequenced frames, zero lane traffic — the exact
    # latched legacy protocol; depth 4: the lane path carried frames
    assert out1["metrics"]["seq_frames"] == 0, out1["metrics"]
    assert all(m["lane_frames"] == 0 for m in out1["lane_metrics"])
    assert outd["metrics"]["seq_frames"] > 0, outd["metrics"]
    assert any(m["lane_frames"] > 0 for m in outd["lane_metrics"])


def test_window_state_metrics_and_stuck_lane_watchdog():
    """Window state is observable: the replication_plane registry carries
    the per-destination frames-in-flight/occupancy gauges and rewind/
    out-of-order counters, and the watchdog journals a stuck-lane event
    when a sender's window stays full while commits are flat."""
    from ratis_tpu.server.watchdog import KIND_STUCK_LANE, StallWatchdog
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def body():
        cluster = BenchCluster(
            4, num_servers=3, batched=False, transport="sim",
            extra_props={
                "raft.tpu.replication.window-depth": "4",
                "raft.tpu.replication.sweep": "1",
                "raft.server.log.appender.coalescing.enabled": "true",
            })
        await cluster.start()
        try:
            await _drive_ordered(cluster, writes_per_group=1, pipeline=2)
            server = cluster.servers[0]
            from ratis_tpu.metrics.registry import MetricRegistries
            reg = MetricRegistries.global_registries().get(
                server._plane_info)
            names = set(reg.metric_names())
            assert "windowDepth" in names
            assert "windowRewinds" in names
            assert "laneOutOfOrderBuffered" in names
            assert any(n.startswith("windowFramesInFlight{")
                       for n in names), sorted(names)
            assert any(n.startswith("windowOccupancy{") for n in names)
            # force the stuck-lane shape: a full window + flat commits
            wd = StallWatchdog(server, interval_s=60.0)
            try:
                senders = list(server.replication._senders.values())
                assert senders
                s = senders[0]
                saved = s._frames_out
                s._frames_out = s.inflight_cap  # window pinned full
                wd.sample()  # establishes the commit baseline
                wd.sample()  # flat round 1
                wd.sample()  # flat round 2 -> episode event
                s._frames_out = saved
                kinds = [e["kind"] for e in wd.events()]
                assert KIND_STUCK_LANE in kinds, kinds
            finally:
                await wd.close()
        finally:
            await cluster.close()

    asyncio.run(body())


def test_task_leak_detector_catches_uncancelled_tasks():
    """Shutdown hygiene (tests/conftest.py): a task left pending on a
    closed loop without a cancel request is reported as a leak exactly
    once — the failure mode the PeerSender/LogAppender inflight-task
    bookkeeping must never produce."""
    import sys

    # use the conftest instance pytest actually loaded (a fresh
    # `import tests.conftest` would carry its own reported-leaks set and
    # the autouse fixture would re-report our deliberate leak)
    conftest = next(m for n, m in sys.modules.items()
                    if n.endswith("conftest")
                    and hasattr(m, "_pending_leaked_tasks"))
    _pending_leaked_tasks = conftest._pending_leaked_tasks

    async def naptime():
        await asyncio.sleep(60)

    loop = asyncio.new_event_loop()
    try:
        task = loop.create_task(naptime())
        loop.run_until_complete(asyncio.sleep(0))  # let the task start
    finally:
        loop.close()  # closed with the task still pending: a leak
    leaked = _pending_leaked_tasks()
    assert task in leaked, "leak detector missed a pending task"
    # reported exactly once: the autouse fixture must not re-fail every
    # later test for the same (deliberate) leak
    assert task not in _pending_leaked_tasks()


async def _latency_rung_elapsed(depth: int, delay_ms: float = 10.0,
                                groups: int = 2, writes: int = 2,
                                pipeline: int = 8) -> float:
    """Seconds to drive ``writes`` rounds of ``pipeline`` concurrent
    ordered writes per group through a sim cluster whose every hop costs
    ``delay_ms``, with 1-entry batches and ~1-item frames — the shape
    where the FRAME window is the only latency-hiding lever (the
    per-request pipeline window is held constant at its default)."""
    import time

    from ratis_tpu.tools.bench_cluster import BenchCluster

    cluster = BenchCluster(
        groups, num_servers=3, batched=False, transport="sim",
        extra_props={
            "raft.tpu.replication.window-depth": str(depth),
            "raft.tpu.replication.sweep": "1",
            "raft.server.log.appender.coalescing.enabled": "true",
            # 1-byte budgets: one entry per request, ~one item per frame,
            # so frames cannot hide latency behind giant batches — the
            # depth knob is isolated (same trick as
            # tests/test_appender_pipeline.py at the request level)
            "raft.server.log.appender.buffer.byte-limit": "1",
            "raft.server.log.appender.envelope.byte-limit": "1",
        })
    await cluster.start()
    try:
        # warm leadership + first commit BEFORE injecting latency
        await _drive_ordered(cluster, writes_per_group=1, pipeline=1)
        cluster.network.base_delay_ms = delay_ms
        t0 = time.monotonic()
        await _drive_ordered(cluster, writes_per_group=writes,
                             pipeline=pipeline)
        return time.monotonic() - t0
    finally:
        cluster.network.base_delay_ms = 0.0
        await cluster.close()


@pytest.mark.slow
def test_frame_window_hides_append_round_trip():
    """The tentpole's mechanism, isolated: with real per-hop latency and
    one-entry frames, depth 1 pays a full RTT of dead time per frame per
    group while depth 8 keeps the lane full — >=2x wall-clock speedup
    (the latency-bound analog of the request-window test in
    tests/test_appender_pipeline.py, one level up the stack)."""

    async def main():
        stop_and_wait = await _latency_rung_elapsed(1)
        pipelined = await _latency_rung_elapsed(8)
        assert pipelined * 2 <= stop_and_wait, (
            f"pipelined={pipelined:.3f}s stop_and_wait={stop_and_wait:.3f}s")

    asyncio.run(main())
