"""Multi-device mesh tests on the 8-virtual-device CPU fleet: the sharded
engine step must produce bit-identical results to the single-device path
(kernel/scalar differential testing is in test_ops_quorum; this layer
checks the SPMD partitioning)."""

import jax
import numpy as np
import pytest

from __graft_entry__ import _example_batch
from ratis_tpu.parallel import (GROUP_AXIS, make_group_mesh, shard_batch,
                                sharded_engine_step)


def _single_device_step(args):
    import jax.numpy as jnp

    from ratis_tpu.ops.quorum import engine_step
    return jax.jit(engine_step)(*[jnp.asarray(a) for a in args])


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_step_matches_single_device(n_devices):
    mesh = make_group_mesh(n_devices)
    args = _example_batch(num_groups=64, num_peers=8, num_events=128,
                          seed=7)
    sharded = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    single = _single_device_step(args)
    for name in sharded._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded, name)),
            np.asarray(getattr(single, name)), err_msg=name)


def test_sharded_output_layout():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=64, num_peers=8, num_events=16)
    out = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    # outputs stay sharded over the group axis — no implicit gather
    spec = out.new_commit.sharding.spec
    assert spec[0] == GROUP_AXIS
    assert out.match_index.sharding.spec[0] == GROUP_AXIS


def test_shard_batch_rejects_indivisible():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=12, num_peers=8, num_events=4)
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(mesh, args)


def test_mesh_requires_enough_devices():
    with pytest.raises(ValueError, match="need 99 devices"):
        make_group_mesh(99)


def test_dryrun_entry_points():
    """entry() compiles; dryrun_multichip runs on the virtual fleet (the
    driver invokes these exact functions)."""
    from __graft_entry__ import dryrun_multichip, entry
    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    dryrun_multichip(8)
