"""Multi-device mesh tests on the 8-virtual-device CPU fleet: the sharded
engine step must produce bit-identical results to the single-device path
(kernel/scalar differential testing is in test_ops_quorum; this layer
checks the SPMD partitioning)."""

import jax
import numpy as np
import pytest

from __graft_entry__ import _example_batch
from ratis_tpu.parallel import (GROUP_AXIS, make_group_mesh, shard_batch,
                                sharded_engine_step)


def _single_device_step(args):
    import jax.numpy as jnp

    from ratis_tpu.ops.quorum import engine_step
    return jax.jit(engine_step)(*[jnp.asarray(a) for a in args])


class _FakeClock:
    def __init__(self):
        self.t = 0

    def now_ms(self):
        return self.t

    def advance_epoch(self, delta_ms):
        self.t -= delta_ms


class _Rec:
    def __init__(self):
        self.events = []

    def on_commit_advance_now(self, c):
        self.events.append(("commit", c))

    async def on_commit_advance(self, c):
        self.events.append(("commit", c))

    async def on_election_timeout(self):
        self.events.append("timeout")

    async def on_leadership_stale(self):
        self.events.append("stale")


@pytest.mark.mesh
@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_step_matches_single_device(n_devices):
    mesh = make_group_mesh(n_devices)
    args = _example_batch(num_groups=64, num_peers=8, num_events=128,
                          seed=7)
    sharded = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    single = _single_device_step(args)
    for name in sharded._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded, name)),
            np.asarray(getattr(single, name)), err_msg=name)


@pytest.mark.mesh
def test_sharded_output_layout():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=64, num_peers=8, num_events=16)
    out = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    # outputs stay sharded over the group axis — no implicit gather
    spec = out.new_commit.sharding.spec
    assert spec[0] == GROUP_AXIS
    assert out.match_index.sharding.spec[0] == GROUP_AXIS


@pytest.mark.mesh
def test_shard_batch_rejects_indivisible():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=12, num_peers=8, num_events=4)
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(mesh, args)


def test_mesh_requires_enough_devices():
    with pytest.raises(ValueError, match="need 99 devices"):
        make_group_mesh(99)


def test_dryrun_entry_points():
    """entry() compiles; dryrun_multichip runs on the virtual fleet (the
    driver invokes these exact functions)."""
    from __graft_entry__ import dryrun_multichip, entry
    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    dryrun_multichip(8)


@pytest.mark.mesh
@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_resident_engine_bit_identical(n_devices):
    """The PRODUCTION resident path (QuorumEngine with mesh=..., donated
    DeviceState sharded over the group axis) must be observationally
    bit-identical to the same engine without a mesh: same state mirror,
    same commit callbacks, same timeout firings, under a scripted
    refresh + fast-tick + timeout scenario."""
    import asyncio

    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.engine.state import NO_DEADLINE, ROLE_FOLLOWER, ROLE_LEADER

    G = 16

    def build(mesh):
        eng = QuorumEngine(max_groups=G, max_peers=8,
                           scalar_fallback_threshold=0, use_device=True,
                           mesh=mesh)
        eng.clock = _FakeClock()
        recs = []
        s = eng.state
        for i in range(G):
            rec = _Rec()
            slot = eng.attach(rec)
            recs.append((slot, rec))
            cur = np.zeros(8, bool)
            cur[:3] = True
            s.set_conf(slot, 0, cur, np.zeros(8, bool),
                       np.zeros(8, np.int32), 0)
            if i % 2 == 0:
                s.role[slot] = ROLE_LEADER
                s.last_ack_ms[slot, :3] = 0
            else:
                s.role[slot] = ROLE_FOLLOWER
                s.election_deadline_ms[slot] = 500 + i
            s.mark_dirty(slot)
        return eng, recs

    async def drive(eng, recs):
        await eng.tick()  # first dispatch: full upload absorbs the dirt
        for slot, _ in recs[::2]:              # leaders: flush + quorum ack
            eng.on_flush(slot, 7)
            eng.on_ack(slot, 1, 7)
        eng.clock.t = 100
        await eng.tick()                       # fast pass
        # Mark rows dirty BETWEEN ticks so the next dispatch exercises the
        # dirty-row REFRESH kernel (sharded_resident_step) — without this
        # the first upload absorbs all dirt and only the fast path runs.
        s = eng.state
        for slot, _ in recs[:4]:
            s.match_index[slot, 2] = 3
            s.mark_dirty(slot)
        eng.clock.t = 200
        await eng.tick()                       # refresh pass
        assert eng.metrics["refresh_ticks"] > 0
        eng.clock.t = 600 + G                  # all follower deadlines past
        await eng.tick()                       # timeout sweep
        return eng, recs

    async def run_pair():
        mesh = make_group_mesh(n_devices)
        e1, r1 = await drive(*build(mesh))
        e2, r2 = await drive(*build(None))
        for (s1, a), (s2, b) in zip(r1, r2):
            assert a.events == b.events, (s1, a.events, b.events)
        for name in ("match_index", "commit_index", "flush_index",
                     "election_deadline_ms", "last_ack_ms"):
            np.testing.assert_array_equal(
                getattr(e1.state, name), getattr(e2.state, name),
                err_msg=name)
        # sharded run's resident state spans all devices
        devs = {sh.device for sh in e1._dev.match_index.addressable_shards}
        assert len(devs) == n_devices

    asyncio.run(run_pair())


@pytest.mark.mesh
@pytest.mark.parametrize("n_devices,seed", [(2, 3), (8, 4), (8, 5)])
def test_mesh_engine_randomized_churn_bit_identical(n_devices, seed):
    """Randomized differential gate: the mesh engine must stay
    OBSERVATIONALLY bit-identical to the single-device engine under a
    seed-derived script of slot churn (attach/detach), demote/re-elect
    flips, joint conf changes, and ack/flush/deadline traffic.  Raw slot
    NUMBERS may legitimately diverge after churn (per-slice free lists vs
    the flat list), so rows and event streams are compared per LISTENER —
    the observable identity a division actually rides on."""
    import asyncio

    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.engine.state import NO_DEADLINE, ROLE_FOLLOWER, ROLE_LEADER

    G, P = 24, 8
    rng = np.random.default_rng(seed)

    # ---- one engine-independent op script, derived only from the seed
    script = []
    alive = []
    next_id = 0

    def gen_attach():
        nonlocal next_id
        i = next_id
        next_id += 1
        alive.append(i)
        script.append(("attach", i, 3 + int(rng.integers(0, 3))))

    for _ in range(12):
        gen_attach()
    t = 0
    for _round in range(6):
        for _ in range(int(rng.integers(2, 6))):
            kind = str(rng.choice(["detach", "attach", "demote", "elect",
                                   "conf", "ack", "flush", "deadline"]))
            if kind == "detach" and len(alive) > 4:
                script.append(("detach",
                               alive.pop(int(rng.integers(0, len(alive))))))
                continue
            if kind == "attach":
                if len(alive) < G - 2:
                    gen_attach()
                continue
            if not alive:
                continue
            i = alive[int(rng.integers(0, len(alive)))]
            if kind == "demote":
                script.append(("demote", i,
                               t + 50 + int(rng.integers(0, 400))))
            elif kind == "elect":
                script.append(("elect", i))
            elif kind == "conf":
                cur = rng.random(P) < 0.5
                cur[0] = True
                old = np.zeros(P, bool)
                if rng.random() < 0.4:
                    old = rng.random(P) < 0.4
                    old[0] = True
                script.append(("conf", i, cur, old))
            elif kind == "ack":
                script.append(("ack", i, int(rng.integers(1, 4)),
                               int(rng.integers(0, 64))))
            elif kind == "flush":
                script.append(("flush", i, int(rng.integers(0, 64))))
            elif kind == "deadline":
                script.append(("deadline", i,
                               t + int(rng.integers(50, 600))))
        t += int(rng.integers(40, 260))
        script.append(("tick", t))
    script.append(("tick", t + 2000))  # sweep every follower deadline

    async def run_engine(mesh):
        eng = QuorumEngine(max_groups=G, max_peers=P,
                           scalar_fallback_threshold=0, use_device=True,
                           mesh=mesh)
        eng.clock = _FakeClock()
        s = eng.state
        recs = {}  # listener idx -> _Rec (kept after detach)
        live = {}  # listener idx -> current slot
        for op in script:
            kind = op[0]
            if kind == "attach":
                _, i, voters = op
                rec = _Rec()
                slot = eng.attach(rec)
                recs[i], live[i] = rec, slot
                cur = np.zeros(P, bool)
                cur[:voters] = True
                s.set_conf(slot, 0, cur, np.zeros(P, bool),
                           np.zeros(P, np.int32), 0)
                s.role[slot] = ROLE_FOLLOWER
                s.election_deadline_ms[slot] = NO_DEADLINE
                s.mark_dirty(slot)
            elif kind == "detach":
                eng.detach(live.pop(op[1]))
            elif kind == "demote":
                slot = live[op[1]]
                s.role[slot] = ROLE_FOLLOWER
                s.election_deadline_ms[slot] = op[2]
                s.mark_dirty(slot)
            elif kind == "elect":
                slot = live[op[1]]
                s.role[slot] = ROLE_LEADER
                s.last_ack_ms[slot, :3] = eng.clock.t
                s.election_deadline_ms[slot] = NO_DEADLINE
                s.mark_dirty(slot)
            elif kind == "conf":
                s.set_conf(live[op[1]], 0, op[2], op[3],
                           np.zeros(P, np.int32), 0)
            elif kind == "ack":
                eng.on_ack(live[op[1]], op[2], op[3])
            elif kind == "flush":
                eng.on_flush(live[op[1]], op[2])
            elif kind == "deadline":
                eng.on_deadline(live[op[1]], op[2])
            elif kind == "tick":
                eng.clock.t = op[1]
                await eng.tick()
        return eng, recs, live

    async def run_pair():
        e1, r1, l1 = await run_engine(make_group_mesh(n_devices))
        e2, r2, l2 = await run_engine(None)
        assert set(r1) == set(r2) and set(l1) == set(l2)
        for i in sorted(r1):
            assert r1[i].events == r2[i].events, \
                (i, r1[i].events, r2[i].events)
        for i in sorted(l1):
            s1, s2 = l1[i], l2[i]
            for name in ("role", "match_index", "commit_index",
                         "flush_index", "election_deadline_ms",
                         "last_ack_ms", "conf_cur", "conf_old"):
                np.testing.assert_array_equal(
                    getattr(e1.state, name)[s1],
                    getattr(e2.state, name)[s2],
                    err_msg=f"listener {i} field {name}")
        devs = {sh.device for sh in e1._dev.match_index.addressable_shards}
        assert len(devs) == n_devices
        assert e1.metrics["fast_ticks"] > 0

    asyncio.run(run_pair())


@pytest.mark.mesh
def test_cluster_on_sharded_engine():
    """A full cluster with raft.tpu.engine.mesh-devices=8: elections,
    writes, and commit advancement all run through the group-sharded
    donated resident state (the production multi-chip configuration)."""
    import sys
    sys.path.insert(0, "tests")
    from minicluster import MiniCluster, batched_properties, run_with_new_cluster
    from ratis_tpu.conf.keys import RaftServerConfigKeys

    p = batched_properties()
    p.set(RaftServerConfigKeys.Engine.MESH_DEVICES_KEY, "8")
    # capacity is auto-padded to the next mesh multiple (PR 18); the
    # default 1024 is already a multiple of 8

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader(timeout=30)
        srv = cluster.servers[leader.member_id.peer_id]
        assert srv.engine.mesh is not None
        for _ in range(5):
            assert (await cluster.send_write()).success
        devs = {sh.device
                for sh in srv.engine._dev.match_index.addressable_shards}
        assert len(devs) == 8, f"resident state on {len(devs)} devices"

    run_with_new_cluster(3, body, properties=p)
