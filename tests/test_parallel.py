"""Multi-device mesh tests on the 8-virtual-device CPU fleet: the sharded
engine step must produce bit-identical results to the single-device path
(kernel/scalar differential testing is in test_ops_quorum; this layer
checks the SPMD partitioning)."""

import jax
import numpy as np
import pytest

from __graft_entry__ import _example_batch
from ratis_tpu.parallel import (GROUP_AXIS, make_group_mesh, shard_batch,
                                sharded_engine_step)


def _single_device_step(args):
    import jax.numpy as jnp

    from ratis_tpu.ops.quorum import engine_step
    return jax.jit(engine_step)(*[jnp.asarray(a) for a in args])


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_step_matches_single_device(n_devices):
    mesh = make_group_mesh(n_devices)
    args = _example_batch(num_groups=64, num_peers=8, num_events=128,
                          seed=7)
    sharded = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    single = _single_device_step(args)
    for name in sharded._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded, name)),
            np.asarray(getattr(single, name)), err_msg=name)


def test_sharded_output_layout():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=64, num_peers=8, num_events=16)
    out = sharded_engine_step(mesh)(*shard_batch(mesh, args))
    # outputs stay sharded over the group axis — no implicit gather
    spec = out.new_commit.sharding.spec
    assert spec[0] == GROUP_AXIS
    assert out.match_index.sharding.spec[0] == GROUP_AXIS


def test_shard_batch_rejects_indivisible():
    mesh = make_group_mesh(8)
    args = _example_batch(num_groups=12, num_peers=8, num_events=4)
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch(mesh, args)


def test_mesh_requires_enough_devices():
    with pytest.raises(ValueError, match="need 99 devices"):
        make_group_mesh(99)


def test_dryrun_entry_points():
    """entry() compiles; dryrun_multichip runs on the virtual fleet (the
    driver invokes these exact functions)."""
    from __graft_entry__ import dryrun_multichip, entry
    fn, args = entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    dryrun_multichip(8)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_resident_engine_bit_identical(n_devices):
    """The PRODUCTION resident path (QuorumEngine with mesh=..., donated
    DeviceState sharded over the group axis) must be observationally
    bit-identical to the same engine without a mesh: same state mirror,
    same commit callbacks, same timeout firings, under a scripted
    refresh + fast-tick + timeout scenario."""
    import asyncio

    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.engine.state import NO_DEADLINE, ROLE_FOLLOWER, ROLE_LEADER

    class FakeClock:
        def __init__(self):
            self.t = 0

        def now_ms(self):
            return self.t

        def advance_epoch(self, delta_ms):
            self.t -= delta_ms

    class Rec:
        def __init__(self):
            self.events = []

        def on_commit_advance_now(self, c):
            self.events.append(("commit", c))

        async def on_commit_advance(self, c):
            self.events.append(("commit", c))

        async def on_election_timeout(self):
            self.events.append("timeout")

        async def on_leadership_stale(self):
            self.events.append("stale")

    G = 16

    def build(mesh):
        eng = QuorumEngine(max_groups=G, max_peers=8,
                           scalar_fallback_threshold=0, use_device=True,
                           mesh=mesh)
        eng.clock = FakeClock()
        recs = []
        s = eng.state
        for i in range(G):
            rec = Rec()
            slot = eng.attach(rec)
            recs.append((slot, rec))
            cur = np.zeros(8, bool)
            cur[:3] = True
            s.set_conf(slot, 0, cur, np.zeros(8, bool),
                       np.zeros(8, np.int32), 0)
            if i % 2 == 0:
                s.role[slot] = ROLE_LEADER
                s.last_ack_ms[slot, :3] = 0
            else:
                s.role[slot] = ROLE_FOLLOWER
                s.election_deadline_ms[slot] = 500 + i
            s.mark_dirty(slot)
        return eng, recs

    async def drive(eng, recs):
        await eng.tick()  # first dispatch: full upload absorbs the dirt
        for slot, _ in recs[::2]:              # leaders: flush + quorum ack
            eng.on_flush(slot, 7)
            eng.on_ack(slot, 1, 7)
        eng.clock.t = 100
        await eng.tick()                       # fast pass
        # Mark rows dirty BETWEEN ticks so the next dispatch exercises the
        # dirty-row REFRESH kernel (sharded_resident_step) — without this
        # the first upload absorbs all dirt and only the fast path runs.
        s = eng.state
        for slot, _ in recs[:4]:
            s.match_index[slot, 2] = 3
            s.mark_dirty(slot)
        eng.clock.t = 200
        await eng.tick()                       # refresh pass
        assert eng.metrics["refresh_ticks"] > 0
        eng.clock.t = 600 + G                  # all follower deadlines past
        await eng.tick()                       # timeout sweep
        return eng, recs

    async def run_pair():
        mesh = make_group_mesh(n_devices)
        e1, r1 = await drive(*build(mesh))
        e2, r2 = await drive(*build(None))
        for (s1, a), (s2, b) in zip(r1, r2):
            assert a.events == b.events, (s1, a.events, b.events)
        for name in ("match_index", "commit_index", "flush_index",
                     "election_deadline_ms", "last_ack_ms"):
            np.testing.assert_array_equal(
                getattr(e1.state, name), getattr(e2.state, name),
                err_msg=name)
        # sharded run's resident state spans all devices
        devs = {sh.device for sh in e1._dev.match_index.addressable_shards}
        assert len(devs) == n_devices

    asyncio.run(run_pair())


def test_cluster_on_sharded_engine():
    """A full cluster with raft.tpu.engine.mesh-devices=8: elections,
    writes, and commit advancement all run through the group-sharded
    donated resident state (the production multi-chip configuration)."""
    import sys
    sys.path.insert(0, "tests")
    from minicluster import MiniCluster, batched_properties, run_with_new_cluster
    from ratis_tpu.conf.keys import RaftServerConfigKeys

    p = batched_properties()
    p.set(RaftServerConfigKeys.Engine.MESH_DEVICES_KEY, "8")
    # mesh size must divide the group capacity; default 1024 % 8 == 0

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader(timeout=30)
        srv = cluster.servers[leader.member_id.peer_id]
        assert srv.engine.mesh is not None
        for _ in range(5):
            assert (await cluster.send_write()).success
        devs = {sh.device
                for sh in srv.engine._dev.match_index.addressable_shards}
        assert len(devs) == 8, f"resident state on {len(devs)} devices"

    run_with_new_cluster(3, body, properties=p)
