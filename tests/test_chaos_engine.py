"""Chaos subsystem units + fast deterministic scenario gates: the link-
fault shim, schedule determinism, the replay artifact contract, the
LOG_SYNC/RUN_LOG_WORKER injection points, and shell-health fault
surfacing."""

import asyncio
import json
import time

import pytest

from ratis_tpu.chaos.cluster import ChaosCluster
from ratis_tpu.chaos.faults import Step, make_step, truncate_log_tail
from ratis_tpu.chaos.link import LinkFaultTable, link_faults
from ratis_tpu.chaos.scenario import run_scenario, write_artifact
from ratis_tpu.chaos.scenarios import build_scenario, scenario_names
from ratis_tpu.protocol.exceptions import TimeoutIOException


# ----------------------------------------------------- link-fault table

def test_link_table_wildcards_and_specificity():
    t = LinkFaultTable()
    t.block("s0", "s1")
    t.set_link("s0", None, latency_ms=5)
    assert t.is_blocked("s0", "s1")          # exact beats wildcard
    assert not t.is_blocked("s0", "s2")      # wildcard entry: latency only
    assert t.lookup("s0", "s2").latency_ms == 5
    assert t.lookup("s2", "s0") is None
    t.heal("s0", "s1")
    assert not t.is_blocked("s0", "s1")
    t.heal_all()
    assert not t


def test_link_table_partition_and_isolate():
    t = LinkFaultTable()
    t.partition(["s0"], ["s1", "s2"])
    assert t.is_blocked("s0", "s1") and t.is_blocked("s1", "s0")
    assert t.is_blocked("s0", "s2") and t.is_blocked("s2", "s0")
    assert not t.is_blocked("s1", "s2")
    t.heal_all()
    t.isolate("s1")
    assert t.is_blocked("s0", "s1") and t.is_blocked("s1", "s2")


def test_link_gate_block_drop_latency():
    async def main():
        t = LinkFaultTable(seed=5)
        t.block("a", "b")
        with pytest.raises(TimeoutIOException):
            await t.gate("a", "b")
        t.heal_all()
        # deterministic drops: same seed -> same accept/drop sequence
        t.set_link("a", "b", drop_rate=0.5)
        async def seq():
            out = []
            for _ in range(20):
                try:
                    await t.gate("a", "b")
                    out.append(1)
                except TimeoutIOException:
                    out.append(0)
            return out
        t.reseed(99)
        first = await seq()
        t.reseed(99)
        assert await seq() == first
        assert 0 < sum(first) < 20  # actually drops AND passes
        # latency actually delays
        t.heal_all()
        t.set_link("a", "b", latency_ms=30)
        t0 = time.monotonic()
        await t.gate("a", "b")
        assert time.monotonic() - t0 >= 0.025
    asyncio.run(main())


def test_transports_skip_gate_unless_chaos_enabled():
    """A production server (key unset) never consults the table: a
    registered fault must NOT bite a chaos-disabled cluster."""
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from minicluster import MiniCluster, fast_properties

    async def main():
        cluster = MiniCluster(3, properties=fast_properties())
        await cluster.start()
        try:
            await cluster.wait_for_leader()
            link_faults().block(None, None)  # blackhole EVERYTHING
            reply = await cluster.send_write()
            assert reply.success  # the fault plane is disarmed
        finally:
            link_faults().heal_all()
            await cluster.close()

    asyncio.run(main())


# ------------------------------------------------ schedule determinism

def test_schedules_are_seed_deterministic():
    cfg = {"servers": 3, "duration_s": 5.0, "durable": True}
    for name in scenario_names():
        a = build_scenario(name, 17, cfg)
        b = build_scenario(name, 17, cfg)
        assert a.steps == b.steps, f"{name}: schedule not deterministic"
        assert a.steps, f"{name}: empty schedule"
        c = build_scenario(name, 18, cfg)
        assert a.steps != c.steps or len(a.steps) <= 2, \
            f"{name}: seed does not vary the schedule"


def test_step_json_roundtrip():
    s = make_step(1.25, "link", "follower:0", latency_ms=5.0,
                  drop_rate=0.125)
    assert Step.from_json(json.loads(json.dumps(s.to_json()))) == s


def test_replay_rebuild_matches_and_detects_drift(tmp_path):
    from ratis_tpu.chaos.scenario import ScenarioResult
    from ratis_tpu.tools.chaos_replay import load_artifact, rebuild_scenario
    sc = build_scenario("partition_leader", 31, {"servers": 3})
    res = ScenarioResult(sc.name, sc.seed, passed=False, error="boom")
    path = write_artifact(res, sc, tmp_path)
    rebuilt = rebuild_scenario(load_artifact(str(path)))
    assert rebuilt.steps == sc.steps  # bit-for-bit, through JSON and back
    # a tampered/stale schedule is refused, not silently re-derived
    artifact = json.loads(path.read_text())
    artifact["scenario"]["steps"][0]["at_s"] += 1.0
    path.write_text(json.dumps(artifact))
    with pytest.raises(SystemExit):
        rebuild_scenario(load_artifact(str(path)))


def test_failing_scenario_writes_artifact(tmp_path):
    """An SLO miss emits the self-contained replay artifact."""

    async def main():
        cluster = ChaosCluster(3, 1)
        await cluster.start()
        try:
            # unmeetable acked floor -> deterministic failure
            sc = build_scenario("partition_minority", 13,
                                {"convergence_s": 20.0, "recovery_s": 30.0,
                                 "min_acked": 10 ** 9})
            res = await run_scenario(cluster, sc,
                                     artifact_dir=str(tmp_path))
            assert not res.passed
            path = tmp_path / "chaos-partition_minority-seed13.json"
            assert path.exists()
            artifact = json.loads(path.read_text())
            assert artifact["scenario"]["seed"] == 13
            assert artifact["journal"], "journal missing from artifact"
            from ratis_tpu.tools.chaos_replay import rebuild_scenario
            assert rebuild_scenario(artifact).steps == sc.steps
        finally:
            await cluster.close()

    asyncio.run(main())


# ------------------------------------- fast deterministic scenario gates

@pytest.mark.chaos
@pytest.mark.parametrize("name", ["partition_leader", "link_degraded",
                                  "crash_restart_leader"])
def test_fast_scenario_gate(name):
    """Tier-1 standing gate: one deterministic scenario per fault class
    on a fresh 3-server cluster, all SLOs asserted by the engine."""

    async def main():
        cluster = ChaosCluster(3, 1, seed=5)
        await cluster.start()
        try:
            sc = build_scenario(name, 5, {"convergence_s": 30.0,
                                          "recovery_s": 60.0,
                                          "min_acked": 10})
            res = await run_scenario(cluster, sc)
            assert res.passed, (
                f"[seed 5] {name} failed: {res.error}\n"
                f"journal: {res.journal}")
            # every injected fault journaled through /events and paired
            kinds = [e["kind"] for e in res.journal]
            assert "injected-fault" in kinds
            assert "fault-recovered" in kinds
            injected = {e["fault"] for e in res.journal
                        if e["kind"] == "injected-fault"}
            recovered = {e["fault"] for e in res.journal
                         if e["kind"] == "fault-recovered"}
            assert injected <= recovered, \
                f"[seed 5] unpaired faults: {injected - recovered}"
        finally:
            await cluster.close()

    asyncio.run(main())


@pytest.mark.chaos
def test_partition_bites_real_tcp_sockets():
    """The tentpole's transport reach: the link-fault shim partitions a
    REAL-socket (TCP) cluster, not just the simulated hub — blocked hops
    show up in the gate metrics and the scenario still meets its SLOs."""

    async def main():
        cluster = ChaosCluster(3, 1, transport="tcp", seed=3)
        await cluster.start()
        try:
            before = dict(link_faults().metrics)
            sc = build_scenario("partition_leader", 3,
                                {"convergence_s": 30.0, "recovery_s": 60.0,
                                 "min_acked": 10})
            res = await run_scenario(cluster, sc)
            assert res.passed, f"[seed 3] tcp partition failed: {res.error}"
            blocked = (link_faults().metrics["blocked"]
                       - before.get("blocked", 0))
            assert blocked > 0, "no TCP hop was ever gated"
        finally:
            await cluster.close()

    asyncio.run(main())


# ------------------------------- LOG_SYNC / RUN_LOG_WORKER actually bite

def test_log_sync_injection_slows_flush(tmp_path):
    """Satellite: the dormant LOG_SYNC point is now wired into the shared
    LogWorker's flush path — a registered delay measurably slows a
    wait_flush append."""
    from ratis_tpu.protocol.logentry import make_transaction_entry
    from ratis_tpu.server.log.segmented import LogWorker, SegmentedRaftLog
    from ratis_tpu.util import injection

    async def main():
        worker_started = []

        async def on_worker(local_id, _remote, *_args):
            worker_started.append(str(local_id))

        injection.put(injection.RUN_LOG_WORKER, on_worker)
        log = SegmentedRaftLog("chaoslog", tmp_path / "current",
                              worker=LogWorker("chaos-test"))
        await log.open()
        e = make_transaction_entry(1, 0, b"c" * 16, 0, b"x" * 16)
        await log.append_entry(e, wait_flush=True)
        assert worker_started == ["chaos-test"]  # RUN_LOG_WORKER fired

        delay = 0.08

        async def slow_sync(local_id, _remote, *_args):
            await asyncio.sleep(delay)

        injection.put(injection.LOG_SYNC, slow_sync)
        t0 = time.monotonic()
        await log.append_entry(
            make_transaction_entry(1, 1, b"c" * 16, 1, b"y" * 16),
                               wait_flush=True)
        took = time.monotonic() - t0
        assert took >= delay * 0.9, \
            f"LOG_SYNC delay did not bite the flush path ({took:.3f}s)"
        injection.remove(injection.LOG_SYNC)
        t0 = time.monotonic()
        await log.append_entry(
            make_transaction_entry(1, 2, b"c" * 16, 2, b"z" * 16),
                               wait_flush=True)
        assert time.monotonic() - t0 < delay  # back to full speed
        await log.close()

    asyncio.run(main())


def test_truncate_log_tail(tmp_path):
    """The crash-with-lost-tail helper drops whole records and leaves a
    structurally valid (recoverable) log behind."""
    from ratis_tpu.protocol.logentry import make_transaction_entry
    from ratis_tpu.server.log.segmented import LogWorker, SegmentedRaftLog

    async def main():
        d = tmp_path / "current"
        log = SegmentedRaftLog("tlog", d, worker=LogWorker("t-test"))
        await log.open()
        for i in range(10):
            await log.append_entry(
                make_transaction_entry(1, i, b"c" * 16, i,
                                       f"e{i}".encode()),
                                   wait_flush=True)
        await log.close()
        assert truncate_log_tail(d, 3) == 3
        log2 = SegmentedRaftLog("tlog2", d, worker=LogWorker("t-test2"))
        await log2.open()
        assert log2.next_index == 7          # tail gone, prefix intact
        assert log2.get(6) is not None and log2.get(7) is None
        await log2.close()

    asyncio.run(main())


# ----------------------------------------- shell health fault surfacing

def test_health_surfaces_active_and_unrecovered_faults(capsys):
    """Active injected faults and unrecovered injected-fault events exit
    1; once healed AND paired with fault-recovered, health goes green
    again (recovered faults print as history only)."""
    import argparse

    from ratis_tpu.shell.cli import cmd_health

    async def main():
        p_extra = {"raft.tpu.metrics.http-port": "0",
                   "raft.tpu.chaos.enabled": "true"}
        from ratis_tpu.chaos.cluster import chaos_properties
        props = chaos_properties(1)
        for k, v in p_extra.items():
            props.set(k, v)
        cluster = ChaosCluster(3, 1, properties=props)
        await cluster.start()
        try:
            await cluster.wait_for_leader()
            endpoints = ",".join(s.metrics_http.address
                                 for s in cluster.servers.values())
            args = argparse.Namespace(endpoints=endpoints, timeout=10.0,
                                      verbose=False)
            assert await cmd_health(args) == 0
            capsys.readouterr()

            # an ACTIVE link fault degrades health even before any event
            link_faults().set_link("s1", None, latency_ms=5)
            assert await cmd_health(args) == 1
            assert "ACTIVE INJECTED FAULTS" in capsys.readouterr().out
            link_faults().heal_all()

            # an unrecovered injected-fault event degrades health...
            cluster.emit_fault_event("injected-fault", "partition s1",
                                     fault_id="t/1/0")
            assert await cmd_health(args) == 1
            assert "UNRECOVERED" in capsys.readouterr().out
            # ...until its recovery pair lands
            cluster.emit_fault_event("fault-recovered",
                                     "recovered: partition s1",
                                     fault_id="t/1/0")
            assert await cmd_health(args) == 0
            out = capsys.readouterr().out
            assert "(recovered)" in out
        finally:
            await cluster.close()

    asyncio.run(main())
