"""Continuous telemetry: the time-series sampler (rates, log2-bucket
latency quantiles, space-saving hot-group sketch), the /timeseries +
/hotgroups + /flightrecorder endpoints with incremental ``?since=``
polling, the flight recorder's dump triggers (watchdog degradation,
chaos failure, explicit request), `shell top`, the watchdog's monotonic
event seq ids, partial-failure-tolerant cluster scrapes, and the
mp-marked cross-process merge."""

import asyncio
import json
import os
import sys

import pytest

from minicluster import MiniCluster, fast_properties
from ratis_tpu.metrics.timeseries import (Log2Buckets, SpaceSavingSketch,
                                          log2_bucket)


def _tel_properties(flight_dir=None):
    p = fast_properties()
    p.set("raft.tpu.metrics.http-port", "0")
    p.set("raft.tpu.watchdog.interval", "150ms")
    p.set("raft.tpu.telemetry.enabled", "true")
    p.set("raft.tpu.telemetry.interval", "100ms")
    if flight_dir is not None:
        p.set("raft.tpu.telemetry.flight-dir", str(flight_dir))
    return p


# ------------------------------------------------------------ unit layer

def test_space_saving_sketch_tracks_heavy_hitters_in_k_space():
    import random
    rng = random.Random(7)
    s = SpaceSavingSketch(8)
    true = {}
    # zipf-ish stream: g0 gets half the mass, a 200-key tail the rest
    for _ in range(20_000):
        k = "g0" if rng.random() < 0.5 else f"g{rng.randrange(1, 200)}"
        true[k] = true.get(k, 0) + 1
        s.offer(k, 1)
    assert len(s) <= 8                       # never more than k counters
    top = s.top()
    assert top[0]["key"] == "g0"
    # space-saving bounds: count - err <= true <= count, err <= total/k
    for e in top:
        t = true.get(e["key"], 0)
        assert e["count"] - e["err"] <= t <= e["count"]
        assert e["err"] <= s.total / 8
    # aux (pending depth) rides along without disturbing the counts
    s.offer("g0", 0, aux=42)
    assert s.top(1)[0]["aux"] == 42


def test_log2_buckets_quantiles_within_2x():
    b = Log2Buckets()
    for v in (0.001,) * 50 + (0.010,) * 45 + (0.100,) * 5:
        b.update(v)
    snap = b.snapshot()
    assert snap["count"] == 100
    # log2 resolution: the reported bucket upper bound is within 2x
    assert 0.001e3 <= snap["p50_ms"] <= 0.002e3 * 2
    assert 0.1e3 <= snap["p99_ms"] <= 0.2e3 * 2
    # sparse bucket encoding merges by plain addition
    assert sum(snap["buckets"].values()) == 100
    assert log2_bucket(0.0) == 0 and log2_bucket(1e9) == 63


# ------------------------------------------------- live-cluster endpoints

def test_timeseries_endpoint_incremental_and_hotgroups():
    """Acceptance: /timeseries serves bounded samples with derived rates
    and ?since= returns only newer ones; /hotgroups shows the written
    group with the sketch's share accounting."""

    async def body():
        from ratis_tpu.metrics.aggregate import fetch_json
        cluster = MiniCluster(3, properties=_tel_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            # let the sampler observe the fresh leadership first: a
            # group's commit baseline anchors at first sight, so load
            # written before that would be (correctly) unattributed
            await asyncio.sleep(0.15)
            for _ in range(5):
                assert (await cluster.send_write()).success
            await asyncio.sleep(0.45)
            srv = cluster.servers[leader.member_id.peer_id]
            addr = srv.metrics_http.address
            ts = await fetch_json(addr, "/timeseries")
            assert ts["count"] >= 3 and ts["seq"] >= 2
            sample = ts["samples"][-1]
            for key in ("seq", "t", "rates", "totals", "occupancy",
                        "pending", "latency"):
                assert key in sample, sample
            assert sample["totals"]["commits"] >= 5
            # rates derived from deltas: commits moved, so SOME sample in
            # the window carries a positive commit rate
            assert any(s["rates"]["commits_per_s"] > 0
                       for s in ts["samples"])
            # incremental poll: only samples newer than `since`
            since = ts["seq"] - 2
            inc = await fetch_json(addr, f"/timeseries?since={since}")
            assert inc["count"] <= 2
            assert all(s["seq"] > since for s in inc["samples"])
            # the ring is bounded by window/interval
            assert srv.telemetry.samples.maxlen == srv.telemetry.capacity

            hot = await fetch_json(addr, "/hotgroups")
            assert hot["tracked"] == 1 and hot["k"] >= 1
            g = hot["groups"][0]
            assert g["commits"] >= 5 and g["share"] == 1.0
            assert str(leader.group_id) == g["group"]

            # explicit-request flight payload over the same endpoint
            fr = await fetch_json(addr, "/flightrecorder")
            assert fr["reason"] == "request"
            assert fr["samples"]
            assert fr["hot_groups"]["groups"]
        finally:
            await cluster.close()

    asyncio.run(body())


def test_sampler_survives_division_register_unregister_churn():
    """Mirrors PR 4's scrape-during-unregister race: sampling passes
    forced while groups register/unregister must never tear (no
    exception, every sample well-formed, per-group bookkeeping pruned)."""

    async def body():
        from ratis_tpu.protocol.group import RaftGroup
        from ratis_tpu.protocol.ids import RaftGroupId
        cluster = MiniCluster(3, properties=_tel_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            srv = cluster.servers[leader.member_id.peer_id]
            me = [p for p in cluster.group.peers
                  if p.id == leader.member_id.peer_id]

            async def churn():
                for _ in range(6):
                    g = RaftGroup.value_of(RaftGroupId.random_id(), me)
                    await srv.group_add(g)
                    await asyncio.sleep(0.01)
                    await srv.group_remove(g.group_id)

            task = asyncio.create_task(churn())
            while not task.done():
                s = srv.telemetry.sample()
                assert {"seq", "rates", "totals"} <= set(s)
                await asyncio.sleep(0.005)
            await task
            srv.telemetry.sample()
            # bookkeeping pruned back to the surviving leaderships
            leaders = sum(1 for d in srv.divisions.values()
                          if d.is_leader())
            assert srv.telemetry.tracked_groups <= leaders
        finally:
            await cluster.close()

    asyncio.run(body())


# -------------------------------------------- watchdog seq + /events?since

def test_watchdog_event_seq_and_incremental_events_route():
    async def body():
        from ratis_tpu.metrics.aggregate import fetch_json
        cluster = MiniCluster(3, properties=_tel_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            srv = cluster.servers[leader.member_id.peer_id]
            for i in range(4):
                srv.watchdog.emit("commit-stall", f"g{i}", f"synthetic {i}")
            assert [e["seq"] for e in srv.watchdog.events()] == [0, 1, 2, 3]
            assert srv.watchdog.last_seq == 3
            assert [e["seq"] for e in srv.watchdog.events(since=1)] == [2, 3]
            addr = srv.metrics_http.address
            payload = await fetch_json(addr, "/events?since=1")
            assert payload["seq"] == 3
            assert [e["seq"] for e in payload["events"]] == [2, 3]
            full = await fetch_json(addr, "/events")
            assert len(full["events"]) == 4
        finally:
            await cluster.close()

    asyncio.run(body())


# ------------------------------------------------ flight-recorder triggers

def test_commit_stall_dumps_flight_artifact(tmp_path):
    """Acceptance: an induced commit stall emits a flight-recorder dump
    containing >= 5 samples spanning the fault window with the stall
    event inside it."""
    from ratis_tpu.util import injection

    async def body():
        p = _tel_properties(tmp_path)
        p.set("raft.tpu.telemetry.interval", "50ms")
        cluster = MiniCluster(3, properties=p)
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            assert (await cluster.send_write()).success
            await asyncio.sleep(0.3)  # pre-fault samples in the ring
            srv = cluster.servers[leader.member_id.peer_id]
            lid = leader.member_id.peer_id
            for s in cluster.servers.values():
                s.engine.leadership_timeout_ms = 600_000
            gate = asyncio.Event()

            async def block(local_id, remote_id, *args):
                await gate.wait()

            injection.put(injection.APPEND_ENTRIES, block)
            injection.put(injection.REQUEST_VOTE, block)
            t_fault = asyncio.get_event_loop().time()
            wtask = asyncio.create_task(
                cluster.send(b"INCREMENT", server_id=lid, timeout=60.0))
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                if list(tmp_path.glob("flight-*.json")):
                    break
                await asyncio.sleep(0.1)
            dumps = list(tmp_path.glob("flight-*.json"))
            assert dumps, "no flight artifact written on commit stall"
            art = json.loads(dumps[0].read_text())
            assert art["reason"].startswith("watchdog-commit-stall")
            assert art["peer"] == str(lid) and art["pid"] == os.getpid()
            # >= 5 samples spanning the fault window: sampling continued
            # from before the fault through the detection
            assert len(art["samples"]) >= 5, len(art["samples"])
            stall = [e for e in art["events"]
                     if e["kind"] == "commit-stall"]
            assert stall and "seq" in stall[0]
            fault_wall = stall[0]["t"]
            ts = [s["t"] for s in art["samples"]]
            span = asyncio.get_event_loop().time() - t_fault
            assert min(ts) < fault_wall, "no samples precede the stall"
            assert max(ts) > fault_wall - span, \
                "samples stop before the fault window"
            # hot-group + rate history rode along
            assert art["hot_groups"]["groups"]
            assert all("rates" in s for s in art["samples"])

            gate.set()
            injection.clear()
            reply = await asyncio.wait_for(wtask, 60.0)
            assert reply.success
        finally:
            injection.clear()
            await cluster.close()

    asyncio.run(body())


def test_failing_chaos_scenario_attaches_flight(tmp_path):
    """Acceptance: a failing chaos scenario's replay artifact carries
    every server's flight window — >= 5 samples spanning the fault, with
    the paired injected-fault / fault-recovered events inside."""
    from ratis_tpu.chaos.cluster import ChaosCluster
    from ratis_tpu.chaos.scenarios import build_scenario
    from ratis_tpu.chaos.scenario import run_scenario

    async def main():
        cluster = ChaosCluster(3, 1)
        await cluster.start()
        try:
            # unmeetable acked floor -> deterministic failure AFTER the
            # faults healed and their recovery pairs journaled
            sc = build_scenario("partition_minority", 13,
                                {"convergence_s": 20.0, "recovery_s": 30.0,
                                 "min_acked": 10 ** 9})
            res = await run_scenario(cluster, sc,
                                     artifact_dir=str(tmp_path))
            assert not res.passed
            artifact = json.loads(
                (tmp_path / "chaos-partition_minority-seed13.json")
                .read_text())
            flights = artifact.get("flight")
            assert flights and len(flights) == 3, \
                "flight windows missing from replay artifact"
            injected = [e for e in artifact["journal"]
                        if e["kind"] == "injected-fault"]
            assert injected
            fault_wall = None
            for f in flights:
                kinds = {e["kind"] for e in f["events"]}
                assert "injected-fault" in kinds, kinds
                assert "fault-recovered" in kinds, kinds
                # pairing by fault id inside the flight window
                inj = {e["fault"] for e in f["events"]
                       if e["kind"] == "injected-fault"}
                rec = {e["fault"] for e in f["events"]
                       if e["kind"] == "fault-recovered"}
                assert inj <= rec, f"unpaired faults in flight: {inj - rec}"
                fault_wall = min(e["t"] for e in f["events"])
                assert len(f["samples"]) >= 5, len(f["samples"])
                ts = [s["t"] for s in f["samples"]]
                assert min(ts) <= fault_wall <= max(ts), \
                    "samples do not span the fault window"
        finally:
            await cluster.close()

    asyncio.run(main())


# ------------------------------------- partial-failure-tolerant scraping

def test_scrape_server_tolerates_single_route_failure():
    """One broken route (500) no longer poisons the whole server scrape;
    the proc reads degraded and shell health exits 1 without a
    traceback.  A fully dead endpoint still classifies unreachable."""

    async def body():
        from ratis_tpu.metrics.aggregate import (scrape_cluster,
                                                 scrape_server)
        from ratis_tpu.metrics.prometheus import MetricsHttpServer

        def boom():
            raise RuntimeError("injected route failure")

        server = MetricsHttpServer(json_routes={
            "/health": lambda: {"status": "ok", "peer": "sX", "pid": 1},
            "/divisions": boom,
            "/events": lambda: {"count": 0, "events": []},
        })
        await server.start()
        try:
            scrape = await scrape_server(server.address)
            assert scrape["health"]["peer"] == "sX"
            assert scrape["divisions"] == []
            assert "/divisions" in scrape["errors"]

            merged = await scrape_cluster([server.address])
            assert merged["servers"] == 1
            proc = next(iter(merged["procs"].values()))
            assert proc["status"] == "degraded"
            assert proc["routeErrors"]
            assert merged["healthy"] == 0

            # a dead endpoint is still an unreachable entry, not a raise
            merged2 = await scrape_cluster([server.address,
                                            "127.0.0.1:1"], timeout_s=3.0)
            assert len(merged2["unreachable"]) == 1
            assert merged2["unreachable"][0]["address"] == "127.0.0.1:1"
        finally:
            await server.close()

    asyncio.run(body())


def test_shell_health_reports_degraded_routes_exit_1(capsys):
    async def body():
        import argparse
        from ratis_tpu.metrics.prometheus import MetricsHttpServer
        from ratis_tpu.shell.cli import cmd_health

        def boom():
            raise RuntimeError("injected route failure")

        server = MetricsHttpServer(json_routes={
            "/health": boom,
            "/divisions": lambda: [],
            "/events": lambda: {"count": 0, "events": []},
        })
        await server.start()
        try:
            rc = await cmd_health(argparse.Namespace(
                endpoints=server.address, timeout=5.0, verbose=False))
            out = capsys.readouterr().out
            assert rc == 1
            assert "degraded" in out
        finally:
            await server.close()

    asyncio.run(body())


# ---------------------------------------------------- shell top rendering

def _top_child_script() -> str:
    """One child process: an in-process trio with telemetry on, a write
    loop, its leader's endpoint printed for the parent to scrape."""
    return """
import asyncio, sys
sys.path.insert(0, %r)
from minicluster import MiniCluster, fast_properties

async def main():
    p = fast_properties()
    p.set("raft.tpu.metrics.http-port", "0")
    p.set("raft.tpu.telemetry.enabled", "true")
    p.set("raft.tpu.telemetry.interval", "100ms")
    cluster = MiniCluster(3, properties=p)
    await cluster.start()
    leader = await cluster.wait_for_leader()
    srv = cluster.servers[leader.member_id.peer_id]
    print("ENDPOINT " + srv.metrics_http.address, flush=True)
    while True:
        await cluster.send_write()
        await asyncio.sleep(0.02)

asyncio.run(main())
""" % os.path.dirname(os.path.abspath(__file__))


@pytest.mark.mp
def test_shell_top_renders_rates_from_two_processes(capsys):
    """Acceptance: `shell top` renders live per-process rates from >= 2
    real processes (each child hosts its own cluster + write load)."""
    import subprocess

    async def body():
        import argparse
        from ratis_tpu.shell.cli import cmd_top
        procs = []
        endpoints = []
        try:
            for _ in range(2):
                proc = subprocess.Popen(
                    [sys.executable, "-c", _top_child_script()],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True)
                procs.append(proc)
            for proc in procs:
                line = proc.stdout.readline()
                assert line.startswith("ENDPOINT "), line
                endpoints.append(line.split()[1])
            await asyncio.sleep(1.0)  # let both samplers accumulate
            rc = await cmd_top(argparse.Namespace(
                endpoints=",".join(endpoints), interval=0.7,
                iterations=2, timeout=10.0))
            assert rc == 0
        finally:
            for proc in procs:
                proc.kill()
        out = capsys.readouterr().out
        pids = {str(p.pid) for p in procs}
        for pid in pids:
            assert pid in out, f"pid {pid} missing from top output:\n{out}"
        # per-process rate rows rendered, with a live commit rate on the
        # second refresh (computed from /timeseries counter deltas)
        assert "C/S" in out and "hot groups:" in out
        rows = [l for l in out.splitlines()
                if len(l.split()) >= 9 and l.split()[1] in pids]
        assert len(rows) >= 4  # 2 processes x 2 refreshes
        assert any(float(r.split()[2]) > 0 for r in rows[2:]), rows

    asyncio.run(body())


# ------------------------------------------- mp cross-process aggregation

@pytest.mark.mp
def test_multiproc_merged_timeseries_and_hotgroups():
    """Acceptance: the multi-process bench parent merges pid-keyed
    /timeseries + /hotgroups scrapes from every child into the rung
    result."""
    from ratis_tpu.tools.bench_cluster import run_multiproc_bench

    async def body():
        # enough writes that the fast-cadence child samplers observe
        # commit deltas MID-load (a 2-write burst can land entirely
        # between two samples and read as zero sketched load)
        return await run_multiproc_bench(
            8, 8, num_servers=3, transport="tcp", client_procs=2,
            concurrency=8, bringup_timeout_s=420.0, load_timeout_s=300.0,
            telemetry_interval="100ms")

    out = asyncio.run(body())
    assert out["commits"] == 64 and out["write_failures"] == 0
    ts = out["cluster_timeseries"]
    procs = ts["procs"]
    assert len(procs) == 3, procs
    assert all(pid.isdigit() for pid in procs), procs
    # every child sampled: pid-keyed series with a latest sample carrying
    # cumulative totals (>= 2 distinct pids is the acceptance floor)
    sampled = [p for p in procs.values() if p["count"] > 0]
    assert len(sampled) >= 2, procs
    assert all(p["last"]["totals"]["commits"] >= 0 for p in sampled)
    # cluster commit load visible in the merged hot-group accounting
    hot = ts["hotgroups"]
    assert hot["total_commits"] > 0
    assert hot["groups"] and hot["groups"][0]["commits"] > 0
