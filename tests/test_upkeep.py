"""Vectorized upkeep plane (PR 15, ``raft.tpu.upkeep.*``): packed
per-group deadline arrays replace the O(G) per-sweep Python walk over
``server.divisions``.  Covers the ops-layer scan against a scalar
reference, the slot/generation lifecycle guard, the thread-CPU scaling
claim (sweep cost sublinear in idle group count vs the legacy walk's
linear tax), the cache-expiry waterline's equivalence to the legacy
periodic walk on a randomized schedule, and live-cluster behavior in
array mode — including the hibernate-backstop force-due regression
(PR 1) that array mode must preserve."""

import asyncio
import random
import time
import types

import numpy as np

import pytest

from minicluster import MiniCluster, batched_properties, run_with_new_cluster
from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.ops import upkeep as ops
from ratis_tpu.ops.upkeep import (CH_CACHE, CH_HEARTBEAT, CH_HIBERNATE,
                                  CH_WATCH, CH_WINDOW, N_CHANNELS,
                                  NO_DEADLINE)
from ratis_tpu.server.upkeep import UpkeepPlane


@pytest.fixture(autouse=True, scope="module")
def _prewarm_kernels():
    # compile the batched kernels once up front: a cold jit stall mid-test
    # distorts the hibernation/backstop timing the cluster tests assert
    from ratis_tpu.engine.engine import QuorumEngine
    QuorumEngine(max_groups=1024, max_peers=8).prewarm(
        group_counts=(64,), event_counts=(64,))


# ------------------------------------------------------------- ops layer


def test_due_scan_matches_scalar_reference():
    """The vectorized scan returns exactly the slots the scalar oracle
    does, over randomized deadline fields (armed past, armed future,
    unarmed) at randomized probe times."""
    rng = random.Random(1507)
    for _ in range(50):
        cap = rng.choice((1, 7, 64, 257))
        deadlines = ops.new_deadlines(cap)
        for s in range(cap):
            for ch in range(N_CHANNELS):
                r = rng.random()
                if r < 0.4:
                    continue  # unarmed
                deadlines[s, ch] = rng.uniform(-10.0, 10.0)
        now = rng.uniform(-5.0, 5.0)
        slots = ops.due_scan(deadlines, now)
        assert list(slots) == ops.reference_due(deadlines, now)
        mask = ops.due_channels(deadlines, slots, now)
        for j, s in enumerate(slots):
            assert mask[j].any()
            for ch in range(N_CHANNELS):
                assert mask[j, ch] == (deadlines[s, ch] <= now)


def test_next_wake_is_min_armed_deadline():
    d = ops.new_deadlines(8)
    assert ops.next_wake(d) == NO_DEADLINE
    d[3, CH_CACHE] = 7.5
    d[5, CH_HEARTBEAT] = 2.25
    assert ops.next_wake(d) == 2.25


# ------------------------------------------------- slot lifecycle / guard


def _plane() -> UpkeepPlane:
    return UpkeepPlane(server=None, shard=0)


def test_slot_generation_guard_drops_stale_handles():
    """engine/ledger.py pattern: unregister bumps the generation, so a
    stale (slot, gen) handle held by a closed division can neither arm
    nor clear the slot's NEXT tenant."""
    plane = _plane()
    d1, d2 = types.SimpleNamespace(), types.SimpleNamespace()
    slot1, gen1 = plane.register(d1)
    plane.set_deadline(slot1, gen1, CH_HEARTBEAT, 1.0)
    assert plane.is_armed(slot1, gen1, CH_HEARTBEAT)
    plane.unregister(slot1, gen1)
    assert plane.registered == 0
    # slot is reused by the next registration with a NEW generation
    slot2, gen2 = plane.register(d2)
    assert slot2 == slot1 and gen2 != gen1
    assert plane.division_at(slot2) is d2
    # the fresh tenant starts fully unarmed (no deadline leak across gens)
    assert not (plane.deadlines[slot2] != NO_DEADLINE).any()
    # every stale-handle mutation is a no-op
    plane.set_deadline(slot1, gen1, CH_CACHE, 0.0)
    plane.clear(slot1, gen1, CH_CACHE)
    plane.mark_watch_dirty(slot1, gen1)
    assert not (plane.deadlines[slot2] != NO_DEADLINE).any()
    # double-unregister with the stale gen must not free the live slot
    plane.unregister(slot1, gen1)
    assert plane.registered == 1 and plane.division_at(slot2) is d2


def test_plane_grows_past_initial_capacity_preserving_deadlines():
    plane = _plane()
    handles = [plane.register(types.SimpleNamespace(idx=i))
               for i in range(300)]
    for i, (slot, gen) in enumerate(handles):
        plane.set_deadline(slot, gen, CH_HIBERNATE, float(i))
    assert plane.registered == 300
    for i, (slot, gen) in enumerate(handles):
        assert plane.division_at(slot).idx == i
        assert plane.deadlines[slot, CH_HIBERNATE] == float(i)
    slots, mask = plane.sweep(now=150.0)
    assert len(slots) == 151  # deadlines 0..150 are due
    assert mask[:, CH_HIBERNATE].all()


def test_watch_dirty_mark_and_idle_skip_accounting():
    plane = _plane()
    slot, gen = plane.register(types.SimpleNamespace())
    # nothing armed: the sweep is an idle skip
    slots, _ = plane.sweep(now=100.0)
    assert len(slots) == 0 and plane.idle_skips == 1 and plane.last_due == 0
    # an ack path marks the watch channel dirty -> due immediately
    plane.mark_watch_dirty(slot, gen)
    slots, mask = plane.sweep(now=100.0)
    assert list(slots) == [slot] and mask[0, CH_WATCH]
    assert plane.idle_skips == 1 and plane.last_due == 1
    plane.clear(slot, gen, CH_WATCH)
    slots, _ = plane.sweep(now=100.0)
    assert len(slots) == 0 and plane.idle_skips == 2


def test_row_min_stays_consistent_under_random_ops():
    """The maintained per-slot min vector (what the sweep actually scans)
    must equal deadlines.min(axis=1) after any interleaving of register /
    unregister / set / clear / dirty-mark / grow."""
    rng = random.Random(77)
    plane = _plane()
    handles = []
    for step in range(2000):
        op = rng.random()
        if op < 0.25 or not handles:
            handles.append(plane.register(types.SimpleNamespace()))
        elif op < 0.35:
            slot, gen = handles.pop(rng.randrange(len(handles)))
            plane.unregister(slot, gen)
        elif op < 0.7:
            slot, gen = handles[rng.randrange(len(handles))]
            plane.set_deadline(slot, gen, rng.randrange(N_CHANNELS),
                               rng.uniform(-5, 5))
        elif op < 0.9:
            slot, gen = handles[rng.randrange(len(handles))]
            plane.clear(slot, gen, rng.randrange(N_CHANNELS))
        else:
            slot, gen = handles[rng.randrange(len(handles))]
            plane.mark_watch_dirty(slot, gen)
    expect = plane.deadlines.min(axis=1)
    assert np.array_equal(plane.row_min, expect), \
        np.nonzero(plane.row_min != expect)
    now = rng.uniform(-5, 5)
    assert list(plane.sweep(now)[0]) == ops.reference_due(
        plane.deadlines, now)


# ------------------------------------------------------ sweep-cost scaling


def test_sweep_thread_cpu_sublinear_vs_legacy_walk():
    """The satellite claim measured directly: 16x more idle groups
    (64 -> 1024) multiplies the legacy walk's thread-CPU roughly
    linearly, while the plane's vectorized scan grows < 3x — and is
    absolutely cheaper at 1024 than walking 1024 divisions."""

    def _fleet(n):
        divs = {}
        for i in range(n):
            d = types.SimpleNamespace(leader_ctx=None)
            d.is_leader = lambda: False
            divs[i] = d
        return divs

    def _legacy_walk(divs):
        # the pre-PR-15 sweep body for an all-idle fleet: visit every
        # division just to discover there is nothing to do
        for div in list(divs.values()):
            if not div.is_leader() or div.leader_ctx is None:
                continue

    def _best_cpu(f, n=7, reps=300):
        best = None
        for _ in range(n):
            t0 = time.thread_time()
            for _ in range(reps):
                f()
            dt = time.thread_time() - t0
            best = dt if best is None else min(best, dt)
        return best

    costs = {}
    for n in (64, 1024):
        plane = _plane()
        for i in range(n):
            plane.register(types.SimpleNamespace(idx=i))
        divs = _fleet(n)
        # back-to-back on the same box, same clock, same rep count
        costs[n] = (_best_cpu(lambda: plane.sweep(1e9)),
                    _best_cpu(lambda: _legacy_walk(divs)))
    plane_ratio = costs[1024][0] / max(1e-9, costs[64][0])
    walk_ratio = costs[1024][1] / max(1e-9, costs[64][1])
    # 16x groups: the walk pays ~16x (allow noise down to 6x); the plane
    # scan must stay sublinear (< 3x) AND beat the walk outright at 1024
    assert walk_ratio > 6.0, (costs, walk_ratio)
    assert plane_ratio < 3.0, (costs, plane_ratio)
    assert costs[1024][0] < costs[1024][1], costs


# ------------------------------------------- cache-waterline equivalence


def test_cache_waterline_equivalent_to_periodic_walk(monkeypatch):
    """Satellite 2: drive TWO identical (RetryCache, WriteIndexCache)
    pairs through one randomized insert schedule on a fake clock — one
    swept by the legacy apply-loop cadence (every expiry/4), one by the
    CH_CACHE waterline (sweep only when the oldest entry expires, re-arm
    from next_expiry_s).  The live-entry sets must agree at every
    checkpoint, both must fully drain, and once drained the waterline
    does ZERO further work while the periodic walk keeps ticking."""
    from ratis_tpu.server import read as read_mod
    from ratis_tpu.server import retrycache as rc_mod
    from ratis_tpu.server.read import WriteIndexCache
    from ratis_tpu.server.retrycache import RetryCache

    clock = types.SimpleNamespace(now=1000.0)
    fake_time = types.SimpleNamespace(monotonic=lambda: clock.now)
    monkeypatch.setattr(rc_mod, "time", fake_time)
    monkeypatch.setattr(read_mod, "time", fake_time)

    async def body():
        rng = random.Random(1942)
        expiry = 8.0
        legacy = (RetryCache(expiry_s=expiry), WriteIndexCache(expiry))
        plane = (RetryCache(expiry_s=expiry), WriteIndexCache(expiry))

        def live_state(pair):
            rc, wic = pair
            now = clock.now
            return ({k for k, e in rc._map.items()
                     if not rc._expired(e, now)},
                    {c for c, (_, t) in wic._map.items()
                     if now - t <= expiry})

        def waterline(pair):
            return min(pair[0].next_expiry_s(), pair[1].next_expiry_s())

        legacy_sweeps = plane_sweeps = 0
        last_legacy_sweep = clock.now
        ch_cache = float("inf")  # CH_CACHE deadline (unarmed)
        for step in range(400):
            clock.now += rng.uniform(0.0, 1.5)
            if rng.random() < 0.5:
                cid = b"c%d" % rng.randrange(8)
                call = rng.randrange(1000)
                legacy[0].get_or_create(cid, call)
                plane[0].get_or_create(cid, call)
                legacy[1].put(cid, step)
                plane[1].put(cid, step)
                # Division.upkeep_arm_cache: arm only if unarmed
                if ch_cache == float("inf"):
                    ch_cache = waterline(plane)
            # legacy apply-loop slow tick
            if clock.now - last_legacy_sweep > expiry / 4:
                legacy[0].sweep()
                legacy[1].sweep(clock.now)
                legacy_sweeps += 1
                last_legacy_sweep = clock.now
            # plane sweep: only when the waterline fires
            if ch_cache <= clock.now:
                plane[0].sweep()
                plane[1].sweep(clock.now)
                plane_sweeps += 1
                ch_cache = waterline(plane)  # Division.sweep_caches re-arm
            assert live_state(legacy) == live_state(plane), step
        # drain: past the last possible expiry both must be empty
        clock.now += 2 * expiry
        legacy[0].sweep(), legacy[1].sweep(clock.now)
        if ch_cache <= clock.now:
            plane[0].sweep(), plane[1].sweep(clock.now)
            ch_cache = waterline(plane)
        assert not legacy[0]._map and not legacy[1]._map
        assert not plane[0]._map and not plane[1]._map
        assert plane_sweeps > 0
        assert ch_cache == float("inf")  # drained caches disarm
        # the idle claim: with no new entries the legacy cadence keeps
        # paying expiry/4 ticks forever; the disarmed waterline pays zero
        idle_legacy = idle_plane = 0
        for _ in range(40):
            clock.now += expiry / 4 + 0.01
            legacy[0].sweep(), legacy[1].sweep(clock.now)
            idle_legacy += 1
            if ch_cache <= clock.now:
                idle_plane += 1
        assert idle_legacy == 40 and idle_plane == 0

    asyncio.run(body())


# ----------------------------------------------------------- live cluster


def _upkeep_properties():
    p = batched_properties()
    p.set(RaftServerConfigKeys.Upkeep.ENABLED_KEY, "true")
    return p


def test_cluster_serves_writes_and_reads_in_array_mode():
    """Smoke + cost shape: a 3-peer cluster with the plane enabled serves
    writes/reads; every division holds a registered slot; follower
    servers' planes idle-skip nearly every sweep while only the leader's
    slot fires."""

    async def body(cluster: MiniCluster):
        for _ in range(5):
            assert (await cluster.send_write()).success
        assert (await cluster.send_read()).success
        leader = await cluster.wait_for_leader()
        await asyncio.sleep(0.5)
        for srv in cluster.servers.values():
            assert srv.upkeep, "array mode not active"
            pl = srv.upkeep[0]
            assert pl.registered == len(srv.divisions) == 1
            assert pl.sweeps > 0
            if srv.peer_id == leader.member_id.peer_id:
                # the leader's slot is due ~every sweep (ack-confirmed
                # heartbeat cadence), so idle skips stay rare
                assert pl.idle_skips < pl.sweeps
            else:
                # followers hold +inf on every channel: almost every
                # sweep is one vectorized compare and nothing else
                assert pl.idle_skips > pl.sweeps * 0.5, (
                    pl.idle_skips, pl.sweeps)

    run_with_new_cluster(3, body, properties=_upkeep_properties())


def test_division_close_unregisters_slot():
    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        srv = next(iter(cluster.servers.values()))
        pl = srv.upkeep[0]
        div = next(iter(srv.divisions.values()))
        slot, gen = div.upkeep_slot, div.upkeep_gen
        assert pl.registered == 1 and pl.division_at(slot) is div
        await div.close()
        assert pl.registered == 0 and pl.division_at(slot) is None
        assert int(pl.gen[slot]) != gen  # stale handles invalidated

    run_with_new_cluster(3, body, properties=_upkeep_properties())


def _hibernate_upkeep_properties(backstop="1s"):
    p = _upkeep_properties()
    p.set(RaftServerConfigKeys.Hibernate.ENABLED_KEY, "true")
    p.set(RaftServerConfigKeys.Hibernate.AFTER_SWEEPS_KEY, "2")
    p.set(RaftServerConfigKeys.Hibernate.BACKSTOP_KEY, backstop)
    return p


async def _wait_hibernated(cluster, timeout=20.0):
    await cluster.wait_for_leader()
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        for d in cluster.divisions():
            if d._hibernating:
                return d
        await asyncio.sleep(0.05)
    raise TimeoutError("group never hibernated")


def test_hibernate_backstop_force_due_under_array_mode():
    """PR 1 force-due regression, array-mode edition: while asleep the
    leader's CH_HEARTBEAT is cleared and only the CH_HIBERNATE backstop
    clock fires — and when it does, the dispatch must still force every
    appender due (``_last_send_s = 0``) so the hibernate-flagged refresh
    is actually SENT.  A healthy sleeping group therefore keeps
    refreshing its followers (heartbeat counters advance slowly) without
    elections, while the plane idle-skips nearly every sweep."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        term = leader.state.current_term
        srv = cluster.servers[leader.member_id.peer_id]
        pl = srv.upkeep[0]
        hb0 = sum(s.heartbeats.metrics["heartbeats"]
                  for s in cluster.servers.values())
        sweeps0, idle0 = pl.sweeps, pl.idle_skips
        await asyncio.sleep(2.5)  # >= 2 full backstop periods
        assert leader.is_leader() and leader._hibernating
        assert leader.state.current_term == term, \
            "backstop refresh triggered an election in a sleeping group"
        hb1 = sum(s.heartbeats.metrics["heartbeats"]
                  for s in cluster.servers.values())
        # the force-due fix is what makes these refreshes non-zero: the
        # due gate alone would decline every backstop dispatch
        assert hb1 > hb0, "no backstop refresh was sent while asleep"
        # ...but asleep means SLOW: far fewer sends than the awake
        # per-sweep cadence over the same window
        sweeps1, idle1 = pl.sweeps, pl.idle_skips
        assert hb1 - hb0 < (sweeps1 - sweeps0) * len(
            cluster.servers), (hb0, hb1, sweeps0, sweeps1)
        # the slot only wakes for the backstop clock: almost every sweep
        # on the leader's plane is an idle skip
        assert idle1 - idle0 > (sweeps1 - sweeps0) * 0.5, (
            idle0, idle1, sweeps0, sweeps1)

    run_with_new_cluster(3, body,
                         properties=_hibernate_upkeep_properties())


def test_dead_hibernated_leader_recovers_via_backstop_array_mode():
    """Dead-leader backstop under array mode: the refreshes stop with the
    leader, the followers' long deadlines lapse, and the group re-elects
    with zero client contact — then serves writes."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        await cluster.kill_server(leader.member_id.peer_id)
        deadline = asyncio.get_event_loop().time() + 12.0
        while asyncio.get_event_loop().time() < deadline:
            if any(d.is_leader() for d in cluster.divisions()):
                break
            await asyncio.sleep(0.05)
        assert any(d.is_leader() for d in cluster.divisions()), \
            "backstop never made the group electable again"
        assert (await cluster.send_write()).success

    run_with_new_cluster(
        3, body, properties=_hibernate_upkeep_properties("1500ms"))


def test_write_wakes_hibernated_group_array_mode():
    """Wake-on-contact re-arms CH_HEARTBEAT (upkeep_touch_heartbeat):
    after the wake the leader is back on the confirmed-contact heartbeat
    cadence and the write commits."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        assert (await cluster.send_write()).success
        assert not leader._hibernating or not leader.is_leader()
        # whoever leads now has CH_HEARTBEAT armed again (due-time finite)
        for d in cluster.divisions():
            if d.is_leader():
                pl = cluster.servers[d.member_id.peer_id].upkeep[0]
                assert pl.is_armed(d.upkeep_slot, d.upkeep_gen,
                                   CH_HEARTBEAT) \
                    or pl.is_armed(d.upkeep_slot, d.upkeep_gen,
                                   CH_HIBERNATE)
        assert (await cluster.send_read()).success

    run_with_new_cluster(3, body,
                         properties=_hibernate_upkeep_properties())
