"""Durable log + storage tests.

Mirrors the reference coverage of TestSegmentedRaftLog, TestRaftLogReadWrite,
TestRaftStorage and ServerRestartTests (ratis-test/.../segmented/,
ratis-server/src/test): segment round-trip, corrupt-tail recovery, truncate,
purge, metadata persistence, full-cluster restart with durable state.
"""

import asyncio
import pathlib

import pytest

from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import make_transaction_entry
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.server.log.segmented import (MAGIC, LogWorker,
                                            SegmentedRaftLog, read_records)
from ratis_tpu.server.storage import (RaftStorageDirectory, atomic_write,
                                      scan_group_dirs)
from tests.minicluster import MiniCluster


def entry(term, index, size=8):
    return make_transaction_entry(term, index, ClientId.random_id(), index,
                                  b"x" * size)


def run(coro):
    return asyncio.run(coro)


class TestSegmentedLog:
    def test_append_close_reopen(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w1"))
            await log.open()
            for i in range(10):
                await log.append_entry(entry(1, i))
            assert log.flush_index == 9
            await log.close()

            log2 = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w2"))
            await log2.open()
            assert log2.next_index == 10
            assert log2.get(5).term_index() == TermIndex(1, 5)
            assert log2.flush_index == 9
            await log2.close()

        run(body())

    def test_segment_rollover_and_recovery(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w"),
                                   segment_size_max=256)
            await log.open()
            for i in range(30):
                await log.append_entry(entry(1, i, size=32))
            await log.close()
            files = sorted(p.name for p in tmp_path.iterdir())
            closed = [f for f in files if f.startswith("log_") and
                      "inprogress" not in f]
            assert len(closed) >= 2, files

            log2 = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w2"))
            await log2.open()
            assert log2.next_index == 30
            assert all(log2.get(i) is not None for i in range(30))
            await log2.close()

        run(body())

    def test_corrupt_tail_truncated_on_recovery(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w"))
            await log.open()
            for i in range(5):
                await log.append_entry(entry(1, i))
            await log.close()
            # simulate a torn write: garbage appended to the open segment
            open_seg = next(p for p in tmp_path.iterdir()
                            if p.name.startswith("log_inprogress_"))
            with open(open_seg, "ab") as f:
                f.write(b"\x13\x37GARBAGE")

            log2 = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w2"))
            await log2.open()
            assert log2.next_index == 5  # garbage dropped, entries intact
            await log2.append_entry(entry(1, 5))  # and appendable again
            await log2.close()
            payloads, _ = read_records(open_seg)
            assert len(payloads) == 6

        run(body())

    def test_truncate_within_and_across_segments(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w"),
                                   segment_size_max=256)
            await log.open()
            for i in range(20):
                await log.append_entry(entry(1, i, size=32))
            await log.truncate(7)
            assert log.next_index == 7
            assert log.get(7) is None and log.get(6) is not None
            # appends continue with a different term (conflict resolution)
            for i in range(7, 12):
                await log.append_entry(entry(2, i))
            await log.close()

            log2 = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w2"))
            await log2.open()
            assert log2.next_index == 12
            assert log2.get(8).term == 2
            await log2.close()

        run(body())

    def test_purge_drops_whole_segments(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w"),
                                   segment_size_max=200)
            await log.open()
            for i in range(30):
                await log.append_entry(entry(1, i, size=32))
            before = len(list(tmp_path.iterdir()))
            await log.purge(15)
            after = len(list(tmp_path.iterdir()))
            assert after < before
            assert log.start_index > 0
            assert log.get(log.start_index) is not None
            assert log.next_index == 30
            await log.close()

        run(body())

    def test_shared_worker_batches_fsync(self, tmp_path):
        async def body():
            w = LogWorker("shared")
            log_a = SegmentedRaftLog("a", tmp_path / "a", worker=w)
            log_b = SegmentedRaftLog("b", tmp_path / "b", worker=w)
            await log_a.open()
            await log_b.open()
            await asyncio.gather(*(
                log.append_entry(entry(1, i))
                for log in (log_a, log_b) for i in [0]))
            await asyncio.gather(log_a.append_entry(entry(1, 1)),
                                 log_b.append_entry(entry(1, 1)))
            assert w.metrics["writes"] >= 4
            # batching: fewer flush rounds than writes
            assert w.metrics["flushes"] <= w.metrics["writes"]
            await log_a.close()
            await log_b.close()

        run(body())


class TestRaftStorageDirectory:
    def test_metadata_roundtrip(self, tmp_path):
        gid = RaftGroupId.random_id()
        sd = RaftStorageDirectory(tmp_path, gid)
        sd.format()
        assert sd.load_metadata() == (0, None)
        sd.persist_metadata(7, RaftPeerId.value_of("s1"))
        assert sd.load_metadata() == (7, RaftPeerId.value_of("s1"))
        assert scan_group_dirs(tmp_path) == [gid]

    def test_lock_reclaims_stale(self, tmp_path):
        gid = RaftGroupId.random_id()
        sd = RaftStorageDirectory(tmp_path, gid)
        sd.format()
        (sd.root / "in_use.lock").write_text("999999")  # dead pid
        sd.lock()  # reclaims
        sd2 = RaftStorageDirectory(tmp_path, gid)
        with pytest.raises(Exception, match="locked by live pid"):
            sd2.lock()
        sd.unlock()


class TestDurableCluster:
    def test_full_cluster_restart_preserves_state(self, tmp_path):
        async def body():
            cluster = MiniCluster(3, storage_root=str(tmp_path))
            await cluster.start()
            try:
                await cluster.wait_for_leader()
                for _ in range(5):
                    assert (await cluster.send_write()).success
                term_before = max(d.state.current_term
                                  for d in cluster.divisions())
                # stop all, restart all — state must come back from disk
                for pid in list(cluster.servers):
                    await cluster.kill_server(pid)
                for pid in list(cluster._stopped):
                    await cluster.restart_server(pid)
                leader = await cluster.wait_for_leader()
                assert leader.state.current_term >= term_before
                last = leader.state.log.get_last_committed_index()
                reply = await cluster.send_read()
                assert reply.message.content == b"5"
                assert (await cluster.send_write()).message.content == b"6"
            finally:
                await cluster.close()

        run(body())

    def test_votes_survive_restart(self, tmp_path):
        async def body():
            cluster = MiniCluster(3, storage_root=str(tmp_path))
            await cluster.start()
            try:
                leader = await cluster.wait_for_leader()
                fid = next(d.member_id.peer_id for d in cluster.divisions()
                           if not d.is_leader())
                term = leader.state.current_term
                await cluster.kill_server(fid)
                server = await cluster.restart_server(fid)
                div = server.divisions[cluster.group.group_id]
                # restarted follower remembers the term it acked
                assert div.state.current_term >= term - 1
            finally:
                await cluster.close()

        run(body())


class TestSnapshotBoundary:
    def test_empty_log_restarts_above_snapshot(self, tmp_path):
        """Review regression: snapshot at 100 + purged log must not restart
        the log at index 0."""
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w"))
            await log.open()
            log.set_snapshot_boundary(TermIndex(2, 100))
            assert log.next_index == 101
            assert log.start_index == 101
            assert log.get_last_entry_term_index() == TermIndex(2, 100)
            await log.append_entry(entry(2, 101))
            await log.close()

            log2 = SegmentedRaftLog("t", tmp_path, worker=LogWorker("w2"))
            await log2.open()
            assert log2.get(101) is not None
            await log2.close()

        run(body())


def test_log_factory_with_durable_storage_rejected(tmp_path):
    """Review regression: volatile injected log + durable metadata would lose
    acked entries across restarts — the combination must be refused."""
    from ratis_tpu.server.log.memory import MemoryRaftLog

    async def body():
        cluster = MiniCluster(1, storage_root=str(tmp_path),
                              log_factory=lambda s, g: MemoryRaftLog())
        with pytest.raises(ValueError, match="log_factory cannot be combined"):
            await cluster.start()
        await cluster.close()

    run(body())


class TestDecoupledFlush:
    def test_leader_append_returns_before_flush(self, tmp_path):
        """wait_flush=False returns after the in-memory append; flush_index
        catches up from the worker and fires the flush callback."""

        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("wd1"))
            flushed = []
            log.set_flush_callbacks(flushed.append, lambda e: None)
            await log.open()
            for i in range(5):
                await log.append_entry(entry(1, i), wait_flush=False)
            assert log.next_index == 5  # appended in memory
            deadline = asyncio.get_event_loop().time() + 5.0
            while log.flush_index < 4:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.01)
            assert flushed[-1] == 4
            await log.close()

        run(body())

    def test_failed_write_latches_log_dead(self, tmp_path, monkeypatch):
        """A failed fsync must latch the log: flush_index never advances past
        the hole even when LATER batches succeed, the error callback fires
        once, and further appends are refused (reference log worker
        terminates on IO failure)."""
        from ratis_tpu.protocol.exceptions import RaftLogIOException
        from ratis_tpu.server.log import segmented as seg_mod

        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("wd2"))
            errors = []
            log.set_flush_callbacks(lambda i: None, errors.append)
            await log.open()
            await log.append_entry(entry(1, 0))
            assert log.flush_index == 0

            real_fsync = seg_mod.os.fsync
            fail = {"on": True}

            def flaky_fsync(fd):
                if fail["on"]:
                    raise OSError(28, "No space left on device")
                real_fsync(fd)

            monkeypatch.setattr(seg_mod.os, "fsync", flaky_fsync)
            await log.append_entry(entry(1, 1), wait_flush=False)
            # let the failing batch complete
            deadline = asyncio.get_event_loop().time() + 5.0
            while not errors:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.01)
            fail["on"] = False  # disk "recovers" — must make no difference
            with pytest.raises(RaftLogIOException):
                await log.append_entry(entry(1, 2))
            assert log.flush_index == 0  # never advanced past the hole
            assert len(errors) == 1
            monkeypatch.setattr(seg_mod.os, "fsync", real_fsync)
            await log.close()

        run(body())


class TestCacheEviction:
    """SegmentedRaftLogCache parity (SegmentedRaftLogCache.java): closed
    segments past the cache budget drop their payloads once applied; reads
    below the eviction line come back through the file."""

    def test_evict_and_read_through(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("we"),
                                   segment_size_max=256,
                                   cache_segments_max=2)
            await log.open()
            for i in range(40):
                await log.append_entry(entry(1, i, size=32))
            closed = [s for s in log._segments if not s.is_open]
            assert len(closed) > 3  # several closed segments exist
            assert log.evict_cache(applied_index=-1) == 0  # nothing applied
            evicted = log.evict_cache(applied_index=39)
            assert evicted == len(closed) - 2
            assert log.cached_segments == 2
            # metadata stays resident: term/prev checks never fault
            assert log.get_term_index(1) == TermIndex(1, 1)
            # payload reads fault the segment in from disk
            e = log.get(1)
            assert e is not None and e.index == 1
            assert log.metrics.cache_miss_count.count >= 1
            # sequential scan (a lagging follower's catch-up batch) is served
            # from the single-slot read-through cache after the first miss
            first_seg = next(s for s in log._segments if not s.cached)
            entries = log.get_entries(first_seg.start, first_seg.end + 1)
            assert [e.index for e in entries] == list(
                range(first_seg.start, first_seg.end + 1))
            await log.close()

        run(body())

    def test_truncate_into_evicted_segment(self, tmp_path):
        async def body():
            log = SegmentedRaftLog("t", tmp_path, worker=LogWorker("wt"),
                                   segment_size_max=256,
                                   cache_segments_max=0)
            await log.open()
            for i in range(40):
                await log.append_entry(entry(1, i, size=32))
            log.evict_cache(applied_index=39)
            assert log.cached_segments == 0
            # truncate into an evicted segment: reloads, rewrites, stays open
            target = next(s for s in log._segments if not s.is_open)
            cut = target.start + 1
            await log.truncate(cut)
            assert log.next_index == cut
            for i in range(cut, cut + 3):
                await log.append_entry(entry(2, i, size=32))
            assert log.get(cut).term == 2
            assert log.get(cut - 1).term == 1
            await log.close()

        run(body())

    def test_lagging_follower_served_from_disk(self, tmp_path):
        """Cluster-level: a killed follower catches up from a leader whose
        log entries were evicted from memory (reads come through the file,
        not the snapshot path)."""

        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            follower = next(d for d in cluster.divisions()
                            if not d.is_leader())
            fid = follower.member_id.peer_id
            await cluster.kill_server(fid)
            for _ in range(40):
                assert (await cluster.send_write()).success
            for d in cluster.divisions():
                d.state.log.evict_cache(d.applied_index)
                assert d.state.log.cached_segments <= 1
            await cluster.restart_server(fid)
            new_div = cluster.servers[fid].divisions[cluster.group.group_id]
            last = (await cluster.wait_for_leader()).state.log \
                .get_last_committed_index()
            await cluster.wait_applied(last, divisions=[new_div],
                                       timeout=20.0)
            assert new_div.state_machine.counter == 40

        from minicluster import run_with_new_cluster
        from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
        from tests.minicluster import fast_properties
        p = fast_properties()
        RaftServerConfigKeys.Log.set_use_memory(p, False)
        p.set(RaftServerConfigKeys.Log.SEGMENT_SIZE_MAX_KEY, "512")
        p.set(RaftServerConfigKeys.Log.SEGMENT_CACHE_NUM_MAX_KEY, "1")
        run_with_new_cluster(3, body, properties=p,
                             storage_root=str(tmp_path))
