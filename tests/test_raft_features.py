"""Retry cache, watch, linearizable reads, snapshots.

Mirrors the reference suites RetryCacheTests, WatchRequestTests,
LinearizableReadTests and RaftSnapshotBaseTest
(ratis-server/src/test/.../).
"""

import asyncio

import pytest

from ratis_tpu.conf import RaftServerConfigKeys
from ratis_tpu.protocol.exceptions import NotReplicatedException
from ratis_tpu.protocol.requests import (ReplicationLevel, read_request_type,
                                         stale_read_request_type,
                                         watch_request_type)
from tests.minicluster import MiniCluster, fast_properties, run_with_new_cluster


class TestRetryCache:
    def test_same_call_id_executes_once(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            r1 = await cluster.send(b"INCREMENT", call_id=777)
            r2 = await cluster.send(b"INCREMENT", call_id=777)  # retry
            assert r1.success and r2.success
            assert r1.message.content == r2.message.content == b"1"
            assert r1.log_index == r2.log_index
            r3 = await cluster.send(b"INCREMENT", call_id=778)
            assert r3.message.content == b"2"

        run_with_new_cluster(3, body)

    def test_retry_after_failover_is_deduped(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            r1 = await cluster.send(b"INCREMENT", call_id=500)
            assert r1.success and r1.message.content == b"1"
            # make sure all peers applied (and thus populated their caches)
            await cluster.wait_applied(r1.log_index)
            await cluster.kill_server(leader.member_id.peer_id)
            await cluster.wait_for_leader()
            # the same call retried against the NEW leader must not re-execute
            r2 = await cluster.send(b"INCREMENT", call_id=500)
            assert r2.success
            assert r2.message.content == b"1", r2.message
            read = await cluster.send_read()
            assert read.message.content == b"1"

        run_with_new_cluster(3, body)


class TestWatch:
    def test_watch_majority_and_all(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            w = await cluster.send_write()
            idx = w.log_index
            for level in (ReplicationLevel.MAJORITY, ReplicationLevel.ALL,
                          ReplicationLevel.MAJORITY_COMMITTED,
                          ReplicationLevel.ALL_COMMITTED):
                reply = await cluster.send(b"", watch_request_type(idx, level))
                assert reply.success, (level, reply)
                assert reply.log_index >= idx

        run_with_new_cluster(3, body)

    def test_watch_all_blocked_follower_times_out(self):
        async def body(cluster: MiniCluster):
            p = cluster.properties
            leader = await cluster.wait_for_leader()
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            cluster.network.block(leader.member_id.peer_id,
                                  follower.member_id.peer_id)
            w = await cluster.send_write()
            # MAJORITY watch passes (2/3 alive)...
            ok = await cluster.send(b"", watch_request_type(
                w.log_index, ReplicationLevel.MAJORITY))
            assert ok.success
            # ...ALL_COMMITTED cannot while one follower is dark
            reply = await cluster.send(b"", watch_request_type(
                w.log_index, ReplicationLevel.ALL_COMMITTED))
            assert not reply.success
            assert isinstance(reply.exception, NotReplicatedException)
            assert reply.exception.replication == ReplicationLevel.ALL_COMMITTED
            cluster.network.unblock_all()

        props = fast_properties()
        props.set("raft.server.watch.timeout", "700ms")
        run_with_new_cluster(3, body, properties=props)


class TestLinearizableRead:
    def _props(self, lease: bool = False):
        p = fast_properties()
        p.set(RaftServerConfigKeys.Read.OPTION_KEY, "LINEARIZABLE")
        if lease:
            p.set_boolean(RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_KEY, True)
        return p

    def test_leader_linearizable_read(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            for i in range(1, 4):
                await cluster.send_write()
            r = await cluster.send_read()
            assert r.success and r.message.content == b"3"

        run_with_new_cluster(3, body, properties=self._props())

    def test_follower_serves_linearizable_read_via_read_index(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            await cluster.send_write()
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            r = await cluster.send(b"GET", read_request_type(),
                                   server_id=follower.member_id.peer_id)
            assert r.success and r.message.content == b"1"
            # served by the follower itself, not redirected:
            assert r.server_id == follower.member_id.peer_id

        run_with_new_cluster(3, body, properties=self._props())

    def test_lease_read(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            await cluster.send_write()
            r = await cluster.send_read()
            assert r.success and r.message.content == b"1"

        run_with_new_cluster(3, body, properties=self._props(lease=True))

    def test_stale_read_from_follower(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            w = await cluster.send_write()
            await cluster.wait_applied(w.log_index)
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            r = await cluster.send(b"GET",
                                   stale_read_request_type(w.log_index),
                                   server_id=follower.member_id.peer_id)
            assert r.success and r.message.content == b"1"

        run_with_new_cluster(3, body)


class TestSnapshot:
    def _props(self, threshold=5):
        p = fast_properties()
        p.set_boolean(RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_ENABLED_KEY, True)
        p.set_int(RaftServerConfigKeys.Snapshot.AUTO_TRIGGER_THRESHOLD_KEY,
                  threshold)
        return p

    def test_auto_snapshot_and_purge(self, tmp_path):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            for _ in range(12):
                assert (await cluster.send_write()).success
            # leader should have snapshotted and purged its log
            deadline = asyncio.get_event_loop().time() + 5
            leader = cluster.leaders()[0]
            while asyncio.get_event_loop().time() < deadline:
                if leader.state_machine.get_latest_snapshot() is not None \
                        and leader.state.log.start_index > 0:
                    break
                await asyncio.sleep(0.05)
            snap = leader.state_machine.get_latest_snapshot()
            assert snap is not None and snap.index >= 5
            assert leader.state.log.start_index > 0

        async def main():
            cluster = MiniCluster(3, properties=self._props(),
                                  storage_root=str(tmp_path))
            await cluster.start()
            try:
                await body(cluster)
            finally:
                await cluster.close()

        asyncio.run(main())

    def test_lagging_follower_gets_snapshot_install(self, tmp_path):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            fid = follower.member_id.peer_id
            await cluster.kill_server(fid)
            for _ in range(12):
                assert (await cluster.send_write()).success
            leader = cluster.leaders()[0]
            await leader.take_snapshot_async()
            assert leader.state.log.start_index > 0
            # restart the follower: it is behind the purged log, must get
            # the snapshot installed
            await cluster.restart_server(fid)
            div = cluster.servers[fid].divisions[cluster.group.group_id]
            deadline = asyncio.get_event_loop().time() + 8
            while asyncio.get_event_loop().time() < deadline:
                if div.state_machine.counter == 12:
                    break
                await asyncio.sleep(0.05)
            assert div.state_machine.counter == 12, div.state_machine.counter
            snap = div.state_machine.get_latest_snapshot()
            assert snap is not None

        async def main():
            cluster = MiniCluster(3, storage_root=str(tmp_path))
            await cluster.start()
            try:
                await body(cluster)
            finally:
                await cluster.close()

        asyncio.run(main())

    def test_restart_from_snapshot(self, tmp_path):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            for _ in range(8):
                assert (await cluster.send_write()).success
            for d in cluster.divisions():
                await cluster.wait_applied(7, divisions=[d])
            for d in cluster.divisions():
                await d.take_snapshot_async()
            for pid in list(cluster.servers):
                await cluster.kill_server(pid)
            for pid in list(cluster._stopped):
                await cluster.restart_server(pid)
            await cluster.wait_for_leader()
            r = await cluster.send_read()
            assert r.message.content == b"8"
            assert (await cluster.send_write()).message.content == b"9"

        async def main():
            cluster = MiniCluster(3, storage_root=str(tmp_path))
            await cluster.start()
            try:
                await body(cluster)
            finally:
                await cluster.close()

        asyncio.run(main())


def test_follower_commit_capped_at_verified_frontier():
    """Raft §5.3: a follower advances commitIndex only to min(leaderCommit,
    last index THIS request verified). A heartbeat with a high leaderCommit
    must not commit a stale uncommitted tail from an old term — doing so
    commits entries the current leader is about to truncate (regression:
    chaos suite wedged a follower on 'conflict at committed index')."""
    from ratis_tpu.protocol.ids import ClientId
    from ratis_tpu.protocol.logentry import make_transaction_entry
    from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest,
                                            AppendResult, RaftRpcHeader)

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        follower = next(d for d in cluster.divisions() if not d.is_leader())
        # Freeze real traffic into the chosen follower so the crafted
        # requests fully control its log.
        for d in cluster.divisions():
            if d is not follower:
                cluster.network.block(d.member_id.peer_id,
                                      follower.member_id.peer_id)
        await asyncio.sleep(0.05)
        cid = ClientId.random_id().to_bytes()
        term1 = follower.state.current_term + 1
        base = follower.state.log.next_index
        hdr = RaftRpcHeader(leader.member_id.peer_id,
                            follower.member_id.peer_id, cluster.group.group_id)

        def entries(term, start, n):
            return tuple(make_transaction_entry(term, start + i, cid, start + i,
                                                b"x") for i in range(n))

        prev = follower.state.log.get_last_entry_term_index()
        # stale tail: entries at term1 that will never commit
        stale = entries(term1, base, 3)
        reply = await follower.handle_append_entries(AppendEntriesRequest(
            hdr, term1, prev, stale, leader_commit=base - 1))
        assert reply.result == AppendResult.SUCCESS
        committed_before = follower.state.log.get_last_committed_index()

        # new term: heartbeat verifying only up to prev (below the stale
        # tail) but advertising a commit beyond it
        term2 = term1 + 1
        reply = await follower.handle_append_entries(AppendEntriesRequest(
            hdr, term2, prev, (), leader_commit=base + 2))
        assert reply.result == AppendResult.SUCCESS
        after = follower.state.log.get_last_committed_index()
        assert after <= max(committed_before, prev.index if prev else -1), (
            f"follower committed unverified stale tail: {after}")

        # the new leader's conflicting entries truncate-and-append cleanly
        fresh = entries(term2, base, 3)
        reply = await follower.handle_append_entries(AppendEntriesRequest(
            hdr, term2, prev, fresh, leader_commit=base + 2))
        assert reply.result == AppendResult.SUCCESS
        assert follower.state.log.get_term_index(base).term == term2
        assert follower.state.log.get_last_committed_index() == base + 2
        cluster.network.unblock_all()

    run_with_new_cluster(3, body)
