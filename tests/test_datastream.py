"""DataStream tests (reference ratis-test datastream suites +
TestNettyDataStream*: framing, routing, stream-write-link end to end)."""

import asyncio

import msgpack
import pytest

from ratis_tpu.models.filestore import FileStoreStateMachine
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.routing import RoutingTable
from ratis_tpu.transport.datastream import (FLAG_CLOSE, FLAG_PRIMARY,
                                            FLAG_SYNC, KIND_DATA,
                                            KIND_HEADER, Packet,
                                            encode_packet, read_packet)
from tests.minicluster import run_with_new_cluster


def _pid(s):
    return RaftPeerId.value_of(s)


def test_packet_roundtrip():
    async def _run():
        p = Packet(KIND_DATA, 12345, 678, FLAG_SYNC | FLAG_CLOSE, b"payload")
        reader = asyncio.StreamReader()
        reader.feed_data(encode_packet(p))
        reader.feed_eof()
        q = await read_packet(reader)
        assert q == p
        assert q.is_sync and q.is_close
        assert await read_packet(reader) is None  # clean EOF

    asyncio.run(_run())


def test_packet_truncation_raises():
    async def _run():
        p = Packet(KIND_HEADER, 1, 0, FLAG_PRIMARY, b"x" * 100)
        raw = encode_packet(p)
        reader = asyncio.StreamReader()
        reader.feed_data(raw[:len(raw) - 5])
        reader.feed_eof()
        with pytest.raises((ConnectionError, asyncio.IncompleteReadError)):
            await read_packet(reader)

    asyncio.run(_run())


def test_routing_table_shapes():
    a, b, c = _pid("a"), _pid("b"), _pid("c")
    chain = RoutingTable.chain([a, b, c])
    assert chain.get_successors(a) == (b,)
    assert chain.get_successors(b) == (c,)
    assert chain.get_successors(c) == ()
    star = RoutingTable.star(a, [b, c])
    assert set(star.get_successors(a)) == {b, c}
    rt = (RoutingTable.Builder().add_successor(a, b)
          .add_successor(a, c).build())
    assert rt.get_successors(a) == (b, c)
    # wire round trip
    assert RoutingTable.from_dict(rt.to_dict()) == rt


def _stream_cmd(path):
    return msgpack.packb({"op": "stream", "path": path}, use_bin_type=True)


def test_filestore_stream_end_to_end():
    """1MB streamed in 64KB packets lands identically on every peer."""

    async def _test(cluster):
        await cluster.wait_for_leader()
        payload = bytes((i * 31) % 256 for i in range(1 << 20))
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(_stream_cmd("big.bin"))
            for i in range(0, len(payload), 64 << 10):
                await out.write_async(payload[i:i + (64 << 10)])
            reply = await out.close_async()
            assert reply.success, reply.exception
            result = msgpack.unpackb(reply.message.content, raw=False)
            assert result == {"ok": True, "size": len(payload)}

            # read back through a linearizable query
            read = await client.io().send_read_only(
                msgpack.packb({"op": "read", "path": "big.bin"},
                              use_bin_type=True))
            data = msgpack.unpackb(read.message.content, raw=False)["data"]
            assert data == payload

            await cluster.wait_applied(reply.log_index)
        # every peer that received the stream has the identical file
        found = 0
        for div in cluster.divisions():
            sm = div.state_machine
            target = sm.resolve("big.bin")
            if target.exists():
                assert target.read_bytes() == payload
                found += 1
        assert found == len(cluster.divisions())  # star routing reaches all
        # stream metrics observed the traffic (NettyServerStreamRpcMetrics
        # analog): bytes counted on the primary, stream opened and closed
        m = [s.datastream.metrics for s in cluster.servers.values()
             if s.datastream is not None]
        assert sum(x.bytes_written.count for x in m) >= len(payload)
        assert sum(x.streams_started.count for x in m) >= 1
        assert sum(x.streams_closed.count for x in m) >= 1
        assert all(x.num_failed.count == 0 for x in m)

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


def test_filestore_stream_via_follower_primary():
    """Streaming to a non-leader primary still commits (forward to leader)."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        follower = next(d for d in cluster.divisions() if d.is_follower())
        follower_peer = cluster.group.get_peer(follower.member_id.peer_id)
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(
                _stream_cmd("via-follower.bin"), primary=follower_peer)
            await out.write_async(b"hello " * 1000)
            reply = await out.close_async()
            assert reply.success, reply.exception

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


def test_filestore_chain_routing():
    """Chain topology: primary -> f1 -> f2; all peers get the bytes."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        order = [leader.member_id.peer_id] + \
            [d.member_id.peer_id for d in cluster.divisions()
             if d.member_id.peer_id != leader.member_id.peer_id]
        rt = RoutingTable.chain(order)
        leader_peer = cluster.group.get_peer(order[0])
        payload = b"chained-data" * 5000
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(
                _stream_cmd("chain.bin"), routing_table=rt,
                primary=leader_peer)
            await out.write_async(payload)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            target = div.state_machine.resolve("chain.bin")
            assert target.exists()
            assert target.read_bytes() == payload

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


def test_filestore_empty_routing_defaults_to_fanout():
    """An explicitly empty RoutingTable means 'primary fans out to all'."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        leader_peer = cluster.group.get_peer(leader.member_id.peer_id)
        payload = b"fanout" * 10000
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(
                _stream_cmd("fanout.bin"), routing_table=RoutingTable(),
                primary=leader_peer)
            await out.write_async(payload)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            assert div.state_machine.resolve("fanout.bin").read_bytes() \
                == payload

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


def test_filestore_write_read_delete():
    """Small files through the ordinary log path."""

    async def _test(cluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            w = await client.io().send(msgpack.packb(
                {"op": "write", "path": "small.txt", "data": b"contents"},
                use_bin_type=True))
            assert w.success
            ls = await client.io().send_read_only(
                msgpack.packb({"op": "list"}, use_bin_type=True))
            assert msgpack.unpackb(ls.message.content,
                                   raw=False)["files"] == ["small.txt"]
            d = await client.io().send(msgpack.packb(
                {"op": "delete", "path": "small.txt"}, use_bin_type=True))
            assert d.success
            ls = await client.io().send_read_only(
                msgpack.packb({"op": "list"}, use_bin_type=True))
            assert msgpack.unpackb(ls.message.content,
                                   raw=False)["files"] == []

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


def test_filestore_rejects_unsafe_paths():
    async def _test(cluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for bad in ("../escape", "/abs/path", ""):
                reply = await client.io().send(msgpack.packb(
                    {"op": "write", "path": bad, "data": b"x"},
                    use_bin_type=True))
                assert not reply.success

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine)


class LinkRecordingFileStore(FileStoreStateMachine):
    """Records data_link(None, ...) calls — the missing-stream repair hook."""

    def __init__(self):
        super().__init__()
        self.null_link_indices: list[int] = []

    async def data_link(self, stream, entry):
        if stream is None:
            self.null_link_indices.append(entry.index)
        await super().data_link(stream, entry)


def test_peer_outside_routing_table_gets_null_link():
    """A replica that never received the stream still gets
    data_link(None, entry) at apply so it can detect/repair the miss
    (reference DataStreamManagement passes a null stream)."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        others = [d.member_id.peer_id for d in cluster.divisions()
                  if d.member_id.peer_id != leader.member_id.peer_id]
        # route only leader -> others[0]; others[1] is outside the table
        rt = RoutingTable.chain([leader.member_id.peer_id, others[0]])
        leader_peer = cluster.group.get_peer(leader.member_id.peer_id)
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(
                _stream_cmd("partial.bin"), routing_table=rt,
                primary=leader_peer)
            await out.write_async(b"x" * 4096)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            sm = div.state_machine
            if div.member_id.peer_id == others[1]:
                assert reply.log_index in sm.null_link_indices
            else:
                assert reply.log_index not in sm.null_link_indices

    run_with_new_cluster(3, _test, sm_factory=LinkRecordingFileStore)


def test_datastream_tls_end_to_end(tmp_path):
    """DataStream over TLS (NettyConfigKeys.DataStreamTls; the reference's
    NettyServerStreamRpc takes its own TlsConfig): a streamed file lands on
    every peer with all stream legs (client->primary, primary->successor)
    riding TLS sockets, and a plaintext stream client cannot connect."""
    import subprocess

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True)

    from ratis_tpu.conf.keys import NettyConfigKeys
    from tests.minicluster import fast_properties

    p = fast_properties()
    p.set(NettyConfigKeys.DataStreamTls.ENABLED_KEY, "true")
    p.set(NettyConfigKeys.DataStreamTls.CERT_CHAIN_KEY, str(cert))
    p.set(NettyConfigKeys.DataStreamTls.PRIVATE_KEY_KEY, str(key))
    p.set(NettyConfigKeys.DataStreamTls.TRUST_ROOT_KEY, str(cert))

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        payload = bytes((i * 7) % 256 for i in range(1 << 16))
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(_stream_cmd("tls.bin"))
            await out.write_async(payload)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            target = div.state_machine.resolve("tls.bin")
            assert target.exists() and target.read_bytes() == payload

        # plaintext connection against the TLS stream port must fail
        from ratis_tpu.transport.datastream import DataStreamConnection
        srv = cluster.servers[leader.member_id.peer_id]
        addr = srv.datastream.transport.address
        plain = DataStreamConnection(addr)
        try:
            await plain.connect()
            # TLS handshake failure may surface on first send instead
            from ratis_tpu.transport.datastream import (FLAG_PRIMARY,
                                                        KIND_HEADER, Packet)
            fut = await plain.send(Packet(KIND_HEADER, 1, 0, FLAG_PRIMARY,
                                          b""))
            await asyncio.wait_for(fut, 2.0)
            raise AssertionError("plaintext stream spoke to TLS endpoint")
        except (ConnectionError, OSError, asyncio.TimeoutError,
                asyncio.IncompleteReadError):
            pass
        finally:
            try:
                await plain.close()
            except Exception:
                pass

    run_with_new_cluster(3, _test, sm_factory=FileStoreStateMachine,
                         properties=p)
