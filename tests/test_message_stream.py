"""MessageStream tests (reference MessageStreamApi: MessageStreamImpl +
MessageStreamRequests; RaftServerImpl.messageStreamAsync:1111)."""

import pytest

from ratis_tpu.protocol.exceptions import StreamException
from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.requests import (RaftClientRequest,
                                         message_stream_request_type)
from ratis_tpu.server.messagestream import MessageStreamRequests
from tests.minicluster import run_with_new_cluster
from tests.statemachines import RecordingStateMachine


def _req(client_id, stream_id, message_id, eor, payload=b"x"):
    return RaftClientRequest(
        client_id, RaftPeerId.value_of("s0"), RaftGroupId.random_id(),
        call_id=message_id, message=Message(payload),
        type=message_stream_request_type(stream_id, message_id, eor))


def test_accumulator_assembles_in_order():
    msr = MessageStreamRequests()
    cid = ClientId.random_id()
    msr.stream_async(_req(cid, 1, 0, False, b"aa"))
    msr.stream_async(_req(cid, 1, 1, False, b"bb"))
    write = msr.stream_end_of_request_async(_req(cid, 1, 2, True, b"cc"))
    assert write.message.content == b"aabbcc"
    assert write.is_write()
    assert len(msr) == 0  # stream retired


def test_accumulator_rejects_out_of_order():
    msr = MessageStreamRequests()
    cid = ClientId.random_id()
    msr.stream_async(_req(cid, 7, 0, False))
    with pytest.raises(StreamException):
        msr.stream_async(_req(cid, 7, 2, False))
    # stream dropped: restart from 0 works
    msr.stream_async(_req(cid, 7, 0, False, b"z"))
    write = msr.stream_end_of_request_async(_req(cid, 7, 1, True, b"!"))
    assert write.message.content == b"z!"


def test_accumulator_byte_limit():
    msr = MessageStreamRequests(byte_limit=10)
    cid = ClientId.random_id()
    with pytest.raises(StreamException):
        msr.stream_async(_req(cid, 1, 0, False, b"x" * 11))
    assert len(msr) == 0


def test_duplicate_chunk_is_acked_noop():
    """A re-sent chunk (lost reply) must not abort the stream."""
    msr = MessageStreamRequests()
    cid = ClientId.random_id()
    msr.stream_async(_req(cid, 1, 0, False, b"aa"))
    msr.stream_async(_req(cid, 1, 0, False, b"aa"))  # client retry
    msr.stream_async(_req(cid, 1, 1, False, b"bb"))
    write = msr.stream_end_of_request_async(_req(cid, 1, 2, True, b"cc"))
    assert write.message.content == b"aabbcc"


def test_retried_end_of_request_returns_retired():
    msr = MessageStreamRequests()
    cid = ClientId.random_id()
    msr.stream_async(_req(cid, 1, 0, False, b"aa"))
    final = _req(cid, 1, 1, True, b"bb")
    write = msr.stream_end_of_request_async(final)
    assert write.message.content == b"aabb"
    # retry of the same end-of-request: caller must consult the retry cache
    assert msr.stream_end_of_request_async(final) is msr.RETIRED
    # while a different (never-seen) stream's late final chunk still fails
    with pytest.raises(StreamException):
        msr.stream_end_of_request_async(_req(cid, 9, 3, True, b"zz"))


def test_byte_accounting_stays_exact():
    msr = MessageStreamRequests(byte_limit=100)
    cid = ClientId.random_id()
    for round_no in range(5):  # a leaky account would go negative and
        sid = round_no + 1     # stop enforcing the limit
        msr.stream_async(_req(cid, sid, 0, False, b"x" * 40))
        msr.stream_end_of_request_async(_req(cid, sid, 1, True, b"y" * 40))
        assert msr.pending_bytes == 0
    # the final chunk counts against the limit too
    msr.stream_async(_req(cid, 99, 0, False, b"x" * 70))
    with pytest.raises(StreamException):
        msr.stream_end_of_request_async(_req(cid, 99, 1, True, b"y" * 70))


def test_idle_stream_expires(monkeypatch):
    import time as time_mod
    msr = MessageStreamRequests(byte_limit=100, expiry_s=10.0)
    cid = ClientId.random_id()
    msr.stream_async(_req(cid, 1, 0, False, b"x" * 90))  # abandoned
    now = time_mod.monotonic()
    monkeypatch.setattr("ratis_tpu.server.messagestream.time.monotonic",
                        lambda: now + 11.0)
    cid2 = ClientId.random_id()
    msr.stream_async(_req(cid2, 1, 0, False, b"y" * 90))  # fits again
    assert msr.pending_bytes == 90


def test_independent_streams_per_client():
    msr = MessageStreamRequests()
    c1, c2 = ClientId.random_id(), ClientId.random_id()
    msr.stream_async(_req(c1, 1, 0, False, b"one"))
    msr.stream_async(_req(c2, 1, 0, False, b"two"))
    w1 = msr.stream_end_of_request_async(_req(c1, 1, 1, True, b"+"))
    w2 = msr.stream_end_of_request_async(_req(c2, 1, 1, True, b"-"))
    assert w1.message.content == b"one+"
    assert w2.message.content == b"two-"


def test_end_to_end_large_message():
    """A 200KB message streamed in 16KB chunks lands as ONE applied entry."""

    async def _test(cluster):
        await cluster.wait_for_leader()
        big = bytes(range(256)) * 800  # 204800 bytes
        async with cluster.new_client() as client:
            reply = await client.message_stream().stream_async(
                big, submessage_size=16 << 10)
            assert reply.success
            read = await client.io().send_read_only(b"LAST")
        assert read.message.content == big
        # every replica applied exactly one entry with the full payload
        for div in cluster.divisions():
            if big in div.state_machine.applied:
                assert div.state_machine.applied.count(big) == 1

    run_with_new_cluster(3, _test, sm_factory=RecordingStateMachine)


def test_end_to_end_single_chunk():
    async def _test(cluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            reply = await client.message_stream().stream_async(b"small")
            assert reply.success
            read = await client.io().send_read_only(b"LAST")
            assert read.message.content == b"small"

    run_with_new_cluster(3, _test, sm_factory=RecordingStateMachine)
