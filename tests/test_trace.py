"""Host-path tracing subsystem (ratis_tpu.trace): span propagation across
the simulated transport end to end, ring-buffer wraparound, disabled-mode
zero cost, decomposition coverage + Perfetto export validity, and the
traced-vs-untraced overhead guard."""

import asyncio
import json

import pytest

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.trace import get_tracer
from ratis_tpu.trace.export import (host_path_decomposition, to_chrome_trace,
                                    write_chrome_trace)
from ratis_tpu.trace.tracer import (STAGE_APPEND, STAGE_APPLY, STAGE_CLIENT,
                                    STAGE_NAMES, STAGE_REPLICATE, STAGE_REPLY,
                                    STAGE_ROUTE, STAGE_TXN, SpanRing)


@pytest.fixture(autouse=True)
def _tracer_sandbox():
    """Tests share ONE process-wide tracer: restore the disabled default so
    a tracing test never bleeds spans (or enablement) into its neighbors."""
    tracer = get_tracer()
    yield
    tracer.configure(enabled=False)


# --------------------------------------------------------------- ring buffer

def test_ring_wraparound_keeps_latest_records():
    ring = SpanRing(8)
    for i in range(20):
        ring.record(trace_id=i, t0_ns=i * 100, t1_ns=i * 100 + 10, tag=i)
    assert ring.count == 8
    assert ring.recorded == 20
    assert ring.dropped == 12
    rows = ring.rows()
    # oldest-first snapshot of the LAST capacity records (12..19)
    assert [r[0] for r in rows.tolist()] == list(range(12, 20))
    assert all(r[2] == 10 for r in rows.tolist())  # durations survive wrap


def test_tracer_sampling_every_n():
    tracer = get_tracer()
    tracer.configure(enabled=True, sample_every=4, ring_size=64)
    ids = [tracer.begin_trace() for _ in range(16)]
    assert sum(1 for i in ids if i) == 4  # one in four sampled
    assert len({i for i in ids if i}) == 4  # sampled ids are distinct


# ------------------------------------------------------- disabled-mode cost

def test_disabled_tracer_records_nothing():
    tracer = get_tracer()
    tracer.configure(enabled=False)

    async def body(cluster: MiniCluster):
        for _ in range(4):
            assert (await cluster.send_write()).success

    run_with_new_cluster(3, body, properties=fast_properties())
    assert tracer.snapshot() == []
    assert tracer.begin_trace() == 0


# -------------------------------------------------- end-to-end propagation

def test_span_propagation_sim_transport_end_to_end():
    """Client send -> leader append -> commit -> apply all share ONE trace
    id, recorded through the full RaftClient stack over the simulated
    transport."""
    tracer = get_tracer()
    tracer.configure(enabled=True, sample_every=1, ring_size=1024)

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        client = cluster.new_client()
        try:
            reply = await client.io().send(b"INCREMENT")
            assert reply.success
        finally:
            await client.close()

    run_with_new_cluster(3, body, properties=fast_properties())

    by_stage: dict[int, set[int]] = {}
    for tid, stage, _t0, _dur, _tag, _origin in tracer.snapshot():
        if tid:
            by_stage.setdefault(stage, set()).add(tid)
    client_ids = by_stage.get(STAGE_CLIENT, set())
    assert client_ids, "no client span recorded"
    # at least one request crossed every layer under a single id
    full_path = (client_ids & by_stage.get(STAGE_ROUTE, set())
                 & by_stage.get(STAGE_TXN, set())
                 & by_stage.get(STAGE_APPEND, set())
                 & by_stage.get(STAGE_REPLICATE, set())
                 & by_stage.get(STAGE_APPLY, set())
                 & by_stage.get(STAGE_REPLY, set()))
    assert full_path, f"no trace id crossed all stages: {by_stage}"


def test_trace_id_rides_the_wire_encoding():
    from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
    from ratis_tpu.protocol.message import Message
    from ratis_tpu.protocol.requests import RaftClientRequest
    req = RaftClientRequest(ClientId.random_id(), RaftPeerId.value_of("s0"),
                            RaftGroupId.random_id(), 7,
                            Message(b"x"), trace_id=12345)
    assert RaftClientRequest.from_bytes(req.to_bytes()).trace_id == 12345
    # untraced requests pay zero wire bytes for the field
    bare = RaftClientRequest(req.client_id, req.server_id, req.group_id, 8,
                             Message(b"x"))
    assert b"tr" not in bare.to_bytes() or \
        RaftClientRequest.from_bytes(bare.to_bytes()).trace_id == 0


# ---------------------------------------- decomposition + Perfetto export

def test_decomposition_coverage_and_perfetto_export(tmp_path):
    """A sim-transport bench rung with tracing on: the per-stage totals
    account for >= 80% of the client-observed wall-clock, and the Chrome
    trace-event export is valid JSON with >= 5 distinct stage names."""
    from ratis_tpu.tools.bench_cluster import run_bench
    tracer = get_tracer()
    tracer.configure(enabled=False)  # run_bench's properties re-enable it
    out_path = str(tmp_path / "trace.json")

    async def main():
        return await run_bench(4, 16, batched=False, concurrency=8,
                               transport="sim", warmup_writes=1,
                               trace=True, trace_sample=1,
                               trace_out=out_path)

    result = asyncio.run(main())
    decomp = result["host_path_decomposition"]
    assert decomp["traced_requests"] > 0
    assert decomp["coverage"] >= 0.8, decomp
    # the tiling stages are all present in the table
    for name in ("server.route", "server.txn_start", "server.append",
                 "server.replicate", "server.apply", "server.reply",
                 "server.respond"):
        assert name in decomp["stages"], decomp["stages"].keys()
    # non-overlap sanity: covered never exceeds the measured wall
    assert decomp["covered_ms_total"] <= decomp["wall_ms_total"] * 1.001

    with open(out_path) as f:
        chrome = json.load(f)  # valid JSON or this raises
    events = chrome["traceEvents"]
    assert len(events) > 0
    names = {e["name"] for e in events}
    assert len(names) >= 5, names
    assert names <= set(STAGE_NAMES)
    for e in events[:50]:
        assert e["ph"] == "X" and e["dur"] > 0 and "ts" in e


def test_export_helpers_on_synthetic_records():
    records = [
        (1, STAGE_CLIENT, 1000, 1000, 0),
        (1, STAGE_APPEND, 1100, 200, 0),
        (1, STAGE_REPLICATE, 1300, 500, 0),
        (1, STAGE_APPLY, 1800, 100, 0),
    ]
    d = host_path_decomposition(records)
    assert d["traced_requests"] == 1
    assert d["coverage"] == 0.8  # (200+500+100)/1000
    chrome = to_chrome_trace(records)
    assert len(chrome["traceEvents"]) == 4
    assert json.loads(json.dumps(chrome)) == chrome


# ------------------------------------------------------------ overhead guard

def test_tracing_overhead_within_tolerance():
    """Traced (sample-every=4) vs untraced throughput on the same small sim
    rung.  The bound is deliberately loose (50%) — the point is catching a
    pathological regression (e.g. tracing work on the untraced path), not
    benchmarking; single-trial small rungs on shared CI scatter widely."""
    from ratis_tpu.tools.bench_cluster import run_bench
    tracer = get_tracer()
    tracer.configure(enabled=False)

    async def rung(trace: bool):
        return await run_bench(2, 48, batched=False, concurrency=16,
                               transport="sim", warmup_writes=4,
                               trace=trace, trace_sample=4)

    untraced = asyncio.run(rung(False))
    tracer.configure(enabled=False)  # fresh state for the traced rung
    traced = asyncio.run(rung(True))
    assert traced["commits_per_sec"] >= untraced["commits_per_sec"] * 0.5, \
        (traced["commits_per_sec"], untraced["commits_per_sec"])
