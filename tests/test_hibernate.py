"""Idle-group hibernation (RaftServerConfigKeys.Hibernate; the TiKV
hibernate-regions pattern, no reference analog): an idle group's leader
stops heartbeating and its followers disarm election timers — zero
background traffic — with wake-on-contact semantics."""

import asyncio

import numpy as np

import pytest

from minicluster import MiniCluster, batched_properties, run_with_new_cluster
from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.engine.state import NO_DEADLINE


@pytest.fixture(autouse=True, scope="module")
def _prewarm_kernels():
    # compile the batched kernels once up front: a cold jit stall mid-test
    # is long enough to distort the hibernation timing being asserted
    from ratis_tpu.engine.engine import QuorumEngine
    QuorumEngine(max_groups=1024, max_peers=8).prewarm(
        group_counts=(64,), event_counts=(64,))


def _hibernate_properties():
    p = batched_properties()
    p.set(RaftServerConfigKeys.Hibernate.ENABLED_KEY, "true")
    p.set(RaftServerConfigKeys.Hibernate.AFTER_SWEEPS_KEY, "2")
    return p


async def _wait_hibernated(cluster, timeout=20.0):
    await cluster.wait_for_leader()
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        # leadership may move while settling; find WHOEVER hibernated
        for d in cluster.divisions():
            if d._hibernating:
                return d
        await asyncio.sleep(0.05)
    raise TimeoutError("group never hibernated")


def test_idle_group_hibernates_and_wakes_on_write():
    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        # followers' election timers hold the LONG backstop deadline, far
        # past any normal election timeout (full disarm only at backstop=0)
        for d in cluster.divisions():
            if d is leader:
                continue
            eng = cluster.servers[d.member_id.peer_id].engine
            dl = int(eng.state.election_deadline_ms[d.engine_slot])
            assert d._hibernated_follower
            assert dl - eng.clock.now_ms() > 10_000
        # heartbeat traffic STOPS: bulk item counts freeze
        before = sum(s.heartbeats.metrics["heartbeats"]
                     for s in cluster.servers.values())
        await asyncio.sleep(0.5)  # several sweep intervals
        after = sum(s.heartbeats.metrics["heartbeats"]
                    for s in cluster.servers.values())
        assert after == before, "hibernated group still heartbeating"
        # a write wakes the group and commits normally
        reply = await cluster.send_write()
        assert reply.success
        assert not leader._hibernating
        # ...and it re-hibernates once idle again
        await _wait_hibernated(cluster)

    run_with_new_cluster(3, body, properties=_hibernate_properties())


def test_sleep_wake_cycles_cause_no_vote_churn():
    """The r5 sparse rung recorded 196 residual vote dispatches around the
    sleep/wake boundary (VERDICT weak #3 tail).  This pins the healthy-
    path bound: repeated sleep -> client-wake -> re-sleep cycles on a
    healthy group must run ZERO elections — the term never moves, no
    follower fires a timeout-path election, and leadership never leaves
    the appointed leader.  (Elections around a DEAD leader's wake are the
    designed behavior and live in the dead-leader tests above.)"""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        term = leader.state.current_term
        lid = leader.member_id.peer_id
        elections_before = sum(
            d.election_metrics.election_count.count
            for d in cluster.divisions())
        for _ in range(3):
            # wake via client contact, commit, then fall back asleep
            assert (await cluster.send_write()).success
            leader = await _wait_hibernated(cluster)
        elections_after = sum(
            d.election_metrics.election_count.count
            for d in cluster.divisions())
        assert elections_after == elections_before, \
            "sleep/wake boundary started an election on a healthy group"
        assert leader.state.current_term == term, \
            "vote churn moved the term across sleep/wake cycles"
        assert leader.member_id.peer_id == lid, \
            "leadership moved across sleep/wake cycles"

    run_with_new_cluster(3, body, properties=_hibernate_properties())


def test_hibernated_leader_not_stepped_down_as_stale():
    """A hibernated leader hears no acks by design; the staleness sweep
    must not abdicate it while asleep, and it serves writes at wake."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        # sleep well past the leadership-staleness window
        timeout_s = leader.server.engine.leadership_timeout_ms / 1000.0
        await asyncio.sleep(min(timeout_s * 2, 3.0))
        assert leader.is_leader(), "hibernated leader was stepped down"
        assert (await cluster.send_write()).success

    run_with_new_cluster(3, body, properties=_hibernate_properties())


def test_dead_hibernated_leader_recovers_on_client_contact():
    """Leader dies while the group sleeps: the group stays quiet (the
    accepted availability trade) until ANY client contact wakes a
    follower, which re-arms its timer, elects, and serves the write."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        lid = leader.member_id.peer_id
        await cluster.kill_server(lid)
        # the survivors are disarmed: give them time to NOT elect
        await asyncio.sleep(0.8)
        assert not any(d.is_leader() for d in cluster.divisions()), \
            "disarmed followers elected without being woken"
        # first client contact wakes a follower -> election -> write lands
        reply = await cluster.send_write()
        assert reply.success
        assert any(d.is_leader() for d in cluster.divisions())

    run_with_new_cluster(3, body, properties=_hibernate_properties())


def test_backstop_elects_after_leader_death_without_contact():
    """Dead-leader backstop: with zero client traffic, a hibernated
    group whose leader dies re-elects within ~backstop — the slow-tick
    refreshes stop, the followers' long deadlines expire, and a normal
    election runs (round-4 advisor: full disarm left such a group
    leaderless forever)."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        await cluster.kill_server(leader.member_id.peer_id)
        # NO client contact at all: the backstop alone must recover it
        deadline = asyncio.get_event_loop().time() + 12.0
        while asyncio.get_event_loop().time() < deadline:
            if any(d.is_leader() for d in cluster.divisions()):
                break
            await asyncio.sleep(0.05)
        assert any(d.is_leader() for d in cluster.divisions()), \
            "backstop never made the group electable again"
        assert (await cluster.send_write()).success

    p = _hibernate_properties()
    p.set(RaftServerConfigKeys.Hibernate.BACKSTOP_KEY, "1500ms")
    run_with_new_cluster(3, body, properties=p)


def test_backstop_slow_tick_keeps_healthy_group_asleep():
    """The slow tick is not a wake: a HEALTHY sleeping group rides
    through several backstop periods without elections, leadership
    movement, or falling out of hibernation."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        term = leader.state.current_term
        await asyncio.sleep(2.5)  # >= 2 full backstop periods
        assert leader.is_leader() and leader._hibernating
        assert leader.state.current_term == term, \
            "slow tick triggered an election in a healthy sleeping group"

    p = _hibernate_properties()
    p.set(RaftServerConfigKeys.Hibernate.BACKSTOP_KEY, "1s")
    run_with_new_cluster(3, body, properties=p)


def test_hibernated_group_partition_safety():
    """Partition a hibernated leader away, then write: the woken leader
    cannot replicate, steps down after its wake grace, and the client's
    retries wake a follower into an election — exactly one committed
    value per write, no divergence after heal."""

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        leader = await _wait_hibernated(cluster)
        lid = leader.member_id.peer_id
        others = [d.member_id.peer_id for d in cluster.divisions()
                  if d.member_id.peer_id != lid]
        cluster.network.partition([lid], others)
        # write while partitioned: must eventually land on the majority
        # side (the isolated leader wakes, fails to replicate, abdicates).
        # Generous budget: the first attempt sinks ~3s pending at the
        # isolated leader before the client moves on and nudges a
        # follower awake.
        reply = await cluster.send(b"INCREMENT", timeout=30.0)
        assert reply.success
        cluster.network.unblock_all()
        # heal: the old leader rejoins as follower and converges
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            counters = {d.member_id.peer_id: d.state_machine.counter
                        for d in cluster.divisions()}
            if len(set(counters.values())) == 1 \
                    and next(iter(counters.values())) == 2:
                break
            await asyncio.sleep(0.05)
        counters = {str(d.member_id.peer_id): d.state_machine.counter
                    for d in cluster.divisions()}
        assert set(counters.values()) == {2}, counters
        assert (await cluster.send_write()).success

    run_with_new_cluster(3, body, properties=_hibernate_properties())
