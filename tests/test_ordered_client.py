"""Ordered-async client semantics (reference OrderedAsync.java:59 +
GrpcClientProtocolService.java:151 + SlidingWindow.java:39): concurrent
sends from one client commit in submission order even when the transport
delivers them out of order."""

import asyncio

import pytest

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from statemachines import RecordingStateMachine
from ratis_tpu.util.sliding_window import SlidingWindowServer


def test_sliding_window_server_reorders():
    """Unit: out-of-order receive dispatches strictly by seqNum; a
    post-failover first request rebases the window."""

    async def main():
        processed = []

        async def process(x):
            processed.append(x)

        win = SlidingWindowServer(process)
        await asyncio.gather(
            win.receive(2, False, "c"),
            win.receive(0, True, "a"),
            win.receive(1, False, "b"),
        )
        assert processed == ["a", "b", "c"]
        # duplicate below the window: dropped
        await win.receive(1, False, "b-dup")
        assert processed == ["a", "b", "c"]
        # failover rebase: first=True resets, parked stale seqs are dropped
        await win.receive(7, False, "z")          # parks
        assert win.pending_count() == 1
        await win.receive(5, True, "x")
        await win.receive(6, False, "y")
        await win.receive(7, False, "z")
        assert processed == ["a", "b", "c", "x", "y", "z"]

    asyncio.run(main())


def test_ordered_sends_commit_fifo_under_jitter():
    """Cluster: 20 concurrent OrderedApi sends under client->server jitter
    apply in exact submission order on every replica."""

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        n = 20
        cluster.network.base_delay_ms = 1.0
        cluster.network.jitter_ms = 8.0  # client requests reorder in flight
        async with cluster.new_client() as client:
            replies = await asyncio.gather(*(
                client.io().send(f"w{i:03d}".encode()) for i in range(n)))
            assert all(r.success for r in replies)
        cluster.network.base_delay_ms = 0.0
        cluster.network.jitter_ms = 0.0
        last = leader.state.log.get_last_committed_index()
        await cluster.wait_applied(last)
        expected = [f"w{i:03d}".encode() for i in range(n)]
        for d in cluster.divisions():
            assert d.state_machine.applied == expected, (
                f"{d.member_id}: {d.state_machine.applied}")

    run_with_new_cluster(3, body, sm_factory=RecordingStateMachine)


def test_ordered_sends_survive_leader_failover():
    """Ordering holds across a leader kill mid-stream: all sends succeed and
    the survivors apply the writes with no duplicates."""

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        n = 12
        async with cluster.new_client() as client:
            first = await asyncio.gather(*(
                client.io().send(f"a{i:02d}".encode()) for i in range(4)))
            assert all(r.success for r in first)
            await cluster.kill_server(leader.member_id.peer_id)
            rest = await asyncio.gather(*(
                client.io().send(f"b{i:02d}".encode()) for i in range(n - 4)))
            assert all(r.success for r in rest)
        new_leader = await cluster.wait_for_leader()
        last = new_leader.state.log.get_last_committed_index()
        divs = [d for d in cluster.divisions()]
        await cluster.wait_applied(last, divisions=divs)
        for d in divs:
            assert len(d.state_machine.applied) == n  # no dupes, no losses
            # the post-failover block is FIFO within itself
            bs = [p for p in d.state_machine.applied if p.startswith(b"b")]
            assert bs == sorted(bs)

    run_with_new_cluster(3, body, sm_factory=RecordingStateMachine)
