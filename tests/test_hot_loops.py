"""tools/check_hot_loops: the static gate that keeps the O(G) per-group
Python walk from creeping back into the tick/sweep modules after PR 15
vectorized it away."""

import ast

from ratis_tpu.tools import check_hot_loops as gate


def test_repo_hot_loops_all_allowlisted():
    assert gate.check() == []


def test_new_divisions_walk_is_flagged(tmp_path):
    src = (
        "class Scheduler:\n"
        "    async def _run(self):\n"
        "        for div in list(self.server.divisions.values()):\n"
        "            div.tick()\n"
    )
    (tmp_path / "mod.py").write_text(src)
    problems = gate.check(repo=str(tmp_path), scanned=("mod.py",),
                          allowlist={})
    assert len(problems) == 1
    assert "Scheduler._run" in problems[0] and "mod.py:3" in problems[0]


def test_comprehension_walk_is_flagged():
    src = (
        "def sample(server):\n"
        "    return [d.lag for d in server.divisions.values()]\n"
    )
    sites = gate.scan_source("mod.py", src)
    assert sites == [("mod.py", "sample", 2)]


def test_allowlisted_walk_passes_and_stale_entry_fails(tmp_path):
    src = (
        "def shutdown(server):\n"
        "    for d in server.divisions.values():\n"
        "        d.close()\n"
    )
    (tmp_path / "mod.py").write_text(src)
    ok = gate.check(repo=str(tmp_path), scanned=("mod.py",),
                    allowlist={("mod.py", "shutdown"): "shutdown only"})
    assert ok == []
    stale = gate.check(
        repo=str(tmp_path), scanned=("mod.py",),
        allowlist={("mod.py", "shutdown"): "shutdown only",
                   ("mod.py", "gone_function"): "no longer exists"})
    assert len(stale) == 1 and "stale allowlist" in stale[0]


def test_loop_free_module_is_clean(tmp_path):
    (tmp_path / "mod.py").write_text("x = 1\n")
    assert gate.check(repo=str(tmp_path), scanned=("mod.py",),
                      allowlist={}) == []


def test_gate_scans_the_sweep_modules():
    # the modules the ISSUE names as hot paths must stay under the gate
    for rel in ("ratis_tpu/server/server.py",
                "ratis_tpu/server/division.py",
                "ratis_tpu/server/leader.py",
                "ratis_tpu/server/upkeep.py"):
        assert rel in gate.SCANNED
