"""Shell CLI + tools tests (reference ratis-test shell suites
ratis-test/src/test/.../shell/cli/sh/ and ratis-tools ParseRatisLog)."""

import io
import subprocess
import sys

import pytest

from ratis_tpu.shell.cli import build_parser, parse_peers
from tests.minicluster import run_with_new_cluster


def _peer_spec(cluster):
    return ",".join(f"{p.id}={p.address}" for p in cluster.group.peers)


def _parse(argv):
    return build_parser().parse_args(argv)


def test_parse_peers_forms():
    peers = parse_peers("s0=h1:1,s1=h2:2")
    assert [str(p.id) for p in peers] == ["s0", "s1"]
    assert peers[0].address == "h1:1"
    bare = parse_peers("10.0.0.1:9000")
    assert bare[0].address == "10.0.0.1:9000"
    assert str(bare[0].id) == "10_0_0_1_9000"
    with pytest.raises(ValueError):
        parse_peers("  ,  ")


def test_shell_group_and_election_commands():
    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        spec = _peer_spec(cluster)
        gid = str(cluster.group.group_id.uuid)

        args = _parse(["group", "list", "-peers", spec])
        assert await args.func(args) == 0

        args = _parse(["group", "info", "-peers", spec, "-groupid", gid])
        assert await args.func(args) == 0

        # group id auto-discovery (single group)
        args = _parse(["group", "info", "-peers", spec])
        assert await args.func(args) == 0

        # transfer leadership to a follower by peer id
        follower = next(d for d in cluster.divisions() if d.is_follower())
        args = _parse(["election", "transfer", "-peers", spec,
                       "-peerId", str(follower.member_id.peer_id),
                       "-groupid", gid])
        assert await args.func(args) == 0
        new_leader = await cluster.wait_for_leader()
        assert new_leader.member_id.peer_id == follower.member_id.peer_id

        # pause + resume elections on a follower
        f2 = next(d for d in cluster.divisions() if d.is_follower())
        args = _parse(["election", "pause", "-peers", spec,
                       "-peerId", str(f2.member_id.peer_id),
                       "-groupid", gid])
        assert await args.func(args) == 0
        args = _parse(["election", "resume", "-peers", spec,
                       "-peerId", str(f2.member_id.peer_id),
                       "-groupid", gid])
        assert await args.func(args) == 0

    run_with_new_cluster(3, _test, rpc_type="GRPC")


def test_shell_snapshot_create(tmp_path):
    async def _test(cluster):
        await cluster.wait_for_leader()
        for _ in range(3):
            reply = await cluster.send_write(b"INCREMENT")
            assert reply.success
        spec = _peer_spec(cluster)
        args = _parse(["snapshot", "create", "-peers", spec])
        assert await args.func(args) == 0

    run_with_new_cluster(3, _test, rpc_type="GRPC",
                         storage_root=str(tmp_path))


def test_shell_main_subprocess():
    """The real entry point: python -m ratis_tpu.shell against a live
    cluster from another process."""

    async def _test(cluster):
        await cluster.wait_for_leader()
        spec = _peer_spec(cluster)
        proc = await __import__("asyncio").create_subprocess_exec(
            sys.executable, "-m", "ratis_tpu.shell", "group", "info",
            "-peers", spec,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={"PYTHONPATH": "/root/repo", "PATH": "/usr/bin:/bin",
                 "JAX_PLATFORMS": "cpu"})
        out, err = await proc.communicate()
        assert proc.returncode == 0, err.decode()
        text = out.decode()
        assert "leader:" in text and "commit index:" in text

    run_with_new_cluster(3, _test, rpc_type="GRPC")


def test_parse_log_tool(tmp_path):
    from ratis_tpu.tools.parse_log import dump_segment

    async def _test(cluster):
        await cluster.wait_for_leader()
        for _ in range(5):
            reply = await cluster.send_write(b"INCREMENT")
            assert reply.success

    run_with_new_cluster(3, _test, storage_root=str(tmp_path))
    segments = list(tmp_path.rglob("log_*"))
    assert segments
    lines = []
    total = sum(dump_segment(str(s), out=lines.append) for s in segments)
    assert total >= 5
    text = "\n".join(lines)
    assert "STATE_MACHINE" in text and "CONFIGURATION" in text


def test_local_raft_meta_conf(tmp_path):
    async def _test(cluster):
        await cluster.wait_for_leader()
        reply = await cluster.send_write(b"INCREMENT")
        assert reply.success

    run_with_new_cluster(3, _test, storage_root=str(tmp_path))
    conf_files = list(tmp_path.rglob("raft-meta.conf"))
    assert conf_files
    current_dir = conf_files[0].parent
    args = _parse(["local", "raftMetaConf", "-path", str(current_dir),
                   "-peers", "n0=h1:1,n1=h2:2,n2=h3:3"])
    assert args.func(args) == 0  # sync command
    from ratis_tpu.protocol.logentry import LogEntry
    rewritten = LogEntry.from_bytes(conf_files[0].read_bytes())
    assert sorted(str(p.id) for p in rewritten.conf.peers) == \
        ["n0", "n1", "n2"]
    assert (current_dir / "raft-meta.conf.bak").exists()
