"""Metric-name / documentation drift gate
(ratis_tpu.tools.check_metrics_docs): every metric name registered on a
``RatisMetricRegistry`` in code must be named in docs/metrics.md — the
round-11 companion to the conf-docs gate (PR 4 wrote the catalog by
hand; this run of the checker already caught six undocumented
datastream metrics)."""

from ratis_tpu.tools.check_metrics_docs import (check, code_metric_names,
                                                doc_metric_names)


def test_metric_names_and_docs_in_sync():
    problems = check()
    assert not problems, "\n".join(problems)


def test_parsers_see_real_catalogs():
    """Guard the checker itself: an empty parse would pass check()
    vacuously while asserting nothing."""
    code = code_metric_names()
    assert len(code) > 50, f"code parse collapsed: {len(code)} names"
    # the four registration forms all parse
    assert "ticks" in code                      # .counter("...")
    assert "dispatchLatency" in code            # .timer("...")
    assert "ackBatchSize" in code               # .histogram("...")
    assert "laneOccupancyGroups" in code        # .gauge("...", ...)
    assert "dispatches" in code                 # labeled("...", k=v)
    assert "telemetrySamples" in code           # round-11 sampler
    doc = doc_metric_names()
    assert len(doc) > 60, f"doc parse collapsed: {len(doc)} names"
    # suffix alternation expands: `numRetryCacheHits/Misses`
    assert "numRetryCacheHits" in doc
    assert "numRetryCacheMisses" in doc
    # labeled-family braces strip: `dispatches{reason=...}`
    assert "dispatches" in doc
