"""StateMachine lifecycle notifications (reference StateMachine.java:237-283,
tested there by TestRaftServerSlownessDetection and
TestRaftServerNoLeaderTimeout): follower slowness, extended no-leader,
not-leader pending drain, and server shutdown all reach the state machine.
"""

import asyncio

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.conf import RaftServerConfigKeys
from ratis_tpu.models.counter import CounterStateMachine


class EventRecordingSM(CounterStateMachine):
    def __init__(self) -> None:
        super().__init__()
        self.events: list[tuple] = []

    async def notify_follower_slowness(self, role_info, slow_peer) -> None:
        self.events.append(("slowness", slow_peer.id if slow_peer else None))

    async def notify_extended_no_leader(self, role_info) -> None:
        self.events.append(("no_leader", role_info["role"]))

    async def notify_not_leader(self, pending_requests) -> None:
        self.events.append(("not_leader", list(pending_requests)))

    async def notify_server_shutdown(self, role_info, all_groups) -> None:
        self.events.append(("shutdown", all_groups))


def _props(**overrides):
    p = fast_properties()
    for k, v in overrides.items():
        p.set(k, v)
    return p


def test_follower_slowness_notification():
    """A follower that stops responding for Rpc.slowness_timeout triggers
    notify_follower_slowness on the leader's SM, once per period
    (TestRaftServerSlownessDetection analog)."""

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        assert (await cluster.send_write()).success
        slow = next(d for d in cluster.divisions() if not d.is_leader())
        sid = slow.member_id.peer_id
        cluster.network.block(leader.member_id.peer_id, sid)
        deadline = asyncio.get_event_loop().time() + 5.0
        sm = leader.state_machine
        while asyncio.get_event_loop().time() < deadline:
            if any(e[0] == "slowness" and e[1] == sid for e in sm.events):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"no slowness event; got {sm.events}")
        cluster.network.unblock_all()

    run_with_new_cluster(
        3, body, sm_factory=EventRecordingSM,
        properties=_props(**{
            RaftServerConfigKeys.Rpc.SLOWNESS_TIMEOUT_KEY: "400ms"}))


def test_extended_no_leader_notification():
    """A follower that cannot find a leader past
    Notification.no_leader_timeout notifies its SM
    (TestRaftServerNoLeaderTimeout analog)."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        victim = next(d for d in cluster.divisions() if not d.is_leader())
        vid = victim.member_id.peer_id
        # full isolation: sees no leader, elections can't win
        others = [d.member_id.peer_id for d in cluster.divisions()
                  if d.member_id.peer_id != vid]
        cluster.network.partition([vid], others)
        sm = victim.state_machine
        deadline = asyncio.get_event_loop().time() + 8.0
        while asyncio.get_event_loop().time() < deadline:
            if any(e[0] == "no_leader" for e in sm.events):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"no no_leader event; got {sm.events}")
        cluster.network.unblock_all()

    run_with_new_cluster(
        3, body, sm_factory=EventRecordingSM,
        properties=_props(**{
            RaftServerConfigKeys.Notification.NO_LEADER_TIMEOUT_KEY: "500ms"}))


def test_not_leader_drains_pending_to_sm():
    """A leader that steps down with uncommittable pending writes hands them
    to notify_not_leader before failing their futures."""

    async def body(cluster: MiniCluster):
        # The write must be IN the isolated leader's pending set before
        # the staleness step-down (~400ms after the partition) drains it
        # — the old single-shot partition-then-write order lost that race
        # ~1/10 runs (step-down with an EMPTY pending set emits nothing).
        # A committed sanity write right before the partition proves the
        # leader is READY (a fresh not-ready leader rejects instead of
        # pending), and a missed window is retried on the new leader.
        leader = write = None
        for _attempt in range(4):
            leader = await cluster.wait_for_leader()
            assert (await cluster.send(b"INCREMENT")).success  # ready
            lid = leader.member_id.peer_id
            others = [d.member_id.peer_id for d in cluster.divisions()
                      if d.member_id.peer_id != lid]
            cluster.network.partition([lid], others)
            write = asyncio.create_task(cluster.send(
                b"INCREMENT", server_id=lid, timeout=30.0))
            deadline = asyncio.get_event_loop().time() + 2.0
            pended = False
            while asyncio.get_event_loop().time() < deadline:
                if leader.leader_ctx is not None \
                        and leader.leader_ctx.pending:
                    pended = True
                    break
                if not leader.is_leader():
                    break  # stepped down before the write arrived
                await asyncio.sleep(0.02)
            if pended:
                break
            # missed the window: heal, let the write land somewhere, retry
            cluster.network.unblock_all()
            await write
            write = None
        else:
            raise AssertionError(
                "write never pended on an isolated leader in 4 attempts")
        sm = leader.state_machine
        deadline = asyncio.get_event_loop().time() + 8.0
        while asyncio.get_event_loop().time() < deadline:
            if any(e[0] == "not_leader" and e[1] for e in sm.events):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"no not_leader event; got "
                                 f"{[e[0] for e in sm.events]}")
        cluster.network.unblock_all()
        reply = await write  # client retries to the majority-side leader
        assert reply.success

    run_with_new_cluster(3, body, sm_factory=EventRecordingSM)


def test_server_shutdown_notification():
    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        sms = [d.state_machine for d in cluster.divisions()]
        await cluster.close()
        for sm in sms:
            assert ("shutdown", True) in sm.events, sm.events

    run_with_new_cluster(3, body, sm_factory=EventRecordingSM)


def test_apply_transaction_serial_runs_before_apply():
    """apply_transaction_serial (StateMachine.java:565) is invoked by the
    apply daemon strictly before apply_transaction for every committed
    entry, in log-index order, and its (possibly transformed) context is
    the one handed to apply_transaction."""
    from ratis_tpu.models.counter import CounterStateMachine

    class SerialRecordingSM(CounterStateMachine):
        def __init__(self):
            super().__init__()
            self.calls = []

        async def apply_transaction_serial(self, trx):
            self.calls.append(("serial", trx.log_entry.index))
            trx.serial_seen = True
            return trx

        async def apply_transaction(self, trx):
            assert getattr(trx, "serial_seen", False), \
                "apply_transaction ran without apply_transaction_serial"
            self.calls.append(("apply", trx.log_entry.index))
            return await super().apply_transaction(trx)

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        for _ in range(3):
            assert (await cluster.send_write()).success
        sm = leader.state_machine
        deadline = asyncio.get_event_loop().time() + 5.0
        while asyncio.get_event_loop().time() < deadline:
            if sum(1 for k, _ in sm.calls if k == "apply") >= 3:
                break
            await asyncio.sleep(0.05)
        applies = [i for k, i in sm.calls if k == "apply"]
        serials = [i for k, i in sm.calls if k == "serial"]
        assert len(applies) >= 3
        assert serials == sorted(serials), "serial hook ran out of order"
        for idx in applies:
            assert idx in serials

    run_with_new_cluster(3, body, sm_factory=SerialRecordingSM)
