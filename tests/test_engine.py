"""QuorumEngine tick-path tests: the device-resident batched path must be
observationally identical to the scalar fallback (same callbacks, same state
mirror) under scripted and randomized scenarios, including dirty-row
refreshes, capacity regrowth, and deadline disarm/re-arm cycles.

Reference behaviors under test: LeaderStateImpl.updateCommit:907,
FollowerState election timeout, LeaderStateImpl.checkLeadership:1096 —
executed here through ops.quorum.engine_step_resident with donated device
buffers (VERDICT r1 item 4: O(events + changed) host<->device per tick).
"""

import asyncio
import random

import numpy as np
import pytest

from ratis_tpu.engine.engine import QuorumEngine
from ratis_tpu.engine.state import (NO_DEADLINE, ROLE_FOLLOWER, ROLE_LEADER,
                                    ROLE_LISTENER)


class FakeClock:
    def __init__(self):
        self.t = 0

    def now_ms(self):
        return self.t

    def advance_epoch(self, delta_ms):
        self.t -= delta_ms


class Recorder:
    def __init__(self):
        self.events = []

    async def on_election_timeout(self):
        self.events.append("timeout")

    async def on_commit_advance(self, c):
        self.events.append(("commit", c))

    async def on_leadership_stale(self):
        self.events.append("stale")


def _mk_engine(use_device: bool, max_groups=8, max_peers=4) -> QuorumEngine:
    e = QuorumEngine(max_groups=max_groups, max_peers=max_peers,
                     scalar_fallback_threshold=10**9,
                     leadership_timeout_ms=300,
                     use_device=use_device)
    e.clock = FakeClock()
    return e


def _setup_leader(e: QuorumEngine, rec, n_peers=3, flush=5):
    slot = e.attach(rec)
    s = e.state
    cur = np.zeros(e.state.max_peers, bool)
    cur[:n_peers] = True
    s.set_conf(slot, 0, cur, np.zeros(e.state.max_peers, bool),
               np.zeros(e.state.max_peers, np.int32), 0)
    s.role[slot] = ROLE_LEADER
    s.flush_index[slot] = flush
    s.commit_index[slot] = -1
    s.first_leader_index[slot] = 0
    s.last_ack_ms[slot, :n_peers] = e.clock.now_ms()
    s.election_deadline_ms[slot] = NO_DEADLINE
    s.mark_dirty(slot)
    return slot


@pytest.mark.parametrize("use_device", [False, True])
def test_commit_advance_via_acks(use_device):
    async def _run():
        e = _mk_engine(use_device)
        rec = Recorder()
        slot = _setup_leader(e, rec, n_peers=3, flush=5)
        # majority = 2 of 3: leader flush=5 plus one follower at 4 -> commit 4
        e.on_ack(slot, 1, 4)
        await e.tick()
        assert ("commit", 4) in rec.events
        assert e.state.commit_index[slot] == 4
        # second follower at 5 -> commit 5 (leader already flushed 5)
        e.on_ack(slot, 2, 5)
        await e.tick()
        assert ("commit", 5) in rec.events
        assert e.state.commit_index[slot] == 5

    asyncio.run(_run())


@pytest.mark.parametrize("use_device", [False, True])
def test_flush_advance_alone_advances_commit(use_device):
    """A leader whose followers already matched must commit when its OWN
    flush catches up — the decoupled-fsync path (flush callback marks the
    slot dirty; no ack event involved)."""

    async def _run():
        e = _mk_engine(use_device)
        rec = Recorder()
        slot = _setup_leader(e, rec, n_peers=3, flush=0)
        e.on_ack(slot, 1, 7)
        e.on_ack(slot, 2, 7)
        await e.tick()
        assert e.state.commit_index[slot] == 7  # majority w/o the leader
        # now a slot untouched by acks: flush alone moves commit via dirty
        e.state.flush_index[slot] = 9
        e.state.mark_dirty(slot)
        e.on_ack(slot, 1, 9)
        await e.tick()
        assert e.state.commit_index[slot] == 9

    asyncio.run(_run())


@pytest.mark.parametrize("use_device", [False, True])
def test_election_timeout_fires_once_and_rearms(use_device):
    async def _run():
        e = _mk_engine(use_device)
        rec = Recorder()
        slot = e.attach(rec)
        s = e.state
        s.role[slot] = ROLE_FOLLOWER
        s.election_deadline_ms[slot] = 100
        s.mark_dirty(slot)
        e.clock.t = 50
        await e.tick()
        assert rec.events == []
        e.clock.t = 150
        await e.tick()
        assert rec.events == ["timeout"]
        # deadline disarmed on both host and device: no refire
        e.clock.t = 250
        await e.tick()
        assert rec.events == ["timeout"]
        assert s.election_deadline_ms[slot] == NO_DEADLINE
        # re-arm (dirty) -> fires again
        s.election_deadline_ms[slot] = 300
        s.mark_dirty(slot)
        e.clock.t = 301
        await e.tick()
        assert rec.events == ["timeout", "timeout"]

    asyncio.run(_run())


@pytest.mark.parametrize("use_device", [False, True])
def test_stale_leadership_detected(use_device):
    async def _run():
        e = _mk_engine(use_device)
        rec = Recorder()
        slot = _setup_leader(e, rec, n_peers=3)
        e.clock.t = 1000
        # scalar path throttles staleness sweeps; tick twice around the gate
        await e.tick()
        e.clock.t = 1400
        await e.tick()
        assert "stale" in rec.events

    asyncio.run(_run())


@pytest.mark.parametrize("use_device", [False, True])
def test_heartbeat_acks_keep_leadership(use_device):
    async def _run():
        e = _mk_engine(use_device)
        rec = Recorder()
        slot = _setup_leader(e, rec, n_peers=3)
        for t in (100, 200, 300, 400):
            e.clock.t = t
            e.on_ack(slot, 1, -1)  # heartbeat acks: time only
            e.on_ack(slot, 2, -1)
            await e.tick()
        assert "stale" not in rec.events

    asyncio.run(_run())


def test_device_capacity_regrow_preserves_state():
    async def _run():
        e = _mk_engine(True, max_groups=2, max_peers=4)
        recs = [Recorder() for _ in range(5)]
        slots = []
        for r in recs[:2]:
            slots.append(_setup_leader(e, r, n_peers=3, flush=5))
        e.on_ack(slots[0], 1, 5)
        await e.tick()  # device state created at capacity 2
        assert e.state.commit_index[slots[0]] == 5
        # allocating past capacity regrows arrays -> device re-upload
        for r in recs[2:]:
            slots.append(_setup_leader(e, r, n_peers=3, flush=3))
        assert e.state.capacity >= 5
        e.on_ack(slots[4], 1, 3)
        e.on_ack(slots[0], 2, 5)
        await e.tick()
        assert e.state.commit_index[slots[4]] == 3
        assert e.state.commit_index[slots[0]] == 5

    asyncio.run(_run())


def test_randomized_scalar_vs_device_equivalence():
    """Drive two engines with an identical random script; callbacks and the
    host state mirrors must agree tick for tick."""

    async def _run():
        rng = random.Random(1234)
        G, P = 12, 4
        eng_s = _mk_engine(False, max_groups=16, max_peers=P)
        eng_d = _mk_engine(True, max_groups=16, max_peers=P)
        recs_s, recs_d, slots = [], [], []
        for g in range(G):
            rs, rd = Recorder(), Recorder()
            recs_s.append(rs)
            recs_d.append(rd)
            role = rng.choice([ROLE_LEADER, ROLE_FOLLOWER, ROLE_LISTENER])
            n_peers = rng.randint(1, P)
            flush = rng.randint(-1, 10)
            deadline = rng.randint(1, 500)
            for e, r in ((eng_s, rs), (eng_d, rd)):
                slot = e.attach(r)
                s = e.state
                cur = np.zeros(P, bool)
                cur[:n_peers] = True
                s.set_conf(slot, 0, cur, np.zeros(P, bool),
                           np.zeros(P, np.int32), 0)
                s.role[slot] = role
                s.flush_index[slot] = flush
                s.first_leader_index[slot] = 0
                if role == ROLE_FOLLOWER:
                    s.election_deadline_ms[slot] = deadline
                s.mark_dirty(slot)
            slots.append(slot)  # same slot ids on both engines

        for step in range(30):
            t = step * 37
            eng_s.clock.t = t
            eng_d.clock.t = t
            for _ in range(rng.randint(0, 6)):
                g = rng.choice(slots)
                p = rng.randint(0, P - 1)
                m = rng.randint(-1, 12)
                eng_s.on_ack(g, p, m)
                eng_d.on_ack(g, p, m)
            if rng.random() < 0.3:
                g = rng.choice(slots)
                f = rng.randint(0, 12)
                for e in (eng_s, eng_d):
                    e.state.flush_index[g] = f
                    e.state.mark_dirty(g)
            if rng.random() < 0.2:
                g = rng.choice(slots)
                d = t + rng.randint(1, 200)
                for e in (eng_s, eng_d):
                    if e.state.role[g] == ROLE_FOLLOWER:
                        e.state.election_deadline_ms[g] = d
                        e.state.mark_dirty(g)
            await eng_s.tick()
            await eng_d.tick()
            np.testing.assert_array_equal(eng_s.state.commit_index,
                                          eng_d.state.commit_index)
            np.testing.assert_array_equal(eng_s.state.match_index,
                                          eng_d.state.match_index)
            np.testing.assert_array_equal(eng_s.state.election_deadline_ms,
                                          eng_d.state.election_deadline_ms)

        for rs, rd in zip(recs_s, recs_d):
            # staleness sweeps are throttled differently (scalar: timeout/4
            # cadence; device: every tick) so compare commit/timeout exactly
            # and staleness as a set property
            assert [x for x in rs.events if x != "stale"] \
                == [x for x in rd.events if x != "stale"]

    asyncio.run(_run())


def test_scalar_batched_mode_crossing_invalidates_device_state():
    """Crossing below the fallback threshold and back must not leave a stale
    device copy: scalar-tick mutations (acks, commit advances, deadline
    disarms) happen host-only, so the next batched tick re-uploads."""

    async def _run():
        e = QuorumEngine(max_groups=8, max_peers=4,
                         scalar_fallback_threshold=3,
                         leadership_timeout_ms=300, use_device=False)
        e.clock = FakeClock()
        recs = [Recorder() for _ in range(3)]
        slots = [_setup_leader(e, r, n_peers=3, flush=5) for r in recs]
        e.on_ack(slots[0], 1, 5)
        await e.tick()  # batched (3 >= 3)
        assert e.state.commit_index[slots[0]] == 5
        assert e._dev is not None

        e.detach(slots[2])  # drops to 2 -> scalar
        e.clock.t = 100
        e.on_ack(slots[1], 1, 3)
        await e.tick()
        assert e._dev is None  # stale device copy dropped
        assert e.state.commit_index[slots[1]] == 3

        # back above the threshold: batched tick must see the scalar-era
        # state (no commit regression, no spurious staleness step-down)
        slots[2] = _setup_leader(e, recs[2], n_peers=3, flush=5)
        e.clock.t = 150
        e.on_ack(slots[0], 1, -1)
        e.on_ack(slots[0], 2, -1)
        e.on_ack(slots[1], 1, -1)
        e.on_ack(slots[1], 2, -1)
        e.on_ack(slots[2], 1, -1)
        e.on_ack(slots[2], 2, -1)
        await e.tick()
        assert e.state.commit_index[slots[0]] == 5
        assert e.state.commit_index[slots[1]] == 3
        assert "stale" not in recs[0].events
        assert "stale" not in recs[1].events

    asyncio.run(_run())


# ---------------------------------------------------------------- vote rounds


def _setup_candidate(e: QuorumEngine, rec, n_peers=3, priorities=None,
                     self_priority=0):
    from ratis_tpu.engine.state import ROLE_CANDIDATE
    slot = e.attach(rec)
    s = e.state
    cur = np.zeros(s.max_peers, bool)
    cur[:n_peers] = True
    prio = np.zeros(s.max_peers, np.int32)
    if priorities is not None:
        prio[:len(priorities)] = priorities
    s.set_conf(slot, 0, cur, np.zeros(s.max_peers, bool), prio,
               self_priority)
    s.role[slot] = ROLE_CANDIDATE
    s.mark_dirty(slot)
    return slot


def test_vote_round_passes_on_majority():
    """Engine-tallied round (LeaderElection.waitForResults analog): self
    grant + one peer grant = 2/3 majority -> PASSED at the next tick."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = _setup_candidate(e, rec)
        fut = e.begin_vote_round(slot, deadline_ms=10_000)
        e.on_vote_reply(slot, 1, granted=True)
        await e.tick()
        assert fut.done() and fut.result() == "PASSED"

    asyncio.run(run())


def test_vote_round_rejected_by_majority():
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = _setup_candidate(e, rec)
        fut = e.begin_vote_round(slot, deadline_ms=10_000)
        e.on_vote_reply(slot, 1, granted=False)
        e.on_vote_reply(slot, 2, granted=False)
        await e.tick()
        assert fut.done() and fut.result() == "REJECTED"

    asyncio.run(run())


def test_vote_round_priority_veto_and_higher_priority_gate():
    """A rejecting higher-priority peer vetoes instantly; an unresponsive
    higher-priority peer blocks the strict pass until the round deadline
    (LeaderElection.java:515-519,554-572)."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        # peer 1 has priority 5 > self 0; peer 2 same priority
        slot = _setup_candidate(e, rec, priorities=[0, 5, 0])
        fut = e.begin_vote_round(slot, deadline_ms=10_000)
        e.on_vote_reply(slot, 2, granted=True)  # majority, but HP silent
        await e.tick()
        assert not fut.done()  # strict pass gated on the HP peer
        e.clock.t = 10_001  # deadline fires -> passed_on_timeout
        await e.tick()
        assert fut.done() and fut.result() == "PASSED"

        # a rejecting higher-priority peer is an unconditional veto
        rec2 = Recorder()
        slot2 = _setup_candidate(e, rec2, priorities=[0, 5, 0])
        fut2 = e.begin_vote_round(slot2, deadline_ms=20_000)
        e.on_vote_reply(slot2, 2, granted=True)
        e.on_vote_reply(slot2, 1, granted=False)
        await e.tick()
        assert fut2.done() and fut2.result() == "REJECTED"

    asyncio.run(run())


def test_vote_round_timeout_without_majority():
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = _setup_candidate(e, rec)
        fut = e.begin_vote_round(slot, deadline_ms=500)
        await e.tick()
        assert not fut.done()
        e.clock.t = 501
        await e.tick()
        assert fut.done() and fut.result() == "TIMEOUT"

    asyncio.run(run())


def test_vote_round_first_reply_wins_and_end_round():
    """A flip-flopped duplicate reply must not double-count
    (waitForResults responses.putIfAbsent); end_vote_round cancels."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = _setup_candidate(e, rec)
        fut = e.begin_vote_round(slot, deadline_ms=10_000)
        e.on_vote_reply(slot, 1, granted=False)
        e.on_vote_reply(slot, 1, granted=True)  # dup: dropped
        await e.tick()
        assert not fut.done()  # 1 grant (self) + 1 reject: undecided
        e.end_vote_round(slot)
        assert fut.cancelled()

    asyncio.run(run())


def test_vote_round_matches_scalar_oracle_randomized():
    """Differential: the engine's batched tally must agree with the
    ops.reference scalar tally for random grant/reject/priority mixes."""
    from ratis_tpu.ops import reference as ref

    async def run():
        rng = random.Random(7)
        for trial in range(40):
            e = _mk_engine(use_device=True, max_groups=8, max_peers=4)
            rec = Recorder()
            n = rng.choice([3, 4])
            priorities = [rng.choice([0, 0, 0, 3]) for _ in range(n)]
            self_priority = priorities[0]
            slot = _setup_candidate(e, rec, n_peers=n,
                                    priorities=priorities,
                                    self_priority=self_priority)
            fut = e.begin_vote_round(slot, deadline_ms=1000)
            grants = [False] * e.state.max_peers
            rejects = [False] * e.state.max_peers
            grants[0] = True
            for peer in range(1, n):
                verdict = rng.choice(["grant", "reject", "silent"])
                if verdict == "grant":
                    e.on_vote_reply(slot, peer, True)
                    grants[peer] = True
                elif verdict == "reject":
                    e.on_vote_reply(slot, peer, False)
                    rejects[peer] = True
            e.clock.t = 1001  # force the deadline path for determinism
            await e.tick()
            conf_cur = [i < n for i in range(e.state.max_peers)]
            conf_old = [False] * e.state.max_peers
            prio = list(priorities) + [0] * (e.state.max_peers - n)
            _, passed_on_timeout, rejected = ref.tally_votes(
                grants, rejects, conf_cur, conf_old, prio, self_priority)
            assert fut.done(), trial
            expect = ("REJECTED" if rejected
                      else "PASSED" if passed_on_timeout else "TIMEOUT")
            assert fut.result() == expect, (trial, fut.result(), expect)

    asyncio.run(run())


def test_sweep_gate_does_not_delay_election_timeout():
    """The sweep-gated dispatch (events accumulate between sweeps) must
    still fire a follower's election timeout at its deadline: the gate is
    bounded by the earliest armed deadline (_compute_next_sweep), not by
    event arrival."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = e.attach(rec)
        s = e.state
        cur = np.zeros(s.max_peers, bool)
        cur[:3] = True
        s.set_conf(slot, 0, cur, np.zeros(s.max_peers, bool),
                   np.zeros(s.max_peers, np.int32), 0)
        s.role[slot] = ROLE_FOLLOWER
        s.mark_dirty(slot)
        e.on_deadline(slot, 500)
        await e.tick()  # dispatch: upload + arm
        # quiet ticks before the deadline: gated (no dispatch, no timeout)
        before = e.metrics["batched_dispatches"]
        for t in (100, 200, 300):
            e.clock.t = t
            await e.tick()
        assert e.metrics["batched_dispatches"] == before
        assert "timeout" not in rec.events
        # deadline passes: the next tick MUST dispatch and fire
        e.clock.t = 501
        await e.tick()
        assert "timeout" in rec.events

    asyncio.run(run())


def test_sweep_gate_ships_backlog_before_staleness_check():
    """Accumulated (gated) acks must reach the device BEFORE the staleness
    sweep evaluates — a leader steadily receiving acks during the gated
    window must not be declared stale at the next sweep."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        slot = _setup_leader(e, rec, n_peers=3, flush=5)
        await e.tick()  # establish device state
        # acks arrive during the gated window, device unaware until sweep
        for t in range(50, 451, 50):
            e.clock.t = t
            e.on_ack(slot, 1, 5)
            e.on_ack(slot, 2, 5)
            await e.tick()
        # leadership_timeout is 300ms; now=450 with fresh acks at 450:
        # the sweep that finally dispatches must see them and NOT step down
        e.clock.t = 460
        await e.tick()
        assert "stale" not in rec.events
        # silence past the timeout -> stale fires at a later sweep
        e.clock.t = 460 + 301
        await e.tick()
        e.clock.t = 460 + 602
        await e.tick()
        assert "stale" in rec.events

    asyncio.run(run())


def test_vote_round_expires_early_when_all_replied():
    """expire_vote_round (all peers replied or failed) resolves the round
    at the NEXT tick via the timeout-path tally instead of waiting out
    the full round deadline — the outstanding==0 early exit of the
    reference's waitForResults."""
    async def run():
        e = _mk_engine(use_device=True)
        rec = Recorder()
        # higher-priority peer 1 never replies (its RPC failed); peer 2
        # grants -> majority, but the strict pass is gated on peer 1
        slot = _setup_candidate(e, rec, priorities=[0, 5, 0])
        fut = e.begin_vote_round(slot, deadline_ms=60_000)
        e.on_vote_reply(slot, 2, granted=True)
        await e.tick()
        assert not fut.done()  # gated on the silent higher-priority peer
        e.expire_vote_round(slot)  # all RPCs concluded
        e.clock.t += 1
        await e.tick()
        assert fut.done() and fut.result() == "PASSED"

    asyncio.run(run())
