"""Lag & health ledger: the fused device pass (ops/ledger.py) against a
naive Python reference, the engine-attached LagLedger's delta/generation
semantics, the ledger-fed sampler's bit-identical hot-group sketch, the
flat pass-cost scaling that retired the per-division walk, GET /lag +
the flight-recorder ledger block, the grey-follower detector, `shell
lag` across two real processes, and the grey_follower chaos scenario."""

import asyncio
import os
import sys
import time

import numpy as np
import pytest

from minicluster import MiniCluster, fast_properties
from ratis_tpu.engine.engine import QuorumEngine
from ratis_tpu.engine.roles import (ROLE_FOLLOWER, ROLE_LEADER,
                                    ROLE_UNUSED)
from ratis_tpu.ops.ledger import (LAG_BUCKETS, lag_buckets, ledger_pass,
                                  pack_slices, packed_size)


class _Listener:
    def __init__(self, gid):
        self.group_id = gid


def _lag_properties(telemetry: bool = False):
    p = fast_properties()
    p.set("raft.tpu.metrics.http-port", "0")
    # slow background cadences: tests below force samples by hand and
    # must own the ledger's delta window
    p.set("raft.tpu.watchdog.interval", "10s")
    if telemetry:
        p.set("raft.tpu.telemetry.enabled", "true")
        p.set("raft.tpu.telemetry.interval", "100ms")
    return p


# ------------------------------------------------------------ unit layer

def _reference_pass(role, match, commit, applied, cur, old, selfm, ack,
                    pidx, prev_commit, prev_valid, now, threshold,
                    up_window, num_peers):
    """Naive per-(group, peer) Python loops over the same inputs — the
    semantics ops.ledger_pass must vectorize exactly."""
    g, p = match.shape
    gap = np.zeros(g, np.int64)
    delta = np.zeros(g, np.int64)
    worst_lag = np.full(g, -1, np.int64)
    worst_peer = np.full(g, -1, np.int64)
    hist = np.zeros((num_peers, LAG_BUCKETS), np.int64)
    links = np.zeros(num_peers, np.int64)
    up_c = np.zeros(num_peers, np.int64)
    laggy_c = np.zeros(num_peers, np.int64)
    active_c = np.zeros(num_peers, np.int64)
    laggy_active_c = np.zeros(num_peers, np.int64)
    peer_max = np.full(num_peers, -1, np.int64)
    leading = 0
    for i in range(g):
        is_leader = role[i] == ROLE_LEADER
        if is_leader:
            leading += 1
        if role[i] != ROLE_UNUSED:
            gap[i] = max(0, int(commit[i]) - int(applied[i]))
        if is_leader and prev_valid[i]:
            delta[i] = max(0, int(commit[i]) - int(prev_commit[i]))
        for j in range(p):
            valid = ((cur[i, j] or old[i, j]) and not selfm[i, j]
                     and is_leader and pidx[i, j] >= 0)
            if not valid:
                continue
            lag = max(0, int(commit[i]) - int(match[i, j]))
            # first-maximum tie-break, same as argmax in the kernel
            if lag > worst_lag[i]:
                worst_lag[i] = lag
                worst_peer[i] = pidx[i, j]
            w = int(pidx[i, j])
            hist[w, int(lag).bit_length()] += 1
            links[w] += 1
            up = (now - int(ack[i, j])) <= up_window
            laggy = lag >= threshold
            link_active = up and delta[i] > 0
            up_c[w] += up
            laggy_c[w] += laggy
            active_c[w] += link_active
            laggy_active_c[w] += link_active and laggy
            peer_max[w] = max(peer_max[w], lag)
    return {"gap": gap, "delta": delta, "worst_lag": worst_lag,
            "worst_peer": worst_peer, "hist": hist.ravel(),
            "peer_links": links, "peer_up": up_c, "peer_laggy": laggy_c,
            "peer_active": active_c, "peer_laggy_active": laggy_active_c,
            "peer_max_lag": peer_max,
            "scalars": np.array([leading, gap.sum()], np.int64)}


def test_ledger_pass_matches_python_reference():
    """Randomized scalar-vs-vectorized equivalence: every packed section
    of the fused pass equals the naive loop, including unused rows, old
    conf members, unmapped peer columns, and duplicate peer ids."""
    g, p, w = 24, 5, 8
    for seed in range(5):
        rng = np.random.default_rng(seed)
        role = rng.choice([ROLE_UNUSED, ROLE_FOLLOWER, ROLE_LEADER],
                          g).astype(np.int8)
        commit = rng.integers(-1, 200, g).astype(np.int32)
        match = rng.integers(-1, 200, (g, p)).astype(np.int32)
        applied = rng.integers(-1, 200, g).astype(np.int32)
        cur = rng.random((g, p)) < 0.7
        old = rng.random((g, p)) < 0.2
        selfm = np.zeros((g, p), bool)
        selfm[np.arange(g), rng.integers(0, p, g)] = True
        ack = rng.integers(0, 6000, (g, p)).astype(np.int32)
        pidx = rng.integers(-1, w, (g, p)).astype(np.int32)
        prev_commit = rng.integers(-1, 200, g).astype(np.int32)
        prev_valid = rng.random(g) < 0.6
        now, threshold, up_window = 5000, 4, 3000
        packed = np.asarray(ledger_pass(
            role, match, commit, applied, cur, old, selfm, ack, pidx,
            prev_commit, prev_valid, np.int32(now), np.int32(threshold),
            np.int32(up_window), num_peers=w))
        assert packed.shape == (packed_size(g, w),)
        ref = _reference_pass(role, match, commit, applied, cur, old,
                              selfm, ack, pidx, prev_commit, prev_valid,
                              now, threshold, up_window, w)
        sl = pack_slices(g, w)
        for name, want in ref.items():
            got = packed[sl[name]]
            assert (got == want).all(), \
                f"[seed {seed}] section {name}: {got} != {want}"


@pytest.mark.mesh
@pytest.mark.parametrize("n_devices", [2, 8])
def test_ledger_pass_mesh_bit_identical(n_devices):
    """The group-axis-sharded ledger pass (parallel.mesh.sharded_ledger_pass,
    what a mesh engine's telemetry tick runs) must produce the EXACT packed
    int32 vector of the single-device pass on randomized state: every
    aggregation is an integer sum / exact-f32 count / row-local argmax, so
    sharding must not perturb a single bit."""
    import jax

    from ratis_tpu.parallel.mesh import make_group_mesh, sharded_ledger_pass
    if len(jax.devices()) < n_devices:
        pytest.skip(f"need {n_devices} devices")
    g, p, w = 64, 5, 8
    mesh_fn = sharded_ledger_pass(make_group_mesh(n_devices), w)
    for seed in range(5):
        rng = np.random.default_rng(seed)
        role = rng.choice([ROLE_UNUSED, ROLE_FOLLOWER, ROLE_LEADER],
                          g).astype(np.int8)
        commit = rng.integers(-1, 200, g).astype(np.int32)
        match = rng.integers(-1, 200, (g, p)).astype(np.int32)
        applied = rng.integers(-1, 200, g).astype(np.int32)
        cur = rng.random((g, p)) < 0.7
        old = rng.random((g, p)) < 0.2
        selfm = np.zeros((g, p), bool)
        selfm[np.arange(g), rng.integers(0, p, g)] = True
        ack = rng.integers(0, 6000, (g, p)).astype(np.int32)
        pidx = rng.integers(-1, w, (g, p)).astype(np.int32)
        prev_commit = rng.integers(-1, 200, g).astype(np.int32)
        prev_valid = rng.random(g) < 0.6
        args = (role, match, commit, applied, cur, old, selfm, ack, pidx,
                prev_commit, prev_valid, np.int32(5000), np.int32(4),
                np.int32(3000))
        plain = np.asarray(ledger_pass(*args, num_peers=w))
        sharded = np.asarray(mesh_fn(*args))
        assert (plain == sharded).all(), f"[seed {seed}] mesh-on != mesh-off"


def test_ledger_sample_mesh_engine_matches_single():
    """LagLedger.sample() through a mesh engine (sharded _jitted_pass) vs
    the same host mirrors through a plain engine: identical LedgerSample
    arrays — the telemetry plane must not notice the mesh."""
    from ratis_tpu.parallel.mesh import make_group_mesh
    e1 = _leader_engine(24)
    e2 = QuorumEngine(max_groups=e1.state.capacity, max_peers=8,
                      mesh=make_group_mesh(2), name="ledger-mesh")
    try:
        # mirror e1's scripted state into e2 wholesale (same slots)
        for name in ("role", "match_index", "commit_index", "applied_index",
                     "conf_cur", "conf_old", "self_mask", "last_ack_ms",
                     "peer_index", "alloc_gen", "pending_count"):
            getattr(e2.state, name)[...] = getattr(e1.state, name)
        e2.state.active = set(e1.state.active)
        e2.ledger.peer_names = list(e1.ledger.peer_names)
        e2.ledger._peer_idx = dict(e1.ledger._peer_idx)
        e2.clock = e1.clock
        s1 = e1.ledger.sample()
        s2 = e2.ledger.sample()
        for field in ("gap", "delta", "worst_lag", "worst_peer", "hist",
                      "peer_links", "peer_up", "peer_laggy", "peer_active",
                      "peer_laggy_active", "peer_max_lag"):
            a, b = getattr(s1, field), getattr(s2, field)
            assert (a == b).all(), f"section {field} differs under mesh"
        assert (s1.leading, s1.gap_total) == (s2.leading, s2.gap_total)
    finally:
        e1.ledger.unregister()
        e1._m.unregister()
        e2.ledger.unregister()
        e2._m.unregister()


def test_lag_histogram_bucket_units():
    """Bucket 0 = caught up; bucket i >= 1 = lag in [2^(i-1), 2^i) —
    exact at the power-of-two boundaries (a float log would misfile)."""
    lags = np.array([0, 1, 2, 3, 4], np.int32)
    assert lag_buckets(lags).tolist() == [0, 1, 2, 2, 3]
    for k in range(1, 30):
        edge = np.array([(1 << k) - 1, 1 << k], np.int32)
        assert lag_buckets(edge).tolist() == [k, k + 1]
    # any int32 lag stays inside the table
    assert int(lag_buckets(np.int32(2**31 - 1))) == LAG_BUCKETS - 1


def _leader_engine(num_groups: int, peers=("s1", "s2")) -> QuorumEngine:
    """An engine with every slot a 3-member leader wired into the dense
    peer table, commits at 0 — the shape the live server produces."""
    e = QuorumEngine(max_groups=num_groups, max_peers=8,
                     scalar_fallback_threshold=10**9, use_device=False)
    s = e.state
    for i in range(num_groups):
        slot = e.attach(_Listener(f"g{i:04d}"))
        cur = np.zeros(8, bool)
        cur[:len(peers) + 1] = True
        s.set_conf(slot, 0, cur, np.zeros(8, bool),
                   np.zeros(8, np.int32), 0)
        s.role[slot] = ROLE_LEADER
        s.commit_index[slot] = 0
        s.match_index[slot, :len(peers) + 1] = 0
        s.applied_index[slot] = 0
        s.last_ack_ms[slot, :len(peers) + 1] = e.clock.now_ms()
        pidx = np.full(8, -1, np.int32)
        for j, peer in enumerate(peers):
            pidx[j + 1] = e.ledger.peer_for(peer)
        s.peer_index[slot] = pidx
    return e


def test_ledger_sample_delta_and_generation_semantics():
    """Engine-level LagLedger: per-group worst lag / gap, the pending
    mirror, commit deltas anchored at first sight, and the allocation-
    generation guard that keeps a reused slot from inheriting the old
    tenant's baseline."""
    e = _leader_engine(4)
    st = e.state
    st.commit_index[0] = 10
    st.match_index[0, 1] = 3           # s1 is 7 behind on slot 0
    st.match_index[0, 2] = 8           # s2 only 2 behind
    st.applied_index[0] = 6            # apply backlog of 4
    st.pending_count[0] = 5
    s1 = e.ledger.sample()
    assert s1.leading == 4
    assert int(s1.worst_lag[0]) == 7
    assert s1.peer_names[int(s1.worst_peer[0])] == "s1"
    assert int(s1.gap[0]) == 4 and s1.gap_total == 4
    assert int(s1.pending[0]) == 5
    # first sight anchors: commits existed before the pass, delta 0
    assert (s1.delta == 0).all()
    assert s1.fetch_ms >= 0.0 and e.ledger.samples.count == 1

    st.commit_index[0] = 25
    st.commit_index[1] = 2
    s2 = e.ledger.sample()
    assert int(s2.delta[0]) == 15 and int(s2.delta[1]) == 2
    assert (s2.delta[2:] == 0).all()

    # slot reuse: release + re-attach bumps alloc_gen, so the new
    # tenant's first pass anchors instead of reading the old baseline
    e.detach(0)
    slot = e.attach(_Listener("tenant2"))
    assert slot == 0
    st.role[0] = ROLE_LEADER
    st.commit_index[0] = 1000
    s3 = e.ledger.sample()
    assert int(s3.delta[0]) == 0
    s4 = e.ledger.sample()
    assert int(s4.delta[0]) == 0      # still flat, no phantom delta
    # a demoted slot drops its baseline: leader again -> anchor again
    st.role[1] = ROLE_FOLLOWER
    e.ledger.sample()
    st.role[1] = ROLE_LEADER
    st.commit_index[1] += 50
    assert int(e.ledger.sample().delta[1]) == 0


def test_sampler_sketch_bit_identical_to_legacy_walk():
    """The ledger-fed TelemetrySampler must feed the Metwally sketch the
    EXACT offers the retired per-division walk produced — same keys,
    counts, error bounds, and pending aux — across anchoring, deltas,
    pending-only groups, leadership flips, and division teardown."""
    import types

    from ratis_tpu.conf.properties import RaftProperties
    from ratis_tpu.metrics.registry import MetricRegistries
    from ratis_tpu.metrics.timeseries import (SpaceSavingSketch,
                                              TelemetrySampler,
                                              legacy_division_walk)

    e = _leader_engine(6)
    st = e.state
    gids = [e._listeners[i].group_id for i in range(6)]

    class _Log:
        def __init__(self, slot):
            self.slot = slot

        def get_last_committed_index(self):
            return st.commit_index[self.slot]

    def _div(slot, gid):
        d = types.SimpleNamespace(
            group_id=gid,
            state=types.SimpleNamespace(log=_Log(slot)),
            leader_ctx=types.SimpleNamespace(pending={}))
        d.is_leader = lambda slot=slot: st.role[slot] == ROLE_LEADER
        return d

    srv = types.SimpleNamespace(
        peer_id="lagledger-sketch-test", properties=RaftProperties(),
        engine=e, watchdog=None,
        replication=types.SimpleNamespace(metrics={}),
        divisions={gid: _div(i, gid) for i, gid in enumerate(gids)})
    sampler = TelemetrySampler(srv, interval_s=1.0, window_s=10.0,
                               top_k=8)
    ref_sketch = SpaceSavingSketch(8)
    last_commit: dict = {}

    def _set_pending(slot, n):
        st.pending_count[slot] = n
        srv.divisions[gids[slot]].leader_ctx.pending = {
            i: None for i in range(n)}

    def _both_pass():
        legacy_division_walk(srv, last_commit, ref_sketch)
        sampler.sample()
        assert sampler.sketch.total == ref_sketch.total
        assert sampler.sketch._entries == ref_sketch._entries

    _set_pending(2, 3)                # pending-only group rides along
    _both_pass()                      # pass 1: everyone anchors
    st.commit_index[0] += 7
    st.commit_index[1] += 2
    _both_pass()                      # pass 2: real deltas
    st.role[1] = ROLE_FOLLOWER       # deposed: both paths drop it
    st.commit_index[0] += 1
    _both_pass()
    st.role[1] = ROLE_LEADER         # re-elected: both re-anchor
    st.commit_index[1] += 100
    _both_pass()
    st.commit_index[1] += 4          # post-anchor delta attributes again
    _set_pending(2, 0)
    _both_pass()
    # division teardown: gone from both views, then a new tenant anchors
    del srv.divisions[gids[5]]
    e.detach(5)
    _both_pass()
    slot = e.attach(_Listener("fresh"))
    st.role[slot] = ROLE_LEADER
    st.commit_index[slot] = 500
    srv.divisions["fresh"] = _div(slot, "fresh")
    _both_pass()
    MetricRegistries.global_registries().remove(sampler._info)


# --------------------------------------------------------- pass cost

def test_ledger_pass_cost_flat_in_group_count():
    """The pass-cost drop that retired the per-division walk: growing
    the fleet 16x (64 -> 1024 groups) multiplies the walk's Python cost
    ~linearly while the fused-pass sample stays near-flat — O(1) Python
    plus one device dispatch whose cost the group axis barely moves."""
    from ratis_tpu.metrics.timeseries import legacy_division_walk

    def _fake_server(e, n):
        import types
        st = e.state

        class _Log:
            def __init__(self, slot):
                self.slot = slot

            def get_last_committed_index(self):
                return st.commit_index[self.slot]

        srv = types.SimpleNamespace()
        srv.divisions = {}
        for i in range(n):
            gid = e._listeners[i].group_id
            d = types.SimpleNamespace(
                group_id=gid,
                state=types.SimpleNamespace(log=_Log(i)),
                leader_ctx=types.SimpleNamespace(pending={}))
            d.is_leader = lambda: True
            srv.divisions[gid] = d
        return srv

    def _best(f, n=10):
        best = None
        for _ in range(n):
            t0 = time.perf_counter()
            f()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    costs = {}
    for n in (64, 1024):
        e = _leader_engine(n)
        for _ in range(3):
            e.ledger.sample()       # warm the jit cache
        srv = _fake_server(e, n)
        last: dict = {}
        legacy_division_walk(srv, last)
        costs[n] = (_best(e.ledger.sample),
                    _best(lambda: legacy_division_walk(srv, last)))
    sample_ratio = costs[1024][0] / max(1e-9, costs[64][0])
    walk_ratio = costs[1024][1] / max(1e-9, costs[64][1])
    # 16x more groups: the walk pays ~16x (allow noise down to 6x), the
    # ledger-fed sample must stay well under half the walk's growth
    assert walk_ratio > 6.0, (costs, walk_ratio)
    assert sample_ratio < walk_ratio / 2, (costs, sample_ratio,
                                           walk_ratio)
    assert costs[1024][0] < 0.020, f"1024-group sample too slow: {costs}"


# ------------------------------------------------- live-cluster endpoints

def test_lag_endpoint_and_flight_recorder_block():
    """GET /lag serves the ledger (peer health scores, laggard groups),
    scrape_cluster_lag degrades per-server, and the flight recorder's
    snapshot embeds the same ledger block."""

    async def body():
        from ratis_tpu.metrics.aggregate import (fetch_json,
                                                 scrape_cluster_lag)
        cluster = MiniCluster(3, properties=_lag_properties(telemetry=True))
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            for _ in range(5):
                assert (await cluster.send_write()).success
            srv = cluster.servers[leader.member_id.peer_id]
            payload = await fetch_json(srv.metrics_http.address, "/lag")
            for key in ("peer", "pid", "now_ms", "lagThreshold",
                        "upWindowMs", "leading", "gapTotal", "fetchMs",
                        "peers", "groups"):
                assert key in payload, payload
            assert payload["leading"] >= 1
            assert payload["lagThreshold"] >= 1
            peers = {p["peer"]: p for p in payload["peers"]}
            assert len(peers) == 2         # both followers watched
            for p in peers.values():
                assert p["links"] >= 1
                assert 0.0 <= p["score"] <= 1.0
                assert sum(p["hist"].values()) == p["links"]
            # caught-up cluster: laggard list is empty or small-lag only
            for g in payload["groups"]:
                assert g["lag"] > 0 and "shard" in g

            out = await scrape_cluster_lag(
                [s.metrics_http.address
                 for s in cluster.servers.values()])
            assert len(out["servers"]) == 3
            assert not out.get("unreachable")
            dead = await scrape_cluster_lag(
                [srv.metrics_http.address, "127.0.0.1:1"], timeout_s=2.0)
            assert len(dead["servers"]) == 1
            assert dead["unreachable"][0]["address"] == "127.0.0.1:1"

            # ?n= caps the laggard list
            info = srv.lag_info(query={"n": ["1"]})
            assert len(info["groups"]) <= 1

            fr = await fetch_json(srv.metrics_http.address,
                                  "/flightrecorder")
            assert fr["lag_ledger"] is not None
            assert fr["lag_ledger"]["peer"] == str(srv.peer_id)
            assert "peers" in fr["lag_ledger"]
        finally:
            await cluster.close()

    asyncio.run(body())


def test_grey_follower_detector_episode():
    """A follower that keeps acking (inside the up-window) while lagging
    on every advancing group opens ONE grey episode, and healing closes
    it with a grey-recovered event carrying the same fault id."""
    from ratis_tpu.server.watchdog import (KIND_GREY_FOLLOWER,
                                           KIND_GREY_RECOVERED)
    from ratis_tpu.util import injection

    async def body():
        cluster = MiniCluster(3, properties=_lag_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            srv = cluster.servers[leader.member_id.peer_id]
            wd = srv.watchdog
            # sensitize: 1 entry of lag on 1 active group is grey, and a
            # 60s up-window keeps the blackholed follower counting as up
            srv.engine.ledger.lag_threshold = 1
            srv.engine.ledger.up_window_ms = 60_000
            wd.grey_fraction = 0.5
            wd.grey_min_groups = 1
            wd.grey_rounds = 1
            followers = [d for d in cluster.divisions()
                         if d.is_follower()]
            victim = followers[0].member_id.peer_id

            async def drop(local_id, remote_id, *args):
                if str(local_id).startswith(str(victim)):
                    raise RuntimeError("injected: grey follower")

            injection.put(injection.APPEND_ENTRIES, drop)
            grey = []
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline and not grey:
                assert (await cluster.send_write()).success
                wd.sample()
                grey = [e for e in wd.events()
                        if e["kind"] == KIND_GREY_FOLLOWER]
                await asyncio.sleep(0.05)
            assert grey, wd.events()
            assert str(victim) in grey[0]["detail"]
            assert grey[0]["fault"].startswith("grey-")

            injection.clear()
            recovered = []
            deadline = asyncio.get_event_loop().time() + 20.0
            while (asyncio.get_event_loop().time() < deadline
                   and not recovered):
                await cluster.send_write()
                await asyncio.sleep(0.1)
                wd.sample()
                recovered = [e for e in wd.events()
                             if e["kind"] == KIND_GREY_RECOVERED]
            assert recovered, wd.events()
            # episode pairing: the recovery carries the SAME fault id
            assert recovered[0]["fault"] == grey[0]["fault"]
            # one event per episode, not one per sample
            assert len([e for e in wd.events()
                        if e["kind"] == KIND_GREY_FOLLOWER]) == 1
        finally:
            injection.clear()
            await cluster.close()

    asyncio.run(body())


# ---------------------------------------------------- shell lag rendering

def _lag_child_script() -> str:
    """One child process: an in-process trio, a few committed writes,
    its leader's endpoint printed for the parent to scrape."""
    return """
import asyncio, sys
sys.path.insert(0, %r)
from minicluster import MiniCluster, fast_properties

async def main():
    p = fast_properties()
    p.set("raft.tpu.metrics.http-port", "0")
    cluster = MiniCluster(3, properties=p)
    await cluster.start()
    leader = await cluster.wait_for_leader()
    for _ in range(5):
        await cluster.send_write()
    srv = cluster.servers[leader.member_id.peer_id]
    print("ENDPOINT " + srv.metrics_http.address, flush=True)
    while True:
        await cluster.send_write()
        await asyncio.sleep(0.02)

asyncio.run(main())
""" % os.path.dirname(os.path.abspath(__file__))


@pytest.mark.mp
def test_shell_lag_renders_matrix_from_two_processes(capsys):
    """Acceptance: `shell lag` renders the peers x leaders health matrix
    from >= 2 real processes (each child hosts its own cluster)."""
    import subprocess

    async def body():
        import argparse
        from ratis_tpu.shell.cli import cmd_lag
        procs = []
        endpoints = []
        try:
            for _ in range(2):
                proc = subprocess.Popen(
                    [sys.executable, "-c", _lag_child_script()],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True)
                procs.append(proc)
            for proc in procs:
                line = proc.stdout.readline()
                assert line.startswith("ENDPOINT "), line
                endpoints.append(line.split()[1])
            rc = await cmd_lag(argparse.Namespace(
                endpoints=",".join(endpoints), timeout=10.0))
            assert rc == 0
        finally:
            for proc in procs:
                proc.kill()
        out = capsys.readouterr().out
        assert "-- lag @" in out and "score = healthy share" in out
        lines = out.splitlines()
        header = next(i for i, l in enumerate(lines)
                      if l.startswith("LEADER"))
        rows = [l.split() for l in lines[header + 1:]
                if l and not l.startswith(("laggard", " "))]
        assert len(rows) == 2          # one matrix row per scraped leader
        for row in rows:
            assert int(row[1]) >= 1    # LEADS
            # every rendered score cell is healthy or absent
            assert all(c in ("-", "1.00") for c in row[3:]), row

        # an unreachable endpoint degrades to rc=1, never a traceback
        rc = await cmd_lag(argparse.Namespace(
            endpoints="127.0.0.1:1", timeout=2.0))
        assert rc == 1
        assert "UNREACHABLE" in capsys.readouterr().out

    asyncio.run(body())


# ------------------------------------------------------- chaos scenario

@pytest.mark.chaos
def test_grey_follower_scenario():
    """The grey_follower chaos scenario: latency+jitter on one follower
    (never a drop — the link stays up) must raise a grey-follower
    episode on a live leader, pair it with grey-recovered after the
    heal, and keep the zero-lost-acks / exactly-once oracles green."""
    from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties
    from ratis_tpu.chaos.scenario import run_scenario
    from ratis_tpu.chaos.scenarios import build_scenario

    async def main():
        p = chaos_properties(8, seed=17)
        cluster = ChaosCluster(3, 8, properties=p, sm="counter", seed=17)
        await cluster.start()
        try:
            cfg = {"servers": 3, "groups": 8, "writers": 4,
                   "active_groups": 8, "sm": "counter",
                   "convergence_s": 30.0, "recovery_s": 60.0,
                   "min_acked": 20}
            scenario = build_scenario("grey_follower", 17, cfg)
            result = await run_scenario(cluster, scenario)
            assert result.passed, (
                f"[seed 17] grey_follower failed: {result.error}\n"
                f"journal: {result.journal}")
            assert result.checks.get("grey_events", 0) >= 1
            assert (result.checks.get("grey_recovered", 0)
                    >= result.checks.get("grey_events", 0))
            assert result.acked > 20
        finally:
            await cluster.close()

    asyncio.run(main())
