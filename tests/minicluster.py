"""MiniCluster: in-process multi-peer test harness.

Capability parity with the reference MiniRaftCluster
(ratis-server/src/test/.../impl/MiniRaftCluster.java:86): all peers in one
process over the simulated transport, leader queries, kill/restart, peer
add/remove, block/partition fault injection, and a run_with_new_cluster
driver.  asyncio-native; sync tests wrap with ``asyncio.run``.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Callable, Optional

from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
from ratis_tpu.models.counter import CounterStateMachine
from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                           NotLeaderException, RaftException)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer, RaftPeerRole
from ratis_tpu.protocol.requests import (RaftClientReply, RaftClientRequest,
                                         TypeCase, read_request_type,
                                         stale_read_request_type,
                                         write_request_type)
from ratis_tpu.server.division import Division
from ratis_tpu.server.server import RaftServer
from ratis_tpu.server.statemachine import StateMachine
from ratis_tpu.transport.simulated import (SimulatedNetwork,
                                           SimulatedTransportFactory)

DEFAULT_TIMEOUT = 10.0


def fast_properties() -> RaftProperties:
    p = RaftProperties()
    RaftServerConfigKeys.Rpc.set_timeout(p, "100ms", "200ms")
    p.set("raft.tpu.engine.tick-interval", "5ms")
    RaftServerConfigKeys.Log.set_use_memory(p, True)
    import os
    if os.environ.get("RATIS_TPU_TEST_BATCHED"):
        # CI knob: force EVERY cluster suite through the jitted batched
        # engine path (scalar fallback disabled).
        p.set("raft.tpu.engine.scalar-fallback-threshold", "0")
    return p


def batched_properties() -> RaftProperties:
    """fast_properties but every engine tick runs the jitted batched kernel
    (scalar_fallback_threshold=0): the TPU-native execution mode under the
    same cluster scenarios."""
    p = fast_properties()
    p.set("raft.tpu.engine.scalar-fallback-threshold", "0")
    return p


_handed_out_ports: set[int] = set()


def free_port() -> int:
    """Allocate a port the kernel considers free, never handing the same port
    out twice in this process — bind-then-close lets the kernel recycle a
    just-closed port for the next bind(0), which raced when a cluster
    allocated RPC + datastream ports for many peers."""
    import socket
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port not in _handed_out_ports:
            _handed_out_ports.add(port)
            return port


class MiniCluster:
    def __init__(self, num_servers: int = 3, num_listeners: int = 0,
                 properties: Optional[RaftProperties] = None,
                 sm_factory: Callable[[], StateMachine] = CounterStateMachine,
                 log_factory=None, storage_root: Optional[str] = None,
                 rpc_type: str = "SIMULATED"):
        self.properties = (properties or fast_properties()).clone()
        self.storage_root = storage_root
        if storage_root is not None:
            RaftServerConfigKeys.Log.set_use_memory(self.properties, False)
        self.rpc_type = rpc_type.upper()
        if self.rpc_type in ("GRPC", "NETTY", "TCP"):
            from ratis_tpu.transport import grpc as grpc_transport  # registers
            from ratis_tpu.transport import tcp as tcp_transport  # registers
            from ratis_tpu.transport.base import TransportFactory
            self.network = None
            self.factory = TransportFactory.get(self.rpc_type)
        else:
            self.network = SimulatedNetwork()
            self.factory = SimulatedTransportFactory(self.network)
        self.sm_factory = sm_factory
        self.log_factory = log_factory

        peers = []
        for i in range(num_servers + num_listeners):
            role = (RaftPeerRole.LISTENER if i >= num_servers
                    else RaftPeerRole.FOLLOWER)
            address = (f"127.0.0.1:{free_port()}" if self.network is None
                       else f"sim:s{i}")
            # DataStream rides real TCP regardless of the RPC transport
            peers.append(RaftPeer(RaftPeerId.value_of(f"s{i}"),
                                  address=address,
                                  datastream_address=f"127.0.0.1:{free_port()}",
                                  startup_role=role))
        self.group = RaftGroup.value_of(RaftGroupId.random_id(), peers)
        self.servers: dict[RaftPeerId, RaftServer] = {}
        self._stopped: dict[RaftPeerId, RaftPeer] = {}
        self._call_ids = itertools.count(1)
        self.client_id = ClientId.random_id()

    # ------------------------------------------------------------ lifecycle

    def _new_server(self, peer: RaftPeer) -> RaftServer:
        props = self.properties
        if self.storage_root is not None:
            props = props.clone()
            RaftServerConfigKeys.set_storage_dir(
                props, f"{self.storage_root}/{peer.id}")
        return RaftServer(
            peer.id, peer.address,
            state_machine_registry=lambda gid: self.sm_factory(),
            properties=props, transport_factory=self.factory,
            group=self.group, log_factory=self.log_factory)

    async def start(self) -> None:
        for peer in self.group.peers:
            server = self._new_server(peer)
            self.servers[peer.id] = server
        await asyncio.gather(*(s.start() for s in self.servers.values()))

    async def close(self) -> None:
        await asyncio.gather(*(s.close() for s in self.servers.values()),
                             return_exceptions=True)
        self.servers.clear()

    async def kill_server(self, peer_id: RaftPeerId) -> None:
        server = self.servers.pop(peer_id)
        self._stopped[peer_id] = self.group.get_peer(peer_id)
        await server.close()

    async def restart_server(self, peer_id: RaftPeerId) -> RaftServer:
        peer = self._stopped.pop(peer_id, None) or self.group.get_peer(peer_id)
        server = self._new_server(peer)
        self.servers[peer_id] = server
        await server.start()
        return server

    # ------------------------------------------------------------- queries

    def divisions(self) -> list[Division]:
        out = []
        for s in self.servers.values():
            if self.group.group_id in s.divisions:
                out.append(s.divisions[self.group.group_id])
        return out

    def leaders(self) -> list[Division]:
        return [d for d in self.divisions() if d.is_leader()]

    async def wait_for_leader(self, timeout: float = DEFAULT_TIMEOUT) -> Division:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            leaders = self.leaders()
            # exactly one leader at the highest term counts
            if leaders:
                top = max(leaders, key=lambda d: d.state.current_term)
                others = [d for d in leaders if d is not top]
                if all(d.state.current_term < top.state.current_term
                       for d in others):
                    return top
            await asyncio.sleep(0.02)
        raise TimeoutError(f"no leader after {timeout}s; roles: "
                           f"{[(str(d.member_id), d.role.name, d.state.current_term) for d in self.divisions()]}")

    async def wait_applied(self, index: int, timeout: float = DEFAULT_TIMEOUT,
                           divisions: Optional[list[Division]] = None) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        divs = divisions if divisions is not None else self.divisions()
        while asyncio.get_event_loop().time() < deadline:
            if all(d.applied_index >= index for d in divs):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"applied index {index} not reached: "
            f"{[(str(d.member_id), d.applied_index) for d in divs]}")

    def new_client(self, retry_policy=None, group: Optional[RaftGroup] = None):
        """A full RaftClient bound to this cluster's transport."""
        from ratis_tpu.client import RaftClient
        return (RaftClient.builder()
                .set_raft_group(group or self.group)
                .set_transport(
                    self.factory.new_client_transport(self.properties))
                .set_retry_policy(retry_policy)
                .set_properties(self.properties)
                .build())

    async def add_new_server(self, peer: RaftPeer,
                             group: Optional[RaftGroup] = None) -> RaftServer:
        """Start a server that (by default) hosts no group yet — the
        bootstrap target for group-add + setConfiguration staging."""
        server = RaftServer(
            peer.id, peer.address,
            state_machine_registry=lambda gid: self.sm_factory(),
            properties=self.properties, transport_factory=self.factory,
            group=group, log_factory=self.log_factory)
        self.servers[peer.id] = server
        await server.start()
        return server

    # -------------------------------------------------------------- client

    def _request(self, server_id: RaftPeerId, message: bytes,
                 type_case: TypeCase,
                 call_id: Optional[int] = None,
                 group_id: Optional[RaftGroupId] = None) -> RaftClientRequest:
        return RaftClientRequest(self.client_id, server_id,
                                 group_id or self.group.group_id,
                                 call_id if call_id is not None
                                 else next(self._call_ids),
                                 Message.value_of(message), type=type_case)

    async def send(self, message: bytes, type_case: Optional[TypeCase] = None,
                   server_id: Optional[RaftPeerId] = None,
                   timeout: float = DEFAULT_TIMEOUT,
                   call_id: Optional[int] = None,
                   group_id: Optional[RaftGroupId] = None) -> RaftClientReply:
        """Minimal failover client: follow NotLeaderException hints, retry on
        not-ready (the full RaftClient lands with the client milestone)."""
        type_case = type_case or write_request_type()
        client = self.factory.new_client_transport(self.properties)
        target = server_id or next(iter(self.servers))
        deadline = asyncio.get_event_loop().time() + timeout
        last_exc: Optional[Exception] = None
        # ONE call id across every retry: an attempt that was appended by
        # a then-deposed leader can still commit later, and only a stable
        # (clientId, callId) lets the retry cache dedupe the re-send (a
        # fresh id per attempt double-applied ~1/full-suite run)
        if call_id is None:
            call_id = next(self._call_ids)
        while asyncio.get_event_loop().time() < deadline:
            server = self.servers.get(target)
            if server is None:
                target = next(iter(self.servers))
                continue
            req = self._request(target, message, type_case, call_id, group_id)
            try:
                reply = await client.send_request(server.address, req)
            except (RaftException, TimeoutError) as e:
                last_exc = e
                await asyncio.sleep(0.05)
                continue
            if reply.success:
                return reply
            exc = reply.exception
            if isinstance(exc, NotLeaderException):
                if exc.suggested_leader is not None:
                    target = exc.suggested_leader.id
                else:
                    ids = list(self.servers)
                    target = ids[(ids.index(target) + 1) % len(ids)] \
                        if target in ids else ids[0]
                await asyncio.sleep(0.02)
                last_exc = exc
                continue
            if isinstance(exc, LeaderNotReadyException):
                await asyncio.sleep(0.02)
                last_exc = exc
                continue
            return reply  # a real failure: surface it
        raise TimeoutError(f"client retries exhausted; last: {last_exc}")

    async def send_write(self, message: bytes = b"INCREMENT") -> RaftClientReply:
        return await self.send(message, write_request_type())

    async def send_read(self, message: bytes = b"GET") -> RaftClientReply:
        return await self.send(message, read_request_type())


def run_with_new_cluster(num_servers: int, test, **kwargs):
    """Reference's MiniRaftCluster.runWithNewCluster(:120-170) equivalent."""

    async def _main():
        cluster = MiniCluster(num_servers, **kwargs)
        await cluster.start()
        try:
            await test(cluster)
        finally:
            await cluster.close()

    asyncio.run(_main())
