"""Appointed-leader bootstrap (Division.bootstrap_as_leader): the
deployment mode that installs leadership on a fresh group with no vote
round — mass multi-raft bring-up without O(groups x peers) elections
(reference analog: operator-chosen initial leaders via startup roles /
priorities, LeaderElection.java:80)."""

import asyncio

import pytest

from minicluster import MiniCluster, batched_properties, fast_properties, \
    run_with_new_cluster
from ratis_tpu.conf.keys import RaftServerConfigKeys
from ratis_tpu.protocol.exceptions import RaftException


def _quiet_properties(batched: bool = False):
    """Election timeouts long enough that no randomized election can fire
    before the test's bootstrap call — the fresh-cluster window the
    deployment mode is FOR (the operator appoints before traffic)."""
    p = batched_properties() if batched else fast_properties()
    RaftServerConfigKeys.Rpc.set_timeout(p, "5s", "10s")
    return p


def test_bootstrap_installs_leadership_and_serves_writes():
    async def body(cluster: MiniCluster):
        d = next(iter(cluster.servers.values())) \
            .divisions[cluster.group.group_id]
        await d.bootstrap_as_leader()
        assert d.is_leader() and d.state.current_term == 1
        # followers adopt the term from the first heartbeat/append; the
        # startup entry commits through real replication
        assert (await cluster.send_write()).success
        for x in cluster.divisions():
            assert x.state.current_term == 1
        leaders = [x for x in cluster.divisions() if x.is_leader()]
        assert leaders == [d]

    run_with_new_cluster(3, body, properties=_quiet_properties())


def test_bootstrap_refuses_non_fresh_group():
    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        assert (await cluster.send_write()).success
        # every division now has history (term > 0 / entries / a leader):
        # the bootstrap guard must refuse all of them
        for d in cluster.divisions():
            with pytest.raises(RaftException):
                await d.bootstrap_as_leader()

    run_with_new_cluster(3, body, properties=fast_properties())


def test_bootstrap_refuses_non_voting_member():
    async def body(cluster: MiniCluster):
        listener = next(
            d for s in cluster.servers.values()
            for d in s.divisions.values() if d.is_listener())
        # a LISTENER-role division trips the follower/fresh-state guard
        with pytest.raises(RaftException, match="fresh"):
            await listener.bootstrap_as_leader()
        # the deeper invariant: even a FOLLOWER-role division that the
        # configuration lists as non-voting must be refused (white-box:
        # flip the role so the first guard passes and the voting guard is
        # the one that fires)
        from ratis_tpu.server.division import RaftPeerRole
        listener.role = RaftPeerRole.FOLLOWER
        with pytest.raises(RaftException, match="non-voting"):
            await listener.bootstrap_as_leader()
        listener.role = RaftPeerRole.LISTENER

    run_with_new_cluster(2, body, properties=_quiet_properties(),
                         num_listeners=1)


def test_bootstrap_refuses_non_appointee():
    """Double-appointment defense: only the configuration's deterministic
    appointee (highest priority, then lowest peer id) may bootstrap — a
    second appointee on the same fresh group fails CLOSED instead of
    becoming a second term-1 leader."""
    async def body(cluster: MiniCluster):
        divisions = {str(d.member_id.peer_id): d for d in cluster.divisions()}
        appointee = divisions["s0"]  # lowest peer id, equal priorities
        for name, d in divisions.items():
            if name == "s0":
                continue
            with pytest.raises(RaftException, match="appointee"):
                await d.bootstrap_as_leader()
            assert d.is_follower() and d.state.current_term == 0
        # the legitimate appointee still bootstraps and serves
        await appointee.bootstrap_as_leader()
        assert appointee.is_leader()
        assert (await cluster.send_write()).success

    run_with_new_cluster(3, body, properties=_quiet_properties())


def test_bootstrap_survives_batched_engine_mode():
    async def body(cluster: MiniCluster):
        d = next(iter(cluster.servers.values())) \
            .divisions[cluster.group.group_id]
        await d.bootstrap_as_leader()
        assert (await cluster.send_write()).success
        # a later real failover still works: kill the appointee
        await cluster.kill_server(d.member_id.peer_id)
        reply = await cluster.send(b"INCREMENT", timeout=30.0)
        assert reply.success

    run_with_new_cluster(3, body, properties=_quiet_properties(batched=True))
