"""Cluster observability plane: engine-depth metrics, the per-server
introspection endpoint (/metrics /health /divisions /events), Prometheus
exposition conformance, the stall watchdog, the shell ``health``
subcommand, and cross-process aggregation (metrics/aggregate.py + merged
Perfetto traces)."""

import asyncio
import json
import threading

import pytest

from minicluster import MiniCluster, batched_properties, fast_properties
from ratis_tpu.metrics.registry import (MetricRegistries, MetricRegistryInfo,
                                        RatisMetricRegistry, labeled)
from ratis_tpu.metrics.prometheus import MetricsHttpServer, render_text


def _obs_properties(batched: bool = False):
    p = batched_properties() if batched else fast_properties()
    p.set("raft.tpu.metrics.http-port", "0")
    p.set("raft.tpu.watchdog.interval", "150ms")
    return p


# --------------------------------------------- exposition conformance

def _private_regs() -> MetricRegistries:
    return MetricRegistries()


def test_render_escapes_label_values():
    regs = _private_regs()
    nasty = 's0@"grp\\one"\nline2'
    reg = regs.create(MetricRegistryInfo(nasty, "ratis", "test", "esc"))
    reg.counter("numThings").inc(3)
    text = render_text(regs)
    line = next(l for l in text.splitlines() if l.startswith("ratis_test_"))
    # backslash, quote, and newline all escaped; raw newline never leaks
    assert r'\\one' in line
    assert r'\"grp' in line
    assert r'\n' in line
    assert "\n" not in line  # the sample stays one exposition line


def test_render_counters_get_total_suffix_and_type():
    regs = _private_regs()
    reg = regs.create(MetricRegistryInfo("p", "ratis", "test", "ct"))
    reg.counter("numRequests").inc(7)
    reg.gauge("depth", lambda: 5)
    text = render_text(regs)
    assert "# TYPE ratis_test_numRequests_total counter" in text
    assert 'ratis_test_numRequests_total{member="p"} 7' in text
    # gauges keep their bare name
    assert "# TYPE ratis_test_depth gauge" in text
    assert 'ratis_test_depth{member="p"} 5' in text


def test_render_labeled_counters_merge_member_label():
    regs = _private_regs()
    reg = regs.create(MetricRegistryInfo("p", "ratis", "engine", "lc"))
    reg.counter(labeled("dispatches", reason="sweep")).inc(2)
    reg.counter(labeled("dispatches", reason="upload")).inc(1)
    text = render_text(regs)
    assert ('ratis_engine_dispatches_total{member="p",reason="sweep"} 2'
            in text)
    assert ('ratis_engine_dispatches_total{member="p",reason="upload"} 1'
            in text)
    # one family, one TYPE line
    assert text.count("# TYPE ratis_engine_dispatches_total counter") == 1


def test_render_groups_families_across_members():
    """All samples of one family must be consecutive (exposition 0.0.4);
    the old per-registry walk interleaved families when two members
    shared a catalog."""
    regs = _private_regs()
    for member in ("a", "b"):
        reg = regs.create(MetricRegistryInfo(member, "ratis", "test", "g"))
        reg.counter("numX").inc()
        reg.gauge("y", lambda: 1)
    lines = render_text(regs).splitlines()
    families = []
    for line in lines:
        fam = (line.split()[3] if line.startswith("# TYPE")
               else line.split("{")[0])
        if not families or families[-1] != fam:
            families.append(fam)
    # each family appears in exactly one consecutive run
    assert len(families) == len(set(families)), families


def test_render_histogram_as_unitless_summary():
    regs = _private_regs()
    reg = regs.create(MetricRegistryInfo("p", "ratis", "engine", "h"))
    h = reg.histogram("ackBatchSize")
    for v in (1, 2, 3, 100):
        h.update(v)
    text = render_text(regs)
    assert "# TYPE ratis_engine_ackBatchSize summary" in text
    assert 'ratis_engine_ackBatchSize_count{member="p"} 4' in text
    assert 'quantile="0.99"' in text
    assert "_seconds" not in text  # dimensionless: no unit suffix


def test_scrape_during_unregister_race():
    """A scraper hitting /metrics while another thread churns registry
    create/remove must always get a 200 and a parseable body — never a
    500 or a torn read."""

    async def body():
        regs = MetricRegistries.global_registries()
        server = MetricsHttpServer()
        await server.start()
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                info = MetricRegistryInfo(f"race-{i % 7}", "ratis",
                                          "racetest", "m")
                reg = regs.create(info)
                reg.counter("numSpins").inc()
                reg.gauge("g", lambda: 1)
                regs.remove(info)
                i += 1

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            from ratis_tpu.metrics.aggregate import fetch_text
            for _ in range(30):
                text = await fetch_text(server.address, "/metrics")
                for line in text.splitlines():
                    # every non-empty line is a TYPE comment or a sample;
                    # a 500 would have raised in fetch_text
                    assert not line or line.startswith("#") or " " in line
        finally:
            stop.set()
            t.join(5.0)
            await server.close()

    asyncio.run(body())


# ------------------------------------------- engine metrics promotion

def test_engine_metrics_dict_view_and_registry():
    """engine.metrics keeps the historical dict surface while the same
    counters live in a real 'engine' registry with the new signals."""
    from ratis_tpu.engine.engine import QuorumEngine

    async def body():
        eng = QuorumEngine(max_groups=64, scalar_fallback_threshold=0,
                           name="view-test")
        try:
            m = eng.metrics
            assert m["ticks"] == 0 and m.get("acks") == 0
            assert m.get("nope") is None and "nope" not in m
            assert "ticks" in m and dict(m.items())["ticks"] == 0
            names = eng._m.registry.metric_names()
            for expected in ("ticks", "dispatchLatency", "ackBatchSize",
                             "laneOccupancyGroups", "laneGroupsLive",
                             'dispatches{reason="sweep"}'):
                assert expected in names, (expected, names)
            # the registry is discoverable as an "engine" component
            infos = [i for i in MetricRegistries.global_registries()
                     .get_registry_infos()
                     if i.component == "engine" and i.prefix == "view-test"]
            assert infos
        finally:
            eng._m.unregister()

    asyncio.run(body())


# ------------------------------------------- live-cluster endpoints

def test_endpoints_on_live_cluster_and_unset_means_no_listener():
    """Acceptance: with raft.tpu.metrics.http-port set, /metrics /health
    /divisions /events all respond on a live 3-peer cluster and the
    engine lane-occupancy gauges reflect the live group count; with the
    key unset no listener is created."""

    async def body():
        from ratis_tpu.metrics.aggregate import (fetch_json, fetch_text,
                                                 parse_prometheus_text,
                                                 scrape_cluster)
        cluster = MiniCluster(3, properties=_obs_properties(batched=True))
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            for _ in range(3):
                assert (await cluster.send_write()).success
            srv = cluster.servers[leader.member_id.peer_id]
            assert srv.metrics_http is not None
            addr = srv.metrics_http.address

            health = await fetch_json(addr, "/health")
            assert health["status"] == "ok"
            assert health["peer"] == str(leader.member_id.peer_id)
            assert health["engine"]["ticks"] > 0
            assert health["engine"]["lastTickAgeS"] is not None

            divisions = await fetch_json(addr, "/divisions")
            assert len(divisions) == 1
            d = divisions[0]
            assert d["role"] == "LEADER" and d["term"] >= 1
            assert d["commitIndex"] >= 3 and d["lastApplied"] >= 3
            assert d["retryCacheSize"] >= 1
            assert set(d["followers"]) == {"s%d" % i for i in range(3)} \
                - {str(leader.member_id.peer_id)}
            for f in d["followers"].values():
                assert f["lag"] == 0 and f["matchIndex"] >= 3

            events = await fetch_json(addr, "/events")
            assert events["enabled"] and events["events"] == []

            samples = parse_prometheus_text(await fetch_text(
                addr, "/metrics"))
            member = str(leader.member_id.peer_id)
            # lane occupancy present and reflecting the live group count
            assert samples[
                f'ratis_engine_laneGroupsLive{{member="{member}"}}'] == 1.0
            cap = samples[
                f'ratis_engine_laneGroupsCapacity{{member="{member}"}}']
            assert samples[
                f'ratis_engine_laneOccupancyGroups{{member="{member}"}}'] \
                == pytest.approx(1.0 / cap)
            # the batched engine dispatched, and the division catalog is
            # scraped alongside
            assert samples[
                f'ratis_engine_batched_dispatches_total{{member="{member}"}}'
            ] > 0
            assert any(k.startswith("ratis_server_numRaftClientRequests")
                       for k in samples)

            # cross-server aggregation over the in-process trio
            merged = await scrape_cluster(
                [s.metrics_http.address
                 for s in cluster.servers.values()])
            assert merged["servers"] == 3 and merged["healthy"] == 3
            roles = {}
            for proc in merged["procs"].values():
                for role, n in proc["roles"].items():
                    roles[role] = roles.get(role, 0) + n
            assert roles.get("LEADER") == 1 and roles.get("FOLLOWER") == 2
        finally:
            await cluster.close()

        # unset key -> no listener object at all
        cluster2 = MiniCluster(3)
        await cluster2.start()
        try:
            assert all(s.metrics_http is None
                       for s in cluster2.servers.values())
        finally:
            await cluster2.close()

    asyncio.run(body())


# ----------------------------------------------------- stall watchdog

def test_watchdog_detects_commit_stall_and_shell_health(capsys):
    """Acceptance: an induced commit stall (leader isolated via the
    existing injection hooks) is detected by the watchdog, visible in
    /events, and surfaced by the shell ``health`` subcommand."""
    from ratis_tpu.util import injection

    async def body():
        from ratis_tpu.metrics.aggregate import fetch_json
        cluster = MiniCluster(3, properties=_obs_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            assert (await cluster.send_write()).success
            srv = cluster.servers[leader.member_id.peer_id]
            lid = leader.member_id.peer_id
            # isolate the leader without letting anyone take over: no
            # staleness abdication, appends and votes both gated
            for s in cluster.servers.values():
                s.engine.leadership_timeout_ms = 600_000
            gate = asyncio.Event()

            async def block(local_id, remote_id, *args):
                await gate.wait()

            injection.put(injection.APPEND_ENTRIES, block)
            injection.put(injection.REQUEST_VOTE, block)
            wtask = asyncio.create_task(
                cluster.send(b"INCREMENT", server_id=lid, timeout=60.0))
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                if srv.watchdog.event_count():
                    break
                await asyncio.sleep(0.1)
            events = srv.watchdog.events()
            assert any(e["kind"] == "commit-stall" for e in events), events
            # the same journal over the wire
            payload = await fetch_json(srv.metrics_http.address, "/events")
            assert payload["count"] >= 1
            assert any(e["kind"] == "commit-stall"
                       for e in payload["events"])
            # the labeled detection counter scraped too
            from ratis_tpu.metrics.aggregate import (fetch_text,
                                                     parse_prometheus_text)
            samples = parse_prometheus_text(
                await fetch_text(srv.metrics_http.address, "/metrics"))
            assert samples[
                f'ratis_server_events_total{{member="{lid}",'
                f'kind="commit-stall"}}'] >= 1

            # shell health scrapes every endpoint and prints the event
            import argparse
            from ratis_tpu.shell.cli import cmd_health
            args = argparse.Namespace(
                endpoints=",".join(s.metrics_http.address
                                   for s in cluster.servers.values()),
                timeout=10.0, verbose=True)
            rc = await cmd_health(args)
            out = capsys.readouterr().out
            assert "commit-stall" in out
            assert "3/3 server(s) healthy" in out
            assert rc == 1  # journaled events -> nonzero exit

            # release: the cluster must recover and commit the write
            gate.set()
            injection.clear()
            reply = await asyncio.wait_for(wtask, 60.0)
            assert reply.success
        finally:
            injection.clear()
            await cluster.close()

    asyncio.run(body())


def test_watchdog_follower_lag_and_churn_units():
    """Follower-lag: a follower whose appends are dropped falls behind
    the advancing commit and is journaled once per episode.  Churn: the
    election-activity rate detector fires from the counters alone."""
    from ratis_tpu.util import injection

    async def body():
        cluster = MiniCluster(3, properties=_obs_properties())
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            srv = cluster.servers[leader.member_id.peer_id]
            srv.watchdog.lag_threshold = 1
            followers = [d for d in cluster.divisions()
                         if d.is_follower()]
            victim = followers[0].member_id.peer_id

            async def drop(local_id, remote_id, *args):
                if str(local_id).startswith(str(victim)):
                    raise RuntimeError("injected: follower blackholed")

            injection.put(injection.APPEND_ENTRIES, drop)
            for _ in range(4):
                assert (await cluster.send_write()).success
            deadline = asyncio.get_event_loop().time() + 15.0
            while asyncio.get_event_loop().time() < deadline:
                if any(e["kind"] == "follower-lag"
                       for e in srv.watchdog.events()):
                    break
                await asyncio.sleep(0.1)
            lag_events = [e for e in srv.watchdog.events()
                          if e["kind"] == "follower-lag"]
            assert lag_events, srv.watchdog.events()
            assert str(victim) in lag_events[0]["detail"]

            # churn detector: synthetic election activity over threshold
            srv.watchdog.churn_threshold = 3
            srv.watchdog.sample()
            leader.election_metrics.timeout_count.inc(5)
            srv.watchdog.sample()
            assert any(e["kind"] == "election-churn"
                       for e in srv.watchdog.events())
        finally:
            injection.clear()
            await cluster.close()

    asyncio.run(body())


# ----------------------------------------- pause monitor registry link

def test_pause_monitor_metrics_in_scrape():
    async def body():
        from ratis_tpu.metrics.aggregate import (fetch_text,
                                                 parse_prometheus_text)
        cluster = MiniCluster(3, properties=_obs_properties())
        await cluster.start()
        try:
            await cluster.wait_for_leader()
            srv = next(iter(cluster.servers.values()))
            srv.pause_monitor.num_pauses.inc()  # simulate one detection
            srv.pause_monitor.max_pause_s = 0.75
            samples = parse_prometheus_text(
                await fetch_text(srv.metrics_http.address, "/metrics"))
            member = str(srv.peer_id)
            assert samples[
                f'ratis_server_numPauses_total{{member="{member}"}}'] == 1.0
            assert samples[
                f'ratis_server_longestPauseMs{{member="{member}"}}'] == 750.0
        finally:
            await cluster.close()

    asyncio.run(body())


# --------------------------------------- multi-process aggregation

@pytest.mark.mp
def test_multiproc_merged_snapshot_and_trace(tmp_path):
    """Acceptance: a multi-process rung produces ONE merged cluster
    snapshot containing every child pid and ONE merged Perfetto trace
    spanning >= 2 child pids."""
    from ratis_tpu.tools.bench_cluster import run_multiproc_bench

    trace_out = str(tmp_path / "merged_trace.json")

    async def body():
        return await run_multiproc_bench(
            4, 2, num_servers=3, transport="tcp", client_procs=2,
            concurrency=8, trace=True, trace_sample=1,
            trace_out=trace_out, bringup_timeout_s=420.0,
            load_timeout_s=300.0)

    out = asyncio.run(body())
    assert out["commits"] == 8 and out["write_failures"] == 0

    merged = out["cluster_metrics"]
    procs = merged["procs"]
    # every child server process present, each under its own pid
    assert len(procs) == 3
    assert all(pid.isdigit() for pid in procs), procs
    assert len({procs[p]["peer"] for p in procs}) == 3
    assert merged["healthy"] == 3
    # counter totals merged across processes: the cluster served commits
    commits = merged["counter_totals"].get(
        "ratis_engine_commit_advances_total", 0)
    assert commits > 0

    # merged chrome trace: valid JSON, spans from >= 2 distinct pids
    with open(trace_out) as f:
        trace = json.load(f)
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert len(pids) >= 2, f"merged trace covers pids {pids}"
    assert out["trace_pids"] == len(pids)
