"""The round-6 wire hot path: write coalescing + encode-once codec.

Covers the ISSUE 2 satellite test checklist:

- frame-coalescing unit tests (byte/latency threshold boundaries,
  flush-on-close, partial-batch failure poisons the connection not the
  loop, and the thresholds-at-0 path is bit-identical to per-frame);
- encode-once fan-out bit-identity vs the slow (generic msgpack) path;
- trace attribution across coalesced frames (per-stage spans survive);
- keyed-FIFO gRPC stream dispatch (same-group chunks keep arrival order);
- the bench's one-line JSON stays inside the driver's 2000-char window.
"""

import asyncio
import json

import msgpack
import pytest

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.conf.keys import WireConfigKeys
from ratis_tpu.transport.coalesce import WriteCoalescer

RPC = "NETTY"


# ------------------------------------------------------------- coalescer

class _FakeWriter:
    """StreamWriter stand-in recording write()/drain() activity."""

    def __init__(self, fail_after_drains: int = -1):
        self.chunks: list[bytes] = []
        self.drains = 0
        self.fail_after_drains = fail_after_drains

    def write(self, b: bytes) -> None:
        self.chunks.append(bytes(b))

    async def drain(self) -> None:
        if self.fail_after_drains >= 0 \
                and self.drains >= self.fail_after_drains:
            raise ConnectionResetError("peer went away mid-batch")
        self.drains += 1


def _tcp_coalescer(writer, **kw):
    from ratis_tpu.transport.tcp import _StreamFrameCoalescer
    return _StreamFrameCoalescer(writer, **kw)


def test_thresholds_zero_is_per_frame_bit_identical():
    """The off-by-default-safe contract: flush thresholds at 0 produce one
    write + one drain per frame, and the byte stream equals the frame
    concatenation — exactly the pre-coalescing path."""

    async def main():
        w = _FakeWriter()
        c = _tcp_coalescer(w, flush_bytes=0, flush_micros=0)
        frames = [b"frame-%d" % i for i in range(5)]
        for f in frames:
            await c.send(f, len(f))
        assert not c.coalescing
        assert w.chunks == frames          # one write per frame, in order
        assert w.drains == len(frames)     # one drain per frame
        assert b"".join(w.chunks) == b"".join(frames)
        assert c.metrics["flushes"] == 5
        assert c.metrics["coalesced_frames"] == 0

    asyncio.run(main())


def test_coalescing_batches_but_stream_is_identical():
    """Concurrent sends under coalescing fold into fewer flushes; the byte
    STREAM stays identical to the per-frame path."""

    async def main():
        w = _FakeWriter()
        c = _tcp_coalescer(w, flush_bytes=1 << 20, flush_micros=0)
        frames = [b"frame-%d" % i for i in range(8)]
        await asyncio.gather(*(c.send(f, len(f)) for f in frames))
        await c.aclose()
        assert b"".join(w.chunks) == b"".join(frames)  # bit-identical
        assert w.drains < len(frames)                  # actually coalesced
        assert c.metrics["coalesced_frames"] > 0

    asyncio.run(main())


def test_byte_threshold_boundary_flushes_immediately():
    """Reaching flush_bytes flushes inline (no latency wait): queue two
    frames whose sum crosses the threshold with a huge flush_micros — the
    flush must not wait for the timer."""

    async def main():
        w = _FakeWriter()
        c = _tcp_coalescer(w, flush_bytes=10, flush_micros=10_000_000)
        t0 = asyncio.get_running_loop().time()
        await asyncio.gather(c.send(b"12345", 5), c.send(b"67890", 5))
        took = asyncio.get_running_loop().time() - t0
        assert took < 1.0, "byte-threshold flush waited on the timer"
        assert b"".join(w.chunks) == b"1234567890"
        await c.aclose()

    asyncio.run(main())


def test_latency_threshold_flushes_single_frame():
    """A lone sub-threshold frame flushes after flush_micros, not never."""

    async def main():
        w = _FakeWriter()
        c = _tcp_coalescer(w, flush_bytes=1 << 20, flush_micros=5_000)
        await asyncio.wait_for(c.send(b"lonely", 6), 2.0)
        assert w.chunks == [b"lonely"]
        await c.aclose()

    asyncio.run(main())


def test_flush_on_close():
    """aclose() drains queued frames before the connection goes away."""

    async def main():
        w = _FakeWriter()
        c = _tcp_coalescer(w, flush_bytes=1 << 20, flush_micros=5_000_000)
        t = asyncio.create_task(c.send(b"queued", 6))
        await asyncio.sleep(0)  # frame is pending, timer far away
        assert w.chunks == []
        await c.aclose()
        await t
        assert w.chunks == [b"queued"]

    asyncio.run(main())


def test_partial_batch_failure_poisons_connection_not_loop():
    """A drain failure mid-batch fails every send awaiting that batch and
    poisons the coalescer; later sends fail fast; nothing leaks into the
    event loop (the flusher task ends cleanly)."""

    async def main():
        w = _FakeWriter(fail_after_drains=0)
        c = _tcp_coalescer(w, flush_bytes=1 << 20, flush_micros=0)
        results = await asyncio.gather(
            c.send(b"a", 1), c.send(b"b", 1), return_exceptions=True)
        assert all(isinstance(r, ConnectionResetError) for r in results)
        assert c.poisoned
        with pytest.raises(ConnectionResetError):
            await c.send(b"c", 1)
        # the flusher died CLEANLY (no exception escaped to the loop)
        await asyncio.sleep(0.01)
        assert c._flusher is None

    asyncio.run(main())


# -------------------------------------------------- encode-once fast path

def _fanout_case():
    from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
    from ratis_tpu.protocol.logentry import (make_config_entry,
                                             make_metadata_entry,
                                             make_transaction_entry)
    from ratis_tpu.protocol.peer import RaftPeer
    from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest,
                                            RaftRpcHeader)
    from ratis_tpu.protocol.termindex import TermIndex
    gid = RaftGroupId.random_id()
    entries = (
        make_transaction_entry(3, 10, b"c" * 16, 42, b"x" * 300),
        make_transaction_entry(3, 11, b"c" * 16, 43, b"y" * 70_000,
                               sm_data=b"z" * 10),
        make_transaction_entry(3, 12, b"c" * 16, 44, b"",
                               is_datastream=True),
        make_config_entry(3, 13, [RaftPeer(RaftPeerId.value_of("s1"),
                                           address="10.0.0.1:5")]),
        make_metadata_entry(2 ** 40, 14, 9),
    )
    reqs = tuple(
        AppendEntriesRequest(
            RaftRpcHeader(RaftPeerId.value_of("s0"),
                          RaftPeerId.value_of(rp), gid, 7),
            3, TermIndex(2, 9), entries, 8, False,
            (("s1", 5), ("s2", -1)))
        for rp in ("s1", "s2", "s3", "s4"))
    return entries, reqs


def _slow_encode(msg):
    from ratis_tpu.protocol.raftrpc import _TYPE_TAGS
    return msgpack.packb({"_": _TYPE_TAGS[type(msg)], "b": msg.to_dict()},
                         use_bin_type=True)


def test_encode_once_fanout_bit_identity():
    """The spliced fast path is byte-identical to the generic packer for
    the whole per-follower fan-out, envelopes included, and round-trips
    through decode_rpc."""
    from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest,
                                            AppendEnvelope, FANOUT_STATS,
                                            _encode, decode_rpc)
    from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
    from ratis_tpu.protocol.raftrpc import RaftRpcHeader
    _entries, reqs = _fanout_case()
    fallback0 = FANOUT_STATS["fallback"]
    for msg in (*reqs, AppendEnvelope(reqs),
                # sequenced lane frame (round-9 append windows)
                AppendEnvelope(reqs, lane=(123 << 32) | 45, seq=6),
                # heartbeat: no entries, no previous
                AppendEntriesRequest(
                    RaftRpcHeader(RaftPeerId.value_of("s0"),
                                  RaftPeerId.value_of("s1"),
                                  RaftGroupId.random_id(), 0),
                    2 ** 35, None, (), -1, True, ())):
        fast = _encode(msg)
        assert fast == _slow_encode(msg)
        assert decode_rpc(fast).to_dict() == msg.to_dict()
    assert FANOUT_STATS["fallback"] == fallback0, \
        "fast path silently fell back"


def test_encode_once_reuses_suffix_across_followers():
    """Fanning one batch to N followers packs the suffix once: followers
    2..N hit the suffix cache (the encode-once contract, observable)."""
    from ratis_tpu.protocol.raftrpc import FANOUT_STATS, _encode
    _entries, reqs = _fanout_case()
    hits0 = FANOUT_STATS["suffix_hits"]
    for r in reqs:
        _encode(r)
    assert FANOUT_STATS["suffix_hits"] - hits0 >= len(reqs) - 1


def test_entry_wire_bytes_memoized_on_entry():
    from ratis_tpu.protocol.logentry import make_transaction_entry
    from ratis_tpu.protocol.raftrpc import entry_wire_bytes
    e = make_transaction_entry(1, 2, b"c" * 16, 3, b"payload")
    w1 = entry_wire_bytes(e)
    assert entry_wire_bytes(e) is w1  # second call returns the memo
    assert w1 == msgpack.packb(e.to_dict(), use_bin_type=True)


# ---------------------------------------------- keyed gRPC stream dispatch

def test_grpc_stream_keyed_fifo_dispatch():
    """Same-key chunks dispatch in strict arrival order even when the
    first suspends longer (ADVICE r5: differing await points reordered
    same-group appends); distinct keys stay concurrent."""
    from ratis_tpu.protocol.ids import RaftPeerId
    from ratis_tpu.transport.grpc import GrpcServerTransport

    async def main():
        t = GrpcServerTransport(RaftPeerId.value_of("s0"), "127.0.0.1:0",
                                None, None, flush_micros=0)
        order: list[str] = []

        def classify(payload: bytes):
            name = payload.decode()
            return name, ("k", name[0])  # key by first letter

        async def dispatch(name: str) -> bytes:
            # the FIRST chunk of each key suspends longest: unordered
            # dispatch would finish a1/b1 AFTER a2/b2
            await asyncio.sleep(0.05 if name.endswith("1") else 0.0)
            order.append(name)
            return name.encode()

        async def chunks():
            for i, name in enumerate(("a1", "a2", "b1", "b2")):
                yield msgpack.packb([i, name.encode()])

        replies = []
        async for item in t._serve_stream(chunks(), dispatch,
                                          classify=classify):
            replies.append(msgpack.unpackb(item))
        assert order.index("a1") < order.index("a2")
        assert order.index("b1") < order.index("b2")
        assert {r[0] for r in replies} == {0, 1, 2, 3}
        assert t.dispatch_metrics["keyed_chunks"] == 4
        assert t.dispatch_metrics["ordered_waits"] >= 2

    asyncio.run(main())


def test_grpc_stream_accepts_coalesced_chunk_batches():
    """One inbound stream message carrying a BATCH of chunks dispatches
    each chunk and answers every call id (the raft.tpu.grpc framing)."""
    from ratis_tpu.protocol.ids import RaftPeerId
    from ratis_tpu.transport.grpc import GrpcServerTransport

    async def main():
        t = GrpcServerTransport(RaftPeerId.value_of("s0"), "127.0.0.1:0",
                                None, None, flush_micros=100)

        async def dispatch(payload: bytes) -> bytes:
            return b"ok-" + payload

        async def chunks():
            yield msgpack.packb([[0, b"a"], [1, b"b"], [2, b"c"]])

        got = {}
        async for item in t._serve_stream(chunks(), dispatch):
            decoded = msgpack.unpackb(item)
            triples = (decoded if decoded
                       and isinstance(decoded[0], (list, tuple))
                       else [decoded])
            for call_id, status, payload in triples:
                got[call_id] = (status, payload)
        assert got == {0: (0, b"ok-a"), 1: (0, b"ok-b"), 2: (0, b"ok-c")}
        assert t.dispatch_metrics["batched_messages"] == 1

    asyncio.run(main())


# ------------------------------------------- end-to-end over real sockets

def _coalescing_properties():
    p = fast_properties()
    p.set(WireConfigKeys.Tcp.FLUSH_BYTES_KEY, "64KB")
    p.set(WireConfigKeys.Tcp.FLUSH_MICROS_KEY, "100")
    p.set(WireConfigKeys.Grpc.FLUSH_MICROS_KEY, "100")
    return p


def test_tcp_cluster_with_coalescing_on():
    """Full consensus over real TCP sockets with write coalescing enabled:
    writes commit, reads see them — the coalesced frames carry the same
    protocol."""

    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(8):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"8"

    run_with_new_cluster(3, t, rpc_type=RPC,
                         properties=_coalescing_properties())


def test_grpc_cluster_with_coalescing_on():
    """Same over the gRPC transport: batched stream framing end to end."""

    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(8):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"8"

    run_with_new_cluster(3, t, rpc_type="GRPC",
                         properties=_coalescing_properties())


def test_trace_attribution_survives_coalescing():
    """Coalesced frames still produce per-stage spans: with tracing on and
    TCP write coalescing enabled, a traced request records decode, the
    full server tiling, and the respond span (which now covers the
    coalesced flush)."""
    from ratis_tpu.trace import get_tracer
    from ratis_tpu.trace.tracer import (STAGE_APPEND, STAGE_APPLY,
                                        STAGE_CLIENT, STAGE_DECODE,
                                        STAGE_REPLICATE, STAGE_RESPOND,
                                        STAGE_ROUTE)
    tracer = get_tracer()
    tracer.configure(enabled=True, sample_every=1, ring_size=1024)
    try:
        async def t(cluster: MiniCluster):
            async with cluster.new_client() as client:
                for _ in range(4):
                    assert (await client.io().send(b"INCREMENT")).success

        run_with_new_cluster(3, t, rpc_type=RPC,
                             properties=_coalescing_properties())
        by_stage: dict[int, set[int]] = {}
        for tid, stage, _t0, _dur, _tag, _origin in tracer.snapshot():
            if tid:
                by_stage.setdefault(stage, set()).add(tid)
        full = (by_stage.get(STAGE_CLIENT, set())
                & by_stage.get(STAGE_DECODE, set())
                & by_stage.get(STAGE_ROUTE, set())
                & by_stage.get(STAGE_APPEND, set())
                & by_stage.get(STAGE_REPLICATE, set())
                & by_stage.get(STAGE_APPLY, set())
                & by_stage.get(STAGE_RESPOND, set()))
        assert full, ("coalescing lost span attribution: "
                      f"{ {k: len(v) for k, v in by_stage.items()} }")
    finally:
        tracer.configure(enabled=False)


# ------------------------------------------------- bench line stays small

def test_bench_summary_line_fits_driver_window():
    """The one-line bench JSON must parse from the driver's 2000-char tail
    capture (BENCH_r05.json overflowed it: parsed null).  Fill every rung
    with worst-case-width synthetic numbers and assert the line fits."""
    import bench

    def rung(**extra):
        out = {"commits_per_sec": 123456.8, "p50_ms": 99999.99,
               "p99_ms": 99999.99, "election_convergence_s": 9999.99,
               "write_failures": 0, "engine_occupancy": 0.9999,
               "watchdog_events": 99999, "reply_hops_per_commit": 99.999,
               "window_occupancy": 0.9999}
        out.update(extra)
        return out

    decomp = {"coverage": 0.975, "stages": {
        name: {"p50_us": 123456.7}
        for name in ("server.route", "server.txn_start", "server.append",
                     "server.replicate", "server.apply", "server.reply",
                     "server.respond")}}
    trials = [rung() for _ in range(5)]
    summary = bench._summarize(
        headline=trials, scalar=trials,
        ladder={1: trials[:2], 64: trials[:2], 1024: trials[:3],
                10_240: trials[:2]},
        mesh_trials=trials[:2],
        peer5=rung(host_path_decomposition=decomp,
                   mp={"server_procs": 5, "client_procs": 4,
                       "loop_shards": 3}),
        peer5_sp=rung(), peer5_mp=rung(),
        peer5_scalar=rung(),
        peer5_grpc=rung(), peer5_grpc_scalar=rung(),
        peer7=rung(host_path_decomposition=decomp),
        sparse_hib=rung(hibernated_groups=10240), sparse_plain=rung(),
        churn=rung(transfers_ok=64, transfers_failed=64),
        mixed=rung(streams_ok=32, stream_mb_per_s=99999.99),
        mixed_fs={"pergroup": rung(stream_mb_per_s=99999.99,
                                   fsyncs_per_commit=99.9999),
                  "shared": rung(stream_mb_per_s=99999.99,
                                 fsyncs_per_commit=99.9999),
                  "pergroup_5ms": rung(stream_mb_per_s=99999.99,
                                       fsyncs_per_commit=99.9999),
                  "shared_5ms": rung(stream_mb_per_s=99999.99,
                                     fsyncs_per_commit=99.9999)},
        stream=rung(stream_mb_per_s=99999.99),
        grpc_b=trials[:3], grpc_s_1024=rung(), grpc_s_256=rung(),
        kernel={"group_updates_per_sec": 1330708656.5,
                "vs_scalar_loop": 99126.85, "platform": "TPU v5 lite0"},
        kernel_100k={"group_updates_per_sec_100k": 1333027867.0},
        mesh100k={"groups": 102400, "devices": 8,
                  "updates_per_s": 1333027867.9, "tick_ms": 99999.99,
                  "efficiency_frac": 0.999},
        tpu_e2e={"dnf": True, "reason": "x" * 500},
        traced=rung(host_path_decomposition=decomp),
        filestore5=rung(streams_ok=32, stream_mb_per_s=99999.99),
        readmix=rung(reads_per_sec=123456.8, read_p99_ms=99999.99,
                     reads_lease_leader=99999,
                     reads_follower_linearizable=99999,
                     reads_stale=99999),
        snapcatch=rung(catchup_s=9999.99, installs=10240,
                       cps_before=123456.8),
        zipf=rung(writes_per_sec=123456.8, reads_per_sec=123456.8,
                  shed_frac=0.9999),
        placement={"hotspot_p99_before_ms": 99999.99,
                   "hotspot_p99_after_ms": 99999.99,
                   "transfers": 99999, "grey_steer_frac": 0.9999},
        win_sweep={str(d): [123456.8, 99999.99, 0.9999]
                   for d in (1, 4, 16)},
        chaos={"passed": 9, "total": 9, "worst_reelect_s": 9999.999,
               "recovery_frac": 99.999, "fault_events": 99999},
        tel_on=rung(telemetry={"samples": 99999,
                               "sample_cost_p99_ms": 9999.999,
                               "hot_share": 0.9999,
                               "hot_group": "group-aabbccdd",
                               "sampler_pass_ms": 9999.999,
                               "ledger_fetch_ms": 9999.999,
                               "walk_pass_ms": 9999.999}),
        tel_off=rung(),
        # realistic-worst width: the idle scan measures in MICROseconds
        # (tests/test_upkeep.py); 9.999ms is already a 1000x degradation
        upkeep=[9.999, 9.999, 0.99])
    line = json.dumps(summary, separators=(",", ":"))
    assert len(line) < 2000, f"bench line would overflow: {len(line)} chars"
    parsed = json.loads(line)
    assert parsed["value"] == 123456.8
    assert parsed["vs_baseline"] == 1.0
    assert parsed["secondary"]["p5_10240"]["vs_scalar"] == 1.0
    assert parsed["secondary"]["p5_10240"]["mp"] == [5, 3, 4]
    assert parsed["secondary"]["p5_fs"][2] == 32
    # durable mixed rung: [pg c/s, pg f/c, shared c/s, shared MB/s,
    # shared f/c, speedup] + the modeled-disk pair [pg, shared, speedup]
    assert parsed["secondary"]["mix_fs"][5] == 1.0
    assert parsed["secondary"]["mix_5ms"][2] == 1.0
    assert parsed["secondary"]["readmix"][1] == 123456.8
    assert parsed["secondary"]["snap_1024"][1] == 10240
    # round-12 zipf fleet rung: [writes/s, reads/s, shed frac, p99 ms]
    assert parsed["secondary"]["zipf"] == [
        123456.8, 123456.8, 0.9999, 99999.99]
    # round-16 placement closed loop: [hot p99 OFF, ON, transfers,
    # grey steer fraction]
    assert parsed["secondary"]["placement"] == [
        99999.99, 99999.99, 99999, 0.9999]
    # observability keys: [engine occupancy, watchdog event count,
    # reply-plane scheduling hops per commit (round-8 fan-out collapse),
    # append-window occupancy (round-9 pipelined windows), the round-11
    # telemetry-on/off overhead pair, the headline hot-group skew, and
    # the round-14 lag-ledger cost pair [sampler pass p50 ms, device
    # ledger fetch p50 ms]]
    assert parsed["secondary"]["obs"] == [
        0.9999, 99999 * 6, 99.999, 0.9999,
        [123457, 123457, 0.0], 0.9999, [9999.999, 9999.999]]
    assert parsed["secondary"]["win_sweep"]["16"] == [123456.8, 99999.99,
                                                      0.9999]
    # chaos campaign rung: [passed, total, worst reelect s,
    # recovery-throughput fraction, injected-fault event records]
    assert parsed["secondary"]["chaos"] == [9, 9, 9999.999, 99.999,
                                                 99999]
    # round-15 upkeep plane: [sweep ms @64 slots, @1024, sim dip frac]
    assert parsed["secondary"]["upkeep"] == [9.999, 9.999, 0.99]
    # kernel throughputs are COUNTS: emitted rounded to the integer
    assert parsed["secondary"]["kernel"][0] == 1330708656
    assert parsed["secondary"]["kernel_100k"] == 1333027867
    # PR-18 flagship mesh rung: [groups, devices, updates/s, tick ms,
    # efficiency vs the mesh-devices=0 control]
    assert parsed["secondary"]["mesh100k"] == [
        102400, 8, 1333027868, 99999.99, 0.999]
    # compact list forms: grpc_1024 = [cps, p99, scalar cps, s256 cps],
    # mesh_10240 = [cps, spread, sim cps, sim spread]
    assert parsed["secondary"]["grpc_1024"][0] == 123456.8
    assert len(parsed["secondary"]["mesh_10240"]) == 4
