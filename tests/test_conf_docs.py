"""Conf-key / documentation drift gate (ratis_tpu.tools.check_conf_docs):
every ``*_KEY`` in conf/keys.py must appear in docs/configurations.md and
vice versa — PRs 2-3 each grew key families the doc silently missed."""

from ratis_tpu.tools.check_conf_docs import check, code_keys, doc_keys


def test_conf_keys_and_docs_in_sync():
    problems = check()
    assert not problems, "\n".join(problems)


def test_parsers_see_real_catalogs():
    """Guard the checker itself: an empty parse would pass check()
    vacuously while asserting nothing."""
    keys = code_keys()
    assert len(keys) > 80, f"keys.py parse collapsed: {len(keys)} keys"
    assert "raft.server.rpc.timeout.min" in keys
    assert "raft.tpu.metrics.http-port" in keys
    exact, wildcards = doc_keys()
    assert len(exact) > 60, f"doc parse collapsed: {len(exact)} keys"
    # suffix alternation expands (min/.max) and multi-segment suffixes
    # replace one segment (enabled/.warn.threshold)
    assert "raft.server.rpc.timeout.max" in exact
    assert "raft.server.pause.monitor.warn.threshold" in exact
    # family wildcards from table rows count; section headings do not
    assert "raft.datastream.tls" in wildcards
    assert "raft.server" not in wildcards
