"""Pipelined appender under injected network latency.

Mirrors the reference's motivation for GrpcLogAppender's streaming pipeline
(GrpcLogAppender.java:343-381): with real per-hop latency, a stop-and-wait
appender commits at most one batch per RTT per follower, while a pipelined
window keeps the link full.  The simulated hub delivers per-link FIFO like
the TCP-based transports, so the window stays coherent.
"""

import asyncio
import time

import pytest

from ratis_tpu.conf import RaftServerConfigKeys
from tests.minicluster import MiniCluster, fast_properties


async def _drive_writes(window: int, delay_ms: float, n: int) -> float:
    """Seconds to commit n 1-entry batches through a 3-peer cluster whose
    every hop costs delay_ms, with the given per-follower pipeline window."""
    p = fast_properties()
    # Elections must tolerate 2x delay round trips comfortably.
    RaftServerConfigKeys.Rpc.set_timeout(p, "500ms", "1000ms")
    # 1-byte budget -> every AppendEntries carries exactly one entry, so the
    # appender cannot hide latency behind giant batches; the window is the
    # only lever (this isolates pipelining, like the reference's perf tests).
    p.set(RaftServerConfigKeys.Log.Appender.BUFFER_BYTE_LIMIT_KEY, "1")
    p.set(RaftServerConfigKeys.Log.Appender.PIPELINE_WINDOW_KEY, str(window))
    cluster = MiniCluster(3, properties=p)
    await cluster.start()
    try:
        await cluster.wait_for_leader()
        assert (await cluster.send_write()).success  # leader ready + warm
        cluster.network.base_delay_ms = delay_ms
        t0 = time.monotonic()
        replies = await asyncio.gather(
            *(cluster.send(b"INCREMENT", timeout=60.0) for _ in range(n)))
        elapsed = time.monotonic() - t0
        assert all(r.success for r in replies)
    finally:
        cluster.network.base_delay_ms = 0.0
        await cluster.close()
    return elapsed


def test_pipeline_beats_stop_and_wait():
    """>=4x speedup over a window of 1 at 20ms hop latency (VERDICT round-1
    acceptance: GrpcLogAppender-style pipelining must actually pay off)."""

    async def main():
        n = 24
        stop_and_wait = await _drive_writes(window=1, delay_ms=20.0, n=n)
        pipelined = await _drive_writes(window=16, delay_ms=20.0, n=n)
        # window=1 needs ~n RTTs (~1.9s at 40ms RTT); window=16 needs ~n/16,
        # plus the shared client/commit path. Demand the headline 4x.
        assert pipelined * 4 <= stop_and_wait, (
            f"pipelined={pipelined:.3f}s stop_and_wait={stop_and_wait:.3f}s")

    asyncio.run(main())


def test_pipeline_correct_under_jitter():
    """Replies complete out of order under jitter; counter must still reach
    exactly n (per-link FIFO + epoch resets keep the window coherent)."""

    async def main():
        p = fast_properties()
        RaftServerConfigKeys.Rpc.set_timeout(p, "500ms", "1000ms")
        p.set(RaftServerConfigKeys.Log.Appender.BUFFER_BYTE_LIMIT_KEY, "1")
        cluster = MiniCluster(3, properties=p)
        await cluster.start()
        try:
            leader = await cluster.wait_for_leader()
            cluster.network.base_delay_ms = 2.0
            cluster.network.jitter_ms = 8.0
            n = 30
            replies = await asyncio.gather(
                *(cluster.send(b"INCREMENT", timeout=60.0) for _ in range(n)))
            assert all(r.success for r in replies)
            cluster.network.base_delay_ms = 0.0
            cluster.network.jitter_ms = 0.0
            last = leader.state.log.get_last_committed_index()
            await cluster.wait_applied(last)
            for d in cluster.divisions():
                assert d.state_machine.counter == n
        finally:
            cluster.network.base_delay_ms = 0.0
            cluster.network.jitter_ms = 0.0
            await cluster.close()

    asyncio.run(main())
