"""Placement controller (ratis_tpu.placement): the plan engine's scoring
rules, the payload -> view builder, read steering, the non-leader
admission bypass, the /divisions rollup, the hibernated-transfer wake,
the opt-in in-server loop (zero-cost off, journaled actuations on), the
shell rebalance frontend, and the rebalance_storm chaos scenario."""

import argparse
import asyncio

import pytest

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.placement.policy import (ClusterSnapshot, HotGroup,
                                        PlacementPolicy, ServerView,
                                        view_from_payloads)
from ratis_tpu.server.read import ReadSteering


def _hot(name, share_min, led=True, shard=None, gid=None):
    return HotGroup(group=name, share=share_min + 0.05,
                    share_min=share_min, led=led, shard=shard, gid=gid)


def _view(peer, leading=0, hot=(), scores=None, grey=(), shed_rate=0.0,
          shards=()):
    return ServerView(peer=peer, leading=leading, hot_groups=tuple(hot),
                      peer_scores=dict(scores or {}),
                      grey_peers=frozenset(grey), shed_rate=shed_rate,
                      shard_counts=tuple(shards))


# ------------------------------------------------------------ plan engine

def test_hot_fair_share_transfer_multi_view():
    """A server leading more hot groups than fair share + hysteresis
    sheds its hottest excess to the least-loaded healthy peer."""
    policy = PlacementPolicy(hot_share=0.2, hysteresis=0.0,
                             max_transfers_per_round=2)
    s0 = _view("s0", leading=6,
               hot=[_hot("g1", 0.5), _hot("g2", 0.3), _hot("g3", 0.25)],
               scores={"s1": 1.0, "s2": 1.0})
    s1 = _view("s1", leading=1, scores={"s0": 1.0, "s2": 1.0})
    s2 = _view("s2", leading=2, scores={"s0": 1.0, "s1": 1.0})
    plan = policy.plan(ClusterSnapshot(views=(s0, s1, s2)))
    transfers = plan.transfers()
    # hot set = 3, fair = ceil(3/3) = 1 -> excess 2, hottest first
    assert [t.group for t in transfers] == ["g1", "g2"]
    assert all(t.category == "hot-group" for t in transfers)
    # least-loaded target ranks first
    assert transfers[0].to_peer == "s1"
    assert "fair share" in transfers[0].reason
    assert plan.imbalance > 0


def test_hysteresis_band_blocks_reverse_move():
    """After one transfer lands the recipient is inside the hysteresis
    band, so the reverse move never plans (the anti-ping-pong rule)."""
    policy = PlacementPolicy(hot_share=0.2, hysteresis=1.0)
    # two hot groups over two servers, one each: fair = 1, and even the
    # view that leads 2 is inside fair + hysteresis = 2
    s0 = _view("s0", leading=3, hot=[_hot("g1", 0.5), _hot("g2", 0.3)],
               scores={"s1": 1.0}, shed_rate=5.0)
    s1 = _view("s1", leading=2, scores={"s0": 1.0})
    plan = policy.plan(ClusterSnapshot(views=(s0, s1)))
    assert plan.transfers() == []


def test_single_view_requires_admission_pressure():
    """The in-server loop's single-view gate: hot excess without live
    shedding plans nothing (sketch shares are self-relative, so the
    recipient of a hot group would otherwise bounce it back)."""
    policy = PlacementPolicy(hot_share=0.2, hysteresis=0.0)
    hot = [_hot("g1", 0.6), _hot("g2", 0.3)]
    idle = _view("s0", leading=4, hot=hot,
                 scores={"s1": 1.0, "s2": 1.0}, shed_rate=0.0)
    plan = policy.plan(ClusterSnapshot(views=(idle,)))
    assert plan.transfers() == []
    assert any("admission pressure" in n for n in plan.notes)

    shedding = _view("s0", leading=4, hot=hot,
                     scores={"s1": 1.0, "s2": 1.0}, shed_rate=12.0)
    plan = policy.plan(ClusterSnapshot(views=(shedding,)))
    # fair = ceil(2 hot / 3 servers) = 1 -> shed the hottest
    assert [t.group for t in plan.transfers()] == ["g1"]


def test_steer_targets_grey_and_low_score():
    """Grey episodes steer first (sharper diagnosis), low health scores
    steer next, steered peers are never transfer targets."""
    policy = PlacementPolicy(hot_share=0.2, grey_score=0.5,
                             hysteresis=0.0)
    v = _view("s0", leading=4, hot=[_hot("g1", 0.5), _hot("g2", 0.4)],
              scores={"s1": 0.2, "s2": 1.0, "s3": 0.9},
              grey={"s1"}, shed_rate=3.0)
    plan = policy.plan(ClusterSnapshot(views=(v,)))
    steers = plan.steers()
    assert [s.away_from for s in steers] == ["s1"]  # deduped: grey wins
    assert "grey-follower" in steers[0].reason
    # s1 steered AND under grey-score: transfers go to s2 (score 1.0)
    assert all(t.to_peer in ("s2", "s3") for t in plan.transfers())

    low = _view("s0", scores={"s1": 0.3, "s2": 1.0})
    plan = policy.plan(ClusterSnapshot(views=(low,)))
    assert [s.away_from for s in plan.steers()] == ["s1"]
    assert "health score 0.30" in plan.steers()[0].reason


def test_cooldown_exclude_and_round_cap():
    """Excluded (cooling) groups and over-cap transfers are skipped WITH
    a note each, so a dry-run shows exactly what the loop would defer."""
    policy = PlacementPolicy(hot_share=0.1, hysteresis=0.0,
                             max_transfers_per_round=1)
    # 4 hot over 4 servers: fair = 1, excess = 3 -> g1, g2, g3 planned
    v = _view("s0", leading=6,
              hot=[_hot("g1", 0.4), _hot("g2", 0.3), _hot("g3", 0.2),
                   _hot("g4", 0.15)],
              scores={"s1": 1.0, "s2": 1.0, "s3": 1.0}, shed_rate=2.0)
    plan = policy.plan(ClusterSnapshot(views=(v,)), exclude={"g1"})
    assert [t.group for t in plan.transfers()] == ["g2"]
    assert any("g1: in cooldown" in n for n in plan.notes)
    assert any("max-transfers-per-round" in n for n in plan.notes)


def test_no_healthy_target_plans_nothing():
    policy = PlacementPolicy(hot_share=0.1, grey_score=0.5,
                             hysteresis=0.0)
    v = _view("s0", leading=3, hot=[_hot("g1", 0.6), _hot("g2", 0.3)],
              scores={"s1": 0.1, "s2": 0.2}, shed_rate=9.0)
    plan = policy.plan(ClusterSnapshot(views=(v,)))
    assert plan.transfers() == []
    assert any("no healthy transfer target" in n for n in plan.notes)


def test_leader_imbalance_fallback_multi_view_only():
    """With nothing over the hot-share floor, a raw leadership spread
    beyond hysteresis plans ONE corrective move — multi-view only (a
    single view cannot see the spread)."""
    policy = PlacementPolicy(hot_share=0.9, hysteresis=1.0)
    s0 = _view("s0", leading=9, hot=[_hot("busy", 0.1)],
               scores={"s1": 1.0})
    s1 = _view("s1", leading=1, scores={"s0": 1.0})
    plan = policy.plan(ClusterSnapshot(views=(s0, s1)))
    transfers = plan.transfers()
    assert len(transfers) == 1
    assert transfers[0].category == "leader-imbalance"
    assert transfers[0].group == "busy"
    assert transfers[0].to_peer == "s1"
    assert plan.imbalance > 0

    solo = policy.plan(ClusterSnapshot(views=(s0,)))
    assert solo.transfers() == []


def test_shard_skew_advisory_repin():
    policy = PlacementPolicy()
    v = _view("s0", hot=[_hot("g1", 0.5, shard=0)], shards=(5, 1))
    plan = policy.plan(ClusterSnapshot(views=(v,)))
    repins = plan.repins()
    assert len(repins) == 1
    assert repins[0].group == "g1" and repins[0].shard == 1
    # advisory: explain prints it, transfers/steers unaffected
    assert any("REPIN (advisory)" in line for line in plan.explain())
    assert plan.transfers() == [] and plan.steers() == []


def test_plan_explain_and_to_dict():
    policy = PlacementPolicy(hot_share=0.2, hysteresis=0.0)
    v = _view("s0", leading=3, hot=[_hot("g1", 0.5, gid=object())],
              scores={"s1": 0.2, "s2": 1.0}, shed_rate=1.0)
    plan = policy.plan(ClusterSnapshot(views=(v,)))
    lines = plan.explain()
    assert any(line.startswith("STEER reads away from s1") for line in lines)
    d = plan.to_dict()
    assert d["imbalance"] == plan.imbalance
    assert d["explain"] == lines
    # gid objects never serialize into the payload
    for a in d["actions"]:
        assert "gid" not in a and a["kind"] in ("transfer", "steer",
                                                "repin")


def test_view_from_payloads_tolerates_partial():
    """The shell builder: any payload subset (telemetry-off servers 404
    /hotgroups), peer name recovered from whichever payload has it."""
    lag = {"peer": "s0", "leading": 7,
           "peers": [{"peer": "s1", "score": 0.4},
                     {"peer": "s2", "score": 1.0}],
           "groups": [{"group": "g9", "lag": 100}]}
    rollup = {"peer": "s0", "leading": 7, "pendingTotal": 11,
              "divisions": 16, "shards": [8, 8]}
    health = {"peer": "s0", "divisions": 16,
              "serving": {"shedTotal": 42, "pendingCount": 11}}
    hotgroups = {"peer": "s0", "groups": [
        {"group": "g1", "share": 0.5, "share_min": 0.45, "led": True,
         "shard": 0}]}
    v = view_from_payloads(health=health, lag=lag, hotgroups=hotgroups,
                           rollup=rollup)
    assert v.peer == "s0" and v.leading == 7
    assert v.pending_total == 11 and v.shed_total == 42
    assert v.shard_counts == (8, 8)
    assert v.peer_scores == {"s1": 0.4, "s2": 1.0}
    assert v.hot_groups[0].group == "g1"
    assert v.laggard_groups[0]["group"] == "g9"

    sparse = view_from_payloads(lag={"peer": "s1", "leading": 2})
    assert sparse.peer == "s1" and sparse.hot_groups == ()


# ---------------------------------------------------------- read steering

def test_read_steering_episode_semantics():
    rs = ReadSteering()
    assert rs.avoided(now=0.0) == set()
    assert rs.steer("s2", 5.0, now=0.0) is True      # new episode
    assert rs.steer("s2", 5.0, now=1.0) is False     # silent renewal
    assert rs.avoided(now=2.0) == {"s2"}
    assert rs.avoided(now=7.0) == set()              # ttl expired
    assert rs.steer("s2", 5.0, now=8.0) is True      # new episode again
    rs.clear("s2")
    assert rs.avoided(now=8.5) == set()


# ----------------------------------------------- server integration layer

def _admission_properties(element_limit=0):
    p = fast_properties()
    p.set("raft.tpu.serving.admission.enabled", "true")
    p.set("raft.tpu.serving.admission.pending.element-limit",
          str(element_limit))
    return p


def test_non_leader_admission_bypass():
    """Requests for groups a server does NOT lead bypass the pending
    budget: the division's NotLeader redirect must reach the client (a
    shed here would trap clients of a just-transferred group in
    retry-after loops against the old leader)."""
    from ratis_tpu.protocol.requests import write_request_type

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        leader_srv = cluster.servers[leader.member_id.peer_id]
        follower_srv = next(s for s in cluster.servers.values()
                            if s is not leader_srv)
        req = cluster._request(leader_srv.peer_id, b"INCREMENT",
                               write_request_type())
        # element-limit 0: the leader sheds every data-plane request...
        shed, ticket = leader_srv.serving.admission.try_admit(req)
        assert shed is not None and ticket is None
        assert not shed.success
        # ...but the follower lets the same request through to its
        # division, which will answer NotLeader with the redirect hint
        req2 = cluster._request(follower_srv.peer_id, b"INCREMENT",
                                write_request_type())
        shed2, ticket2 = follower_srv.serving.admission.try_admit(req2)
        assert shed2 is None and ticket2 is None

    run_with_new_cluster(3, body, properties=_admission_properties())


def test_divisions_rollup_payload():
    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        srv = cluster.servers[leader.member_id.peer_id]
        rollup = srv.divisions_info(query={"rollup": ["1"]})
        assert rollup["peer"] == str(srv.peer_id)
        assert rollup["divisions"] == 1 and rollup["leading"] == 1
        assert sum(rollup["shards"]) == 1
        assert rollup["pendingTotal"] == 0
        assert rollup["hibernating"] == 0
        # without the flag the full per-division list is unchanged
        full = srv.divisions_info()
        assert isinstance(full, list) and len(full) == 1

    run_with_new_cluster(3, body)


def test_transfer_leadership_wakes_hibernated_group():
    """A transfer targeting a hibernated group must wake it first: a
    sleeping leader sends no heartbeats and its followers hold no armed
    election timers, so the handover would stall against them."""
    from ratis_tpu.conf.keys import RaftServerConfigKeys
    from ratis_tpu.protocol.admin import TransferLeadershipArguments
    from ratis_tpu.protocol.message import Message
    from ratis_tpu.protocol.requests import (RequestType,
                                             admin_request_type)

    p = fast_properties()
    p.set(RaftServerConfigKeys.Hibernate.ENABLED_KEY, "true")
    p.set(RaftServerConfigKeys.Hibernate.AFTER_SWEEPS_KEY, "2")

    async def body(cluster: MiniCluster):
        assert (await cluster.send_write()).success
        deadline = asyncio.get_event_loop().time() + 20.0
        leader = None
        while asyncio.get_event_loop().time() < deadline:
            leader = next((d for d in cluster.divisions()
                           if d.hibernating), None)
            if leader is not None:
                break
            await asyncio.sleep(0.05)
        assert leader is not None, "group never hibernated"
        target = next(d for d in cluster.divisions()
                      if d is not leader).member_id.peer_id
        args = TransferLeadershipArguments(str(target), 5000.0)
        reply = await cluster.send(
            args.to_payload(),
            admin_request_type(RequestType.TRANSFER_LEADERSHIP),
            server_id=leader.member_id.peer_id, timeout=20.0)
        assert reply.success, reply.exception
        assert not leader.hibernating
        new_leader = await cluster.wait_for_leader()
        assert new_leader.member_id.peer_id == target

    run_with_new_cluster(3, body, properties=p)


def test_controller_off_by_default_on_when_enabled():
    """Unset key -> no controller object, no /placement route, empty
    steering (zero-cost).  Enabled -> the loop runs, a forced round
    journals paired rebalance events for its steering actuation, and
    GET /placement serves the explained plan."""

    async def off_body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        for s in cluster.servers.values():
            assert s.placement is None
            assert s.read_steering.avoided() == set()

    run_with_new_cluster(3, off_body)

    p = fast_properties()
    p.set("raft.tpu.placement.enabled", "true")
    p.set("raft.tpu.placement.interval", "60s")  # rounds forced by hand

    async def on_body(cluster: MiniCluster):
        from ratis_tpu.server.watchdog import (KIND_REBALANCE,
                                               KIND_REBALANCE_DONE)
        leader = await cluster.wait_for_leader()
        srv = cluster.servers[leader.member_id.peer_id]
        ctrl = srv.placement
        assert ctrl is not None
        await ctrl.round()
        assert ctrl.rounds == 1 and ctrl.last_plan is not None
        info = ctrl.placement_info()
        assert info["enabled"] and info["rounds"] == 1
        assert info["lastPlan"]["explain"] == ctrl.last_plan.explain()

        # inject a grey episode; the next round must steer away from it
        grey = next(name for name in
                    (str(peer.id) for peer in cluster.group.peers)
                    if name != str(srv.peer_id))
        srv.watchdog._grey.add(grey)
        await ctrl.round()
        assert grey in srv.read_steering.avoided()
        events = srv.watchdog.events()
        opened = [e for e in events if e["kind"] == KIND_REBALANCE]
        closed = [e for e in events if e["kind"] == KIND_REBALANCE_DONE]
        assert opened and {e["fault"] for e in opened} \
            == {e["fault"] for e in closed}
        # renewal inside the active ttl journals nothing new
        await ctrl.round()
        assert len([e for e in srv.watchdog.events()
                    if e["kind"] == KIND_REBALANCE]) == len(opened)

    run_with_new_cluster(3, on_body, properties=p)


# ------------------------------------------------------- shell rebalance

def test_shell_rebalance_dry_run(monkeypatch, capsys):
    """The scraped frontend: canned endpoint payloads -> the same policy
    -> printed plan with reasons; exit 2 = work exists, 0 = balanced."""
    from ratis_tpu.metrics import aggregate
    from ratis_tpu.shell.cli import cmd_rebalance

    def payloads(peer, leading, hot=(), scores=()):
        return {
            "/lag": {"peer": peer, "leading": leading,
                     "peers": [{"peer": n, "score": s} for n, s in scores],
                     "groups": []},
            "/divisions?rollup=1": {"peer": peer, "leading": leading,
                                    "pendingTotal": 0, "divisions": 8,
                                    "shards": [8]},
            "/health": {"peer": peer, "divisions": 8, "serving": {}},
            "/hotgroups": {"peer": peer, "groups": list(hot)},
        }

    fleet = {
        "h0:1": payloads("s0", 6, hot=[
            {"group": "g1", "share": 0.6, "share_min": 0.5, "led": True},
            {"group": "g2", "share": 0.3, "share_min": 0.25, "led": True},
        ], scores=[("s1", 1.0)]),
        "h1:1": payloads("s1", 1, scores=[("s0", 1.0)]),
    }

    async def fake_fetch(address, path, timeout):
        return fleet[address][path]

    monkeypatch.setattr(aggregate, "fetch_json", fake_fetch)
    args = argparse.Namespace(endpoints="h0:1,h1:1", dry_run=True,
                              peers=None, hot_share=0.2, grey_score=0.5,
                              hysteresis=0.0, max_transfers=2,
                              timeout=5.0)
    rc = asyncio.run(cmd_rebalance(args))
    out = capsys.readouterr().out
    assert rc == 2
    assert "placement plan over 2 server(s)" in out
    assert "TRANSFER g1 -> s1" in out and "fair share" in out

    # a balanced fleet: nothing to do, exit 0
    fleet["h0:1"] = payloads("s0", 1, scores=[("s1", 1.0)])
    rc = asyncio.run(cmd_rebalance(args))
    assert rc == 0
    assert "balanced: nothing to do" in capsys.readouterr().out


# ------------------------------------------------------- chaos scenario

@pytest.mark.chaos
def test_rebalance_storm_scenario():
    """The rebalance_storm chaos scenario: the placement controller runs
    armed (fast rounds, zero hysteresis) WHILE faults fire; the standing
    oracles hold (zero lost acks, exactly-once apply) and every
    rebalance actuation the controller opened has its rebalance-done
    pair on the surviving journals."""
    from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties
    from ratis_tpu.chaos.scenario import run_scenario
    from ratis_tpu.chaos.scenarios import build_scenario

    async def main():
        p = chaos_properties(8, seed=7)
        cluster = ChaosCluster(3, 8, properties=p, sm="counter", seed=7)
        await cluster.start()
        try:
            cfg = {"servers": 3, "groups": 8, "writers": 4,
                   "active_groups": 8, "sm": "counter",
                   "convergence_s": 30.0, "recovery_s": 60.0,
                   "min_acked": 20}
            scenario = build_scenario("rebalance_storm", 7, cfg)
            result = await run_scenario(cluster, scenario)
            assert result.passed, (
                f"[seed 7] rebalance_storm failed: {result.error}\n"
                f"journal: {result.journal}")
            assert result.checks.get("rebalance_events", 0) >= 1
            assert (result.checks.get("rebalance_done", 0)
                    >= result.checks.get("rebalance_events", 0))
            assert result.acked > 20
        finally:
            await cluster.close()

    asyncio.run(main())
