"""Serving plane: admission control, batched linearizable reads,
overload behavior.

Covers the round-13 contracts — typed RESOURCE_EXHAUSTED-style shed
replies with a retry-after hint crossing the wire intact, pending-budget
accounting that always drains back to zero, the batched readIndex
confirmation sweep amortizing the per-group heartbeat round, read
linearizability under randomized write/read interleavings on both the
lease and confirmation paths (and across a leadership change), the
overload chaos scenario's SLOs, and the watchdog's sustained-overload
event."""

import asyncio
import random

import pytest

from ratis_tpu.conf import RaftServerConfigKeys
from ratis_tpu.protocol.exceptions import (ResourceUnavailableException,
                                           exception_from_wire,
                                           exception_to_wire)
from ratis_tpu.protocol.ids import ClientId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.requests import (RaftClientRequest,
                                         read_request_type,
                                         write_request_type)
from ratis_tpu.server.read import WriteIndexCache
from tests.minicluster import MiniCluster, fast_properties, run_with_new_cluster

S = RaftServerConfigKeys.Serving


def _admission_props(element_limit: int = 1, retry_after: str = "20ms",
                     linearizable: bool = False, lease: bool = False):
    p = fast_properties()
    p.set(S.ADMISSION_ENABLED_KEY, "true")
    p.set(S.PENDING_ELEMENT_LIMIT_KEY, str(element_limit))
    p.set(S.RETRY_AFTER_KEY, retry_after)
    if linearizable:
        p.set(RaftServerConfigKeys.Read.OPTION_KEY, "LINEARIZABLE")
    if lease:
        p.set_boolean(RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_KEY,
                      True)
    return p


async def _read(cluster: MiniCluster, server_id=None, attempts: int = 40):
    """A read through the MiniCluster failover loop, retrying the
    transient failure replies it surfaces directly: readIndex rejections
    around leadership/term-commit windows, and admission sheds when the
    test budget is deliberately tiny."""
    last = None
    for _ in range(attempts):
        if server_id is None:
            r = await cluster.send_read()
        else:
            r = await cluster.send(b"GET", read_request_type(),
                                   server_id=server_id)
        if r.success:
            return r
        last = r
        await asyncio.sleep(0.05)
    raise AssertionError(f"read kept failing: {last.exception}")


# --------------------------------------------------- write-index cache

def test_write_index_cache_sweep_evicts_expired():
    """The slow-tick sweep drops EVERY expired entry — the lazy get()
    path only evicts keys that are queried again, so a fleet of
    transient client ids would otherwise accrete one entry each."""
    cache = WriteIndexCache(expiry_s=10.0)
    t0 = 1000.0
    import time as _time
    real = _time.monotonic
    _time.monotonic = lambda: t0
    try:
        for i in range(8):
            cache.put(f"c{i}".encode(), i)
        assert len(cache) == 8
        # nothing expired yet
        assert cache.sweep(now=t0 + 5.0) == 0
        assert len(cache) == 8
        # refresh half at t+8; the stale half expires at t+19
        for i in range(4):
            t0 = 1008.0
            cache.put(f"c{i}".encode(), 100 + i)
        assert cache.sweep(now=1000.0 + 11.0) == 4
        assert len(cache) == 4
        assert cache.get(b"c0") == 100
        assert cache.get(b"c7") == -1
        # the refreshed half expires too, and sweep returns the count
        assert cache.sweep(now=1008.0 + 11.0) == 4
        assert len(cache) == 0
    finally:
        _time.monotonic = real


# ------------------------------------------------- typed overload reply

def test_resource_unavailable_retry_after_crosses_wire():
    e = ResourceUnavailableException("s0 shard 0 over pending budget",
                                     retry_after_ms=160)
    d = exception_to_wire(e)
    back = exception_from_wire(d)
    assert isinstance(back, ResourceUnavailableException)
    assert back.retry_after_ms == 160
    assert "over pending budget" in str(back)
    # the zero hint stays off the wire (and decodes to 0)
    plain = exception_from_wire(
        exception_to_wire(ResourceUnavailableException("x")))
    assert plain.retry_after_ms == 0


def test_admission_sheds_typed_replies_and_releases_budget():
    """Overflowing the pending budget sheds with a typed reply carrying a
    retry-after hint; admitted requests apply exactly once; the budget
    drains back to zero afterwards (no ticket leaks on either the
    immediate- or deferred-reply path)."""

    async def body(cluster: MiniCluster):
        from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                                   NotLeaderException)
        client = cluster.factory.new_client_transport(cluster.properties)
        client_id = ClientId.random_id()
        ok, shed = [], []
        # a burst can race a leadership change (the one admitted write
        # fails NotLeader); re-resolve the leader and retry the burst
        for attempt in range(4):
            leader = await cluster.wait_for_leader()
            server = cluster.servers[leader.member_id.peer_id]

            async def one(i: int):
                req = RaftClientRequest(client_id, server.peer_id,
                                        cluster.group.group_id,
                                        1000 + attempt * 100 + i,
                                        Message.value_of(b"INCREMENT"),
                                        type=write_request_type())
                return await client.send_request(server.address, req)

            replies = await asyncio.gather(*(one(i) for i in range(24)))
            ok += [r for r in replies if r.success]
            for r in replies:
                if r.success:
                    continue
                if isinstance(r.exception, (NotLeaderException,
                                            LeaderNotReadyException)):
                    continue  # leadership raced the burst; not a shed
                shed.append(r)
            if ok and shed:
                break
        assert ok, "every write was shed — budget never admits"
        assert shed, "concurrent writes against a 1-element budget " \
                     "never shed"
        for r in shed:
            assert isinstance(r.exception, ResourceUnavailableException), r
            assert r.exception.retry_after_ms >= 20
        admissions = [s.serving.admission for s in cluster.servers.values()]
        assert sum(a.shed_total for a in admissions) == len(shed)
        assert sum(a.admitted_total for a in admissions) >= len(ok)
        # exactly once, no silent drops: the counter equals the ack count
        await cluster.wait_applied(max(r.log_index for r in ok),
                                   divisions=[leader])
        read = await _read(cluster)
        assert read.message.content == str(len(ok)).encode()
        # budget fully released once the dust settles
        for a in admissions:
            assert sum(a.pending_count) == 0
            assert sum(a.pending_bytes) == 0
        # the health endpoint surfaces the serving plane
        h = server.health_info()
        assert h["serving"]["admissionEnabled"] is True
        assert h["serving"]["shedTotal"] == server.serving.admission.shed_total
        assert h["serving"]["pendingCount"] == 0

    run_with_new_cluster(3, body, properties=_admission_props(1))


def test_client_retry_loop_honors_retry_after():
    """The full RaftClient absorbs shed replies: it backs off by the
    server's hint and retries, so a burst against a tiny budget still
    completes every write — the server shed plenty, the caller saw none
    of it."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            replies = await asyncio.gather(
                *(client.io().send(b"INCREMENT") for _ in range(12)))
        assert all(r.success for r in replies)
        shed = sum(s.serving.admission.shed_total
                   for s in cluster.servers.values())
        assert shed > 0, "12 pipelined writes never tripped the 1-element " \
                         "budget — admission was not exercised"
        read = await _read(cluster)
        assert read.message.content == b"12"

    run_with_new_cluster(3, body, properties=_admission_props(1))


# ---------------------------------------------- batched readIndex sweep

def test_batched_confirmation_amortizes_concurrent_reads():
    """40 concurrent linearizable reads (no lease) ride a handful of
    confirmation sweeps, not 40 scalar heartbeat rounds."""

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        server = cluster.servers[leader.member_id.peer_id]
        w = await cluster.send_write()
        await cluster.wait_applied(w.log_index, divisions=[leader])
        sched = server.serving.read_batch
        assert sched is not None
        sweeps0, confirmed0 = sched.sweeps, sched.confirmed
        client = cluster.factory.new_client_transport(cluster.properties)
        client_id = ClientId.random_id()

        async def one_read(i: int):
            req = RaftClientRequest(client_id, server.peer_id,
                                    cluster.group.group_id, 5000 + i,
                                    Message.value_of(b"GET"),
                                    type=read_request_type())
            return await client.send_request(server.address, req)

        replies = await asyncio.gather(*(one_read(i) for i in range(40)))
        assert all(r.success for r in replies), \
            [str(r.exception) for r in replies if not r.success][:3]
        assert all(r.message.content == b"1" for r in replies)
        sweeps = sched.sweeps - sweeps0
        confirmed = sched.confirmed - confirmed0
        assert confirmed == 40, confirmed
        # the acceptance shape: rounds per read well under 1 (the scalar
        # path would have fired 40)
        assert sweeps <= 4, f"{sweeps} sweeps for 40 concurrent reads"

    run_with_new_cluster(3, body,
                         properties=_admission_props(64,
                                                     linearizable=True))


@pytest.mark.chaos
def test_cross_group_sweep_batches_distinct_groups():
    """Reads pending on DIFFERENT groups of one shard share a sweep: the
    confirmation round goes out as one zero-entry envelope per
    destination, not one per group."""
    from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties

    async def main():
        props = chaos_properties(1, seed=3)
        props.set(RaftServerConfigKeys.Read.OPTION_KEY, "LINEARIZABLE")
        cluster = ChaosCluster(3, num_groups=8, properties=props, seed=3)
        await cluster.start()
        try:
            for g in cluster.groups:
                assert await cluster.write(g.group_id)
            servers = list(cluster.servers.values())
            sweeps0 = sum(s.serving.read_batch.sweeps for s in servers)

            async def one_read(g):
                async with cluster.new_client(group=g) as client:
                    return await client.io().send_read_only(b"GET")

            replies = await asyncio.gather(
                *(one_read(g) for g in cluster.groups))
            assert all(r.success for r in replies)
            assert all(r.message.content == b"1" for r in replies)
            sweeps = sum(s.serving.read_batch.sweeps
                         for s in servers) - sweeps0
            assert sweeps <= 4, \
                f"{sweeps} sweeps for 8 cross-group concurrent reads"
        finally:
            await cluster.close()

    asyncio.run(main())


# ------------------------------------------------ read linearizability

@pytest.mark.parametrize("lease", [False, True],
                         ids=["confirmation", "lease"])
def test_reads_never_older_than_acked_writes(lease):
    """Randomized interleaving: a linearizable read submitted AFTER a
    write was acked must observe at least that write — on both the
    confirmation path and the lease fast path."""

    async def body(cluster: MiniCluster):
        await cluster.wait_for_leader()
        rng = random.Random(42 + int(lease))
        acked = 0
        violations: list[tuple[int, int]] = []

        async def writer():
            nonlocal acked
            for _ in range(25):
                r = await cluster.send_write()
                assert r.success
                acked += 1
                await asyncio.sleep(rng.random() * 0.004)

        async def reader():
            for _ in range(15):
                floor = acked
                # the floor is captured BEFORE the first submission, so
                # a transient-failure retry can only see MORE writes —
                # it never weakens the check
                r = await _read(cluster)
                seen = int(r.message.content)
                if seen < floor:
                    violations.append((floor, seen))
                await asyncio.sleep(rng.random() * 0.004)

        await asyncio.gather(writer(), reader(), reader(), reader())
        assert not violations, \
            f"stale linearizable reads (acked_floor, seen): {violations}"
        assert acked == 25

    props = fast_properties()
    props.set(RaftServerConfigKeys.Read.OPTION_KEY, "LINEARIZABLE")
    if lease:
        props.set_boolean(RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_KEY,
                          True)
    run_with_new_cluster(3, body, properties=props)


def test_linearizable_reads_across_leadership_change():
    """A leadership change invalidates the old leader's lease: after the
    old leader is partitioned away and a new one elected, reads reflect
    every write acked by EITHER leader, and the deposed leader steps
    down on heal instead of serving from its stale lease."""

    async def body(cluster: MiniCluster):
        old = await cluster.wait_for_leader()
        for _ in range(3):
            assert (await cluster.send_write()).success
        old_id = old.member_id.peer_id
        for d in cluster.divisions():
            pid = d.member_id.peer_id
            if pid != old_id:
                cluster.network.block(old_id, pid)
                cluster.network.block(pid, old_id)
        # a new leader must rise among the connected majority
        new = None
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            live = [d for d in cluster.divisions()
                    if d.member_id.peer_id != old_id and d.is_leader()]
            if live:
                new = live[0]
                break
            await asyncio.sleep(0.02)
        assert new is not None, "no new leader after partitioning the old"
        new_id = new.member_id.peer_id
        for _ in range(2):
            r = await cluster.send(b"INCREMENT", write_request_type(),
                                   server_id=new_id)
            assert r.success
        # a read submitted after 5 acked writes sees all 5 — the new
        # leader's readIndex covers both reigns
        r = await _read(cluster, server_id=new_id)
        assert r.message.content == b"5"
        cluster.network.unblock_all()
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if not old.is_leader():
                break
            await asyncio.sleep(0.02)
        assert not old.is_leader(), \
            "deposed leader kept leadership (and its lease) after heal"
        r = await _read(cluster)
        assert int(r.message.content) >= 5

    props = fast_properties()
    props.set(RaftServerConfigKeys.Read.OPTION_KEY, "LINEARIZABLE")
    props.set_boolean(RaftServerConfigKeys.Read.LEADER_LEASE_ENABLED_KEY,
                      True)
    run_with_new_cluster(3, body, properties=props)


# ------------------------------------------------- overload under chaos

@pytest.mark.chaos
def test_overload_shed_scenario_slos():
    """The overload_shed scenario: degraded links push a 10-writer burst
    past a 2-element budget.  SLOs — zero lost acks, exactly-once apply,
    shed requests all got typed replies (client timeouts forbidden),
    and shedding actually happened."""
    from ratis_tpu.chaos.cluster import ChaosCluster, chaos_properties
    from ratis_tpu.chaos.scenario import run_scenario
    from ratis_tpu.chaos.scenarios import build_scenario

    async def main():
        props = chaos_properties(1, seed=5)
        props.set(S.ADMISSION_ENABLED_KEY, "true")
        props.set(S.PENDING_ELEMENT_LIMIT_KEY, "2")
        props.set(S.RETRY_AFTER_KEY, "20ms")
        cluster = ChaosCluster(3, 1, properties=props, seed=5)
        await cluster.start()
        try:
            sc = build_scenario("overload_shed", 5,
                                {"convergence_s": 30.0, "recovery_s": 60.0,
                                 "min_acked": 10, "writers": 10,
                                 "expect_shed": True})
            res = await run_scenario(cluster, sc)
            assert res.passed, (
                f"[seed 5] overload_shed failed: {res.error}\n"
                f"journal: {res.journal}")
            assert res.checks["shed_total"] > 0
            assert res.checks["client_timeouts"] == 0
        finally:
            await cluster.close()

    asyncio.run(main())


def test_watchdog_emits_one_overload_event_per_episode():
    """A shed rate above raft.tpu.serving.overload.shed-rate journals ONE
    overload event for the whole episode; a quiet interval closes it."""
    from ratis_tpu.server.watchdog import KIND_OVERLOAD, StallWatchdog

    async def body(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        server = cluster.servers[leader.member_id.peer_id]
        wd = StallWatchdog(server, interval_s=1.0)
        try:
            wd.sample()  # baseline: primes _last_shed
            server.serving.admission.shed_total += 100
            wd.sample()
            events = [e for e in wd.events() if e["kind"] == KIND_OVERLOAD]
            assert len(events) == 1, wd.events()
            assert "shedding" in events[0]["detail"]
            # still saturated: same episode, no second event
            server.serving.admission.shed_total += 100
            wd.sample()
            assert sum(1 for e in wd.events()
                       if e["kind"] == KIND_OVERLOAD) == 1
            # a quiet interval closes the episode; the next burst reopens
            wd.sample()
            server.serving.admission.shed_total += 100
            wd.sample()
            assert sum(1 for e in wd.events()
                       if e["kind"] == KIND_OVERLOAD) == 2
        finally:
            await wd.close()

    props = _admission_props(4)
    props.set(S.OVERLOAD_SHED_RATE_KEY, "10.0")
    run_with_new_cluster(3, body, properties=props)
