"""Basic Raft behavior over the simulated transport.

Mirrors the reference's RaftBasicTests / LeaderElectionTests coverage
(ratis-server/src/test/.../RaftBasicTests.java, LeaderElectionTests.java):
single-leader election, replicated writes, reads, leader kill/failover,
follower catch-up after partition, restart recovery.
"""

import asyncio

import pytest

from ratis_tpu.protocol.ids import RaftPeerId
from tests.minicluster import MiniCluster, run_with_new_cluster


class TestElection:
    def test_three_peer_cluster_elects_one_leader(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            assert leader.is_leader()
            await asyncio.sleep(0.3)  # stability: no dueling leaders
            leaders = cluster.leaders()
            assert len(leaders) == 1
            assert leaders[0].member_id == leader.member_id
            # every follower agrees on the leader
            for d in cluster.divisions():
                if not d.is_leader():
                    assert d.state.leader_id == leader.member_id.peer_id

        run_with_new_cluster(3, body)

    def test_single_peer_self_elects(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            assert leader.is_leader()

        run_with_new_cluster(1, body)


class TestWrites:
    def test_write_replicates_and_applies(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            for i in range(1, 6):
                reply = await cluster.send_write()
                assert reply.success
                assert reply.message.content == str(i).encode()
            read = await cluster.send_read()
            assert read.message.content == b"5"
            # all state machines converge
            last = cluster.leaders()[0].state.log.get_last_committed_index()
            await cluster.wait_applied(last)
            for d in cluster.divisions():
                assert d.state_machine.counter == 5

        run_with_new_cluster(3, body)

    def test_invalid_command_rejected_by_statemachine(self):
        async def body(cluster: MiniCluster):
            await cluster.wait_for_leader()
            reply = await cluster.send(b"bogus")
            assert not reply.success
            from ratis_tpu.protocol.exceptions import StateMachineException
            assert isinstance(reply.exception, StateMachineException)
            # the failed transaction must not have consumed an index
            ok = await cluster.send_write()
            assert ok.success and ok.message.content == b"1"

        run_with_new_cluster(3, body)


class TestFailover:
    def test_leader_kill_triggers_reelection_and_writes_continue(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            for _ in range(3):
                assert (await cluster.send_write()).success
            await cluster.kill_server(leader.member_id.peer_id)
            new_leader = await cluster.wait_for_leader()
            assert new_leader.member_id != leader.member_id
            reply = await cluster.send_write()
            assert reply.success
            assert reply.message.content == b"4"  # no committed writes lost

        run_with_new_cluster(3, body)

    def test_blocked_follower_catches_up(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            fid = follower.member_id.peer_id
            cluster.network.block(leader.member_id.peer_id, fid)
            for _ in range(3):
                assert (await cluster.send_write()).success
            assert follower.state_machine.counter == 0
            cluster.network.unblock(leader.member_id.peer_id, fid)
            last = leader.state.log.get_last_committed_index()
            await cluster.wait_applied(last, divisions=[follower])
            assert follower.state_machine.counter == 3

        run_with_new_cluster(3, body)

    def test_minority_partition_blocks_commit_majority_restores(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            others = [d.member_id.peer_id for d in cluster.divisions()
                      if not d.is_leader()]
            # isolate the leader from both followers: no commits possible
            for f in others:
                cluster.network.block(leader.member_id.peer_id, f)
                cluster.network.block(f, leader.member_id.peer_id)
            write = asyncio.create_task(cluster.send(b"INCREMENT"))
            await asyncio.sleep(0.8)
            # a new leader must have emerged on the majority side
            new_leader = await cluster.wait_for_leader()
            assert new_leader.member_id.peer_id != leader.member_id.peer_id
            cluster.network.unblock_all()
            reply = await write
            assert reply.success  # the client retried to the new leader

        run_with_new_cluster(3, body)


class TestRestart:
    def test_killed_follower_restarts_and_catches_up(self):
        async def body(cluster: MiniCluster):
            leader = await cluster.wait_for_leader()
            follower = next(d for d in cluster.divisions() if not d.is_leader())
            fid = follower.member_id.peer_id
            await cluster.kill_server(fid)
            for _ in range(4):
                assert (await cluster.send_write()).success
            await cluster.restart_server(fid)
            new_div = cluster.servers[fid].divisions[cluster.group.group_id]
            last = (await cluster.wait_for_leader()).state.log \
                .get_last_committed_index()
            await cluster.wait_applied(last, divisions=[new_div])
            # memory log restart: state rebuilt from replicated log
            assert new_div.state_machine.counter == 4

        run_with_new_cluster(3, body)
