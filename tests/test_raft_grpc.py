"""Core Raft scenarios over the real gRPC transport.

Mirrors the reference pattern of instantiating abstract suites per transport
(ratis-test TestRaftWithGrpc etc.): the same behaviors the simulated-rpc
tests cover, driven over real localhost sockets through grpc.aio.
"""

import asyncio

from minicluster import (MiniCluster, fast_properties, free_port,
                         run_with_new_cluster)
from ratis_tpu.protocol.admin import SetConfigurationMode
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


def test_grpc_write_read():
    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(5):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"5"

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_leader_kill_failover():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(leader.member_id.peer_id)
            await cluster.wait_for_leader()
            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"2"

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_restart_rejoins():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        victim = next(d for d in cluster.divisions() if not d.is_leader())
        vid = victim.member_id.peer_id
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(vid)
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.restart_server(vid)
            r = await client.io().send(b"INCREMENT")
            assert r.success
            await cluster.wait_applied(r.log_index)

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_add_peer_and_transfer():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            p = RaftPeer(RaftPeerId.value_of("g1"),
                         address=f"127.0.0.1:{free_port()}")
            await cluster.add_new_server(p)
            empty = RaftGroup.value_of(cluster.group.group_id, [])
            assert (await client.group_management().group_add(empty, p)).success
            r = await client.admin().set_configuration(
                [p], mode=SetConfigurationMode.ADD)
            assert r.success, r
            r = await client.admin().transfer_leadership(p.id,
                                                         timeout_ms=8000.0)
            assert r.success, r
            assert (await client.io().send(b"INCREMENT")).success
            deadline = asyncio.get_event_loop().time() + 10.0
            while True:
                info = await client.group_management().group_info(p)
                if info.role == "LEADER":
                    break
                assert asyncio.get_event_loop().time() < deadline, info
                await asyncio.sleep(0.05)

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_watch_and_stale_read():
    async def t(cluster: MiniCluster):
        from ratis_tpu.protocol.requests import ReplicationLevel
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            r = await client.io().send(b"INCREMENT")
            assert r.success
            w = await client.io().watch(r.log_index, ReplicationLevel.ALL)
            assert w.success
            await cluster.wait_applied(r.log_index)
            follower = next(d for d in cluster.divisions()
                            if not d.is_leader())
            sr = await client.io().send_stale_read(
                b"GET", r.log_index, follower.member_id.peer_id)
            assert sr.success and sr.message.content == b"1"

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_tls_cluster(tmp_path):
    """TLS-secured gRPC transport (reference GrpcTlsConfig +
    GrpcServicesImpl.newNettyServerBuilder:197): a full cluster elects and
    serves writes over TLS; both RPC planes (server-server incl. the append
    stream, client-server) ride secure channels."""
    import subprocess

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True)

    from ratis_tpu.conf.keys import GrpcConfigKeys

    p = fast_properties()
    p.set(GrpcConfigKeys.Tls.ENABLED_KEY, "true")
    p.set(GrpcConfigKeys.Tls.CERT_CHAIN_KEY, str(cert))
    p.set(GrpcConfigKeys.Tls.PRIVATE_KEY_KEY, str(key))
    p.set(GrpcConfigKeys.Tls.TRUST_ROOT_KEY, str(cert))

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for i in range(1, 4):
                r = await client.io().send(b"INCREMENT")
                assert r.success
                assert r.message.content == str(i).encode()
        # a plaintext client cannot talk to the TLS endpoint
        from ratis_tpu.transport.grpc import GrpcClientTransport
        insecure = GrpcClientTransport()
        from ratis_tpu.protocol.exceptions import (RaftException,
                                                   TimeoutIOException)
        from ratis_tpu.protocol.ids import ClientId
        from ratis_tpu.protocol.message import Message
        from ratis_tpu.protocol.requests import (RaftClientRequest,
                                                 write_request_type)
        req = RaftClientRequest(ClientId.random_id(),
                                leader.member_id.peer_id,
                                cluster.group.group_id, 1,
                                Message.value_of(b"INCREMENT"),
                                type=write_request_type(), timeout_ms=2000)
        srv = cluster.servers[leader.member_id.peer_id]
        try:
            await insecure.send_request(srv.address, req)
            raise AssertionError("plaintext request succeeded against TLS")
        except (RaftException, TimeoutIOException):
            pass
        finally:
            await insecure.close()

    run_with_new_cluster(3, t, rpc_type="GRPC", properties=p)


def test_grpc_separate_client_port():
    """Client/admin traffic on its own port (reference GrpcConfigKeys
    client/admin port split): client requests succeed on the dedicated
    endpoint, and the replication plane's port does not serve them... while
    the dedicated port serves no server-to-server RPC."""
    from ratis_tpu.conf.keys import GrpcConfigKeys

    client_ports = {f"s{i}": free_port() for i in range(3)}

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        # every server bound its dedicated client endpoint
        for s in cluster.servers.values():
            assert s.transport.bound_client_port \
                == s.transport.client_port != None  # noqa: E711
        # drive a write via the leader's client port
        from ratis_tpu.transport.grpc import GrpcClientTransport
        srv = cluster.servers[leader.member_id.peer_id]
        host = srv.address.rsplit(":", 1)[0]
        client = GrpcClientTransport()
        try:
            from ratis_tpu.protocol.ids import ClientId
            from ratis_tpu.protocol.message import Message
            from ratis_tpu.protocol.requests import (RaftClientRequest,
                                                     write_request_type)
            req = RaftClientRequest(ClientId.random_id(),
                                    leader.member_id.peer_id,
                                    cluster.group.group_id, 1,
                                    Message.value_of(b"INCREMENT"),
                                    type=write_request_type(),
                                    timeout_ms=10000)
            from ratis_tpu.protocol.exceptions import \
                LeaderNotReadyException
            for _ in range(100):
                reply = await client.send_request(
                    f"{host}:{srv.transport.bound_client_port}", req)
                if not isinstance(reply.exception, LeaderNotReadyException):
                    break  # a real client retries not-ready the same way
                await asyncio.sleep(0.05)
            assert reply.success, reply.exception
            # the replication port no longer serves the client plane
            from ratis_tpu.protocol.exceptions import (RaftException,
                                                       TimeoutIOException)
            req2 = RaftClientRequest(ClientId.random_id(),
                                     leader.member_id.peer_id,
                                     cluster.group.group_id, 2,
                                     Message.value_of(b"INCREMENT"),
                                     type=write_request_type(),
                                     timeout_ms=2000)
            try:
                await client.send_request(srv.address, req2)
                raise AssertionError(
                    "replication port served a client request")
            except (RaftException, TimeoutIOException):
                pass
        finally:
            await client.close()

    # per-peer ports: patch properties per server via a factory-level key is
    # global, so use one port value per server id through a cluster subclass
    class _PerPeerPorts(MiniCluster):
        def _new_server(self, peer):
            self.properties.set(GrpcConfigKeys.CLIENT_PORT_KEY,
                                str(client_ports[str(peer.id)]))
            return super()._new_server(peer)

    async def _main():
        cluster = _PerPeerPorts(3, rpc_type="GRPC")
        await cluster.start()
        try:
            await t(cluster)
        finally:
            await cluster.close()

    asyncio.run(_main())


def test_grpc_client_port_with_advertised_client_address():
    """The standard failover RaftClient works against dedicated client
    ports when peers advertise client_address (RaftPeer.get_client_address;
    without it, a split-port cluster would be unreachable to clients)."""
    from ratis_tpu.conf import RaftProperties, RaftServerConfigKeys
    from ratis_tpu.conf.keys import GrpcConfigKeys
    from ratis_tpu.models.counter import CounterStateMachine
    from ratis_tpu.protocol.group import RaftGroup
    from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
    from ratis_tpu.protocol.peer import RaftPeer as Peer
    from ratis_tpu.server.server import RaftServer
    from ratis_tpu.transport.base import TransportFactory
    from ratis_tpu.client import RaftClient

    async def main():
        factory = TransportFactory.get("GRPC")
        rpc_ports = [free_port() for _ in range(3)]
        cli_ports = [free_port() for _ in range(3)]
        peers = [Peer(RaftPeerId.value_of(f"s{i}"),
                      address=f"127.0.0.1:{rpc_ports[i]}",
                      client_address=f"127.0.0.1:{cli_ports[i]}")
                 for i in range(3)]
        group = RaftGroup.value_of(RaftGroupId.random_id(), peers)
        servers = []
        for i, peer in enumerate(peers):
            p = RaftProperties()
            RaftServerConfigKeys.Rpc.set_timeout(p, "100ms", "200ms")
            RaftServerConfigKeys.Log.set_use_memory(p, True)
            p.set(GrpcConfigKeys.CLIENT_PORT_KEY, str(cli_ports[i]))
            s = RaftServer(peer.id, peer.address,
                           state_machine_registry=lambda gid: CounterStateMachine(),
                           properties=p, transport_factory=factory,
                           group=group)
            servers.append(s)
        for s in servers:
            await s.start()
        try:
            deadline = asyncio.get_event_loop().time() + 10
            while not any(d.is_leader() for s in servers
                          for d in s.divisions.values()):
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.05)
            client = (RaftClient.builder().set_raft_group(group)
                      .set_transport(factory.new_client_transport()).build())
            async with client:
                for i in range(1, 4):
                    r = await client.io().send(b"INCREMENT")
                    assert r.success
                    assert r.message.content == str(i).encode()
        finally:
            for s in servers:
                await s.close()

    asyncio.run(main())


def test_grpc_dedicated_admin_endpoint():
    """Optional third gRPC server for the admin plane
    (GrpcServicesImpl.java:56,197-224): admin operations are served on the
    dedicated port; data-plane requests there are rejected."""
    from ratis_tpu.conf.keys import GrpcConfigKeys

    p = fast_properties()
    admin_port = free_port()
    p.set(GrpcConfigKeys.ADMIN_PORT_KEY, str(admin_port))

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        srv = cluster.servers[leader.member_id.peer_id]
        assert srv.transport.bound_admin_port == admin_port

        from ratis_tpu.protocol.admin import TransferLeadershipArguments
        from ratis_tpu.protocol.exceptions import RaftException
        from ratis_tpu.protocol.ids import ClientId
        from ratis_tpu.protocol.message import Message
        from ratis_tpu.protocol.requests import (RaftClientRequest,
                                                 RequestType,
                                                 admin_request_type,
                                                 write_request_type)
        from ratis_tpu.transport.grpc import GrpcClientTransport

        host = srv.address.rsplit(":", 1)[0]
        admin_addr = f"{host}:{admin_port}"
        client = GrpcClientTransport()
        try:
            # GROUP_LIST (an admin type) served on the admin port
            req = RaftClientRequest(
                ClientId.random_id(), leader.member_id.peer_id,
                cluster.group.group_id, 1, Message.EMPTY,
                type=admin_request_type(RequestType.GROUP_LIST),
                timeout_ms=3000)
            reply = await client.send_request(admin_addr, req)
            assert reply.success

            # a data-plane WRITE is refused on the admin port
            wreq = RaftClientRequest(
                ClientId.random_id(), leader.member_id.peer_id,
                cluster.group.group_id, 2,
                Message.value_of(b"INCREMENT"),
                type=write_request_type(), timeout_ms=3000)
            try:
                await client.send_request(admin_addr, wreq)
                raise AssertionError("WRITE served on the admin port")
            except RaftException:
                pass
        finally:
            await client.close()
        # ... while the normal endpoint still serves both
        async with cluster.new_client() as c:
            assert (await c.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t, rpc_type="GRPC", properties=p)
