"""Core Raft scenarios over the real gRPC transport.

Mirrors the reference pattern of instantiating abstract suites per transport
(ratis-test TestRaftWithGrpc etc.): the same behaviors the simulated-rpc
tests cover, driven over real localhost sockets through grpc.aio.
"""

import asyncio

from minicluster import MiniCluster, free_port, run_with_new_cluster
from ratis_tpu.protocol.admin import SetConfigurationMode
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


def test_grpc_write_read():
    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(5):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"5"

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_leader_kill_failover():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(leader.member_id.peer_id)
            await cluster.wait_for_leader()
            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"2"

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_restart_rejoins():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        victim = next(d for d in cluster.divisions() if not d.is_leader())
        vid = victim.member_id.peer_id
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(vid)
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.restart_server(vid)
            r = await client.io().send(b"INCREMENT")
            assert r.success
            await cluster.wait_applied(r.log_index)

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_add_peer_and_transfer():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            p = RaftPeer(RaftPeerId.value_of("g1"),
                         address=f"127.0.0.1:{free_port()}")
            await cluster.add_new_server(p)
            empty = RaftGroup.value_of(cluster.group.group_id, [])
            assert (await client.group_management().group_add(empty, p)).success
            r = await client.admin().set_configuration(
                [p], mode=SetConfigurationMode.ADD)
            assert r.success, r
            r = await client.admin().transfer_leadership(p.id,
                                                         timeout_ms=8000.0)
            assert r.success, r
            assert (await client.io().send(b"INCREMENT")).success
            deadline = asyncio.get_event_loop().time() + 10.0
            while True:
                info = await client.group_management().group_info(p)
                if info.role == "LEADER":
                    break
                assert asyncio.get_event_loop().time() < deadline, info
                await asyncio.sleep(0.05)

    run_with_new_cluster(3, t, rpc_type="GRPC")


def test_grpc_watch_and_stale_read():
    async def t(cluster: MiniCluster):
        from ratis_tpu.protocol.requests import ReplicationLevel
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            r = await client.io().send(b"INCREMENT")
            assert r.success
            w = await client.io().watch(r.log_index, ReplicationLevel.ALL)
            assert w.success
            await cluster.wait_applied(r.log_index)
            follower = next(d for d in cluster.divisions()
                            if not d.is_leader())
            sr = await client.io().send_stale_read(
                b"GET", r.log_index, follower.member_id.peer_id)
            assert sr.success and sr.message.content == b"1"

    run_with_new_cluster(3, t, rpc_type="GRPC")
