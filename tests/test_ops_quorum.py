"""Differential tests: batched quorum kernels vs the scalar reference.

Strategy mirrors the build plan (SURVEY.md §7 step 4): the scalar module is a
transliteration of the reference algorithms; hypothesis generates arbitrary
[G, P] states and the jitted kernels must agree elementwise for every group.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from ratis_tpu.ops import quorum as q
from ratis_tpu.ops import reference as ref

P_MAX = 8
G_FIXED = 4  # pad every generated batch to this many groups: one jit cache hit

# jit once at module scope so each hypothesis example reuses the compiled fn
_jmajority_min = jax.jit(q.majority_min)
_jupdate_commit = jax.jit(q.update_commit)
_jtally_votes = jax.jit(q.tally_votes)
_jcheck_leadership = jax.jit(q.check_leadership)
_jlease_expiry = jax.jit(q.lease_expiry)
_jall_replicated_min = jax.jit(q.all_replicated_min)


def _pad(groups):
    """Repeat the last group until the batch has G_FIXED rows (static shape)."""
    groups = list(groups)
    while len(groups) < G_FIXED:
        groups.append(groups[-1])
    return groups[:G_FIXED]


@st.composite
def group_state(draw):
    """One group's quorum-relevant state with realistic invariants."""
    n = draw(st.integers(1, P_MAX))
    conf_cur = [draw(st.booleans()) for _ in range(n)] + [False] * (P_MAX - n)
    if not any(conf_cur):
        conf_cur[draw(st.integers(0, n - 1))] = True
    joint = draw(st.booleans())
    conf_old = [False] * P_MAX
    if joint:
        conf_old = [draw(st.booleans()) for _ in range(n)] + [False] * (P_MAX - n)
    match = [draw(st.integers(-1, 50)) for _ in range(P_MAX)]
    self_slot = draw(st.integers(0, n - 1))
    return {
        "conf_cur": conf_cur, "conf_old": conf_old, "match": match,
        "self_slot": self_slot,
        "flush": draw(st.integers(-1, 60)),
        "commit": draw(st.integers(-1, 40)),
        "first_leader_index": draw(st.integers(0, 30)),
        "is_leader": draw(st.booleans()),
        "grants": [draw(st.booleans()) for _ in range(P_MAX)],
        "rejects": [draw(st.booleans()) for _ in range(P_MAX)],
        "priority": [draw(st.integers(0, 3)) for _ in range(P_MAX)],
        "self_priority": draw(st.integers(0, 3)),
        "last_ack": [draw(st.integers(0, 1000)) for _ in range(P_MAX)],
    }


def _batch(groups, key, dtype=np.int32):
    return jnp.asarray(np.array([g[key] for g in groups], dtype=dtype))


def _self_mask(groups):
    m = np.zeros((len(groups), P_MAX), dtype=bool)
    for i, g in enumerate(groups):
        m[i, g["self_slot"]] = True
    return jnp.asarray(m)


@settings(max_examples=60, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4))
def test_majority_min_differential(groups):
    groups = _pad(groups)
    got = np.asarray(_jmajority_min(_batch(groups, "match"),
                                    _batch(groups, "conf_cur", bool)))
    for i, g in enumerate(groups):
        assert got[i] == ref.majority_min(g["match"], g["conf_cur"]), g


@settings(max_examples=60, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4))
def test_update_commit_differential(groups):
    groups = _pad(groups)
    out = _jupdate_commit(
        _batch(groups, "match"), _self_mask(groups), _batch(groups, "flush"),
        _batch(groups, "conf_cur", bool), _batch(groups, "conf_old", bool),
        _batch(groups, "commit"), _batch(groups, "first_leader_index"),
        _batch(groups, "is_leader", bool))
    for i, g in enumerate(groups):
        want_commit, want_changed = ref.update_commit(
            g["match"], g["self_slot"], g["flush"], g["conf_cur"],
            g["conf_old"], g["commit"], g["first_leader_index"], g["is_leader"])
        assert int(out.new_commit[i]) == want_commit, g
        assert bool(out.changed[i]) == want_changed, g


@settings(max_examples=60, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4))
def test_tally_votes_differential(groups):
    groups = _pad(groups)
    out = _jtally_votes(
        _batch(groups, "grants", bool), _batch(groups, "rejects", bool),
        _batch(groups, "conf_cur", bool), _batch(groups, "conf_old", bool),
        _batch(groups, "priority"), _batch(groups, "self_priority"))
    for i, g in enumerate(groups):
        want_pass, want_pass_to, want_rej = ref.tally_votes(
            g["grants"], g["rejects"], g["conf_cur"], g["conf_old"],
            g["priority"], g["self_priority"])
        assert bool(out.passed[i]) == want_pass, g
        assert bool(out.passed_on_timeout[i]) == want_pass_to, g
        assert bool(out.rejected[i]) == want_rej, g
        assert bool(out.decided[i]) == (want_pass or want_rej)


@settings(max_examples=50, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4),
       st.integers(0, 2000), st.integers(1, 500))
def test_check_leadership_differential(groups, now, timeout):
    groups = _pad(groups)
    got = np.asarray(_jcheck_leadership(
        _batch(groups, "last_ack"), _self_mask(groups),
        _batch(groups, "conf_cur", bool), _batch(groups, "conf_old", bool),
        jnp.int32(now), jnp.int32(timeout), _batch(groups, "is_leader", bool)))
    for i, g in enumerate(groups):
        want = ref.check_leadership(g["last_ack"], g["self_slot"],
                                    g["conf_cur"], g["conf_old"], now, timeout,
                                    g["is_leader"])
        assert bool(got[i]) == want, g


@settings(max_examples=50, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4), st.integers(1, 500))
def test_lease_expiry_differential(groups, lease_ms):
    groups = _pad(groups)
    got = np.asarray(_jlease_expiry(
        _batch(groups, "last_ack"), _self_mask(groups),
        _batch(groups, "conf_cur", bool), _batch(groups, "conf_old", bool),
        jnp.int32(lease_ms)))
    for i, g in enumerate(groups):
        want = ref.lease_expiry(g["last_ack"], g["self_slot"], g["conf_cur"],
                                g["conf_old"], lease_ms)
        assert int(got[i]) == want, g


@settings(max_examples=50, deadline=None)
@given(st.lists(group_state(), min_size=1, max_size=4))
def test_all_replicated_min_differential(groups):
    groups = _pad(groups)
    got = np.asarray(_jall_replicated_min(
        _batch(groups, "match"), _self_mask(groups), _batch(groups, "flush"),
        _batch(groups, "conf_cur", bool), _batch(groups, "conf_old", bool)))
    for i, g in enumerate(groups):
        want = ref.all_replicated_min(g["match"], g["self_slot"], g["flush"],
                                      g["conf_cur"], g["conf_old"])
        assert int(got[i]) == want, g


class TestKnownCases:
    """Hand-checked cases pinned from the reference semantics."""

    def test_five_peer_median(self):
        # matchIndexes [9, 5, 7, 2, 8] -> majority-min is 7 (3 peers >= 7)
        vals = jnp.asarray([[9, 5, 7, 2, 8, 0, 0, 0]], dtype=jnp.int32)
        mask = jnp.asarray([[1, 1, 1, 1, 1, 0, 0, 0]], dtype=bool)
        assert int(q.majority_min(vals, mask)[0]) == 7

    def test_term_gate_blocks_old_term_commit(self):
        # Majority index 5 but leader's first index this term is 6: no commit
        # (Raft §5.4.2; reference updateCommit's term check).
        out = _jupdate_commit(
            jnp.asarray([[5, 5, 0, 0, 0, 0, 0, 0]], jnp.int32),
            jnp.asarray([[0, 0, 1, 0, 0, 0, 0, 0]], bool),
            jnp.asarray([9], jnp.int32),
            jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool),
            jnp.zeros((1, 8), bool),
            jnp.asarray([2], jnp.int32), jnp.asarray([6], jnp.int32),
            jnp.asarray([True]))
        assert int(out.new_commit[0]) == 2 and not bool(out.changed[0])

    def test_joint_consensus_needs_both(self):
        # grants majority in new conf only -> not passed while joint.
        grants = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
        conf_cur = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
        conf_old = jnp.asarray([[0, 0, 1, 1, 1, 0, 0, 0]], bool)
        out = _jtally_votes(grants, jnp.zeros((1, 8), bool), conf_cur,
                            conf_old, jnp.zeros((1, 8), jnp.int32),
                            jnp.zeros(1, jnp.int32))
        assert not bool(out.passed[0])

    def test_priority_veto_beats_majority(self):
        # 2-of-3 grants BUT the rejecting peer has higher priority: REJECTED
        # unconditionally (LeaderElection.java:554-556).
        grants = jnp.asarray([[1, 1, 0, 0, 0, 0, 0, 0]], bool)
        rejects = jnp.asarray([[0, 0, 1, 0, 0, 0, 0, 0]], bool)
        conf = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
        prio = jnp.asarray([[0, 0, 5, 0, 0, 0, 0, 0]], jnp.int32)
        out = _jtally_votes(grants, rejects, conf, jnp.zeros((1, 8), bool),
                            prio, jnp.zeros(1, jnp.int32))
        assert bool(out.rejected[0])
        assert not bool(out.passed[0]) and not bool(out.passed_on_timeout[0])

    def test_unreplied_higher_priority_blocks_until_timeout(self):
        # Majority granted, higher-priority peer silent: strict pass blocked
        # (higherPriorityPeers.isEmpty() gate, LeaderElection.java:569-572)
        # but the round-deadline path passes (LeaderElection.java:515-519).
        grants = jnp.asarray([[1, 1, 0, 0, 0, 0, 0, 0]], bool)
        rejects = jnp.zeros((1, 8), bool)
        conf = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
        prio = jnp.asarray([[0, 0, 5, 0, 0, 0, 0, 0]], jnp.int32)
        out = _jtally_votes(grants, rejects, conf, jnp.zeros((1, 8), bool),
                            prio, jnp.zeros(1, jnp.int32))
        assert not bool(out.passed[0])
        assert bool(out.passed_on_timeout[0])
        # once the higher-priority peer replies with a grant, strict pass:
        grants2 = jnp.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], bool)
        out2 = _jtally_votes(grants2, rejects, conf, jnp.zeros((1, 8), bool),
                             prio, jnp.zeros(1, jnp.int32))
        assert bool(out2.passed[0])


class TestEventPacking:
    def test_ack_scatter_max(self):
        match = jnp.zeros((3, 4), jnp.int32)
        ack = jnp.zeros((3, 4), jnp.int32)
        # two acks for (g1,p2): 7 then 5 -> keeps 7; invalid slot ignored
        evg = jnp.asarray([1, 1, 2, 0], jnp.int32)
        evp = jnp.asarray([2, 2, 3, 0], jnp.int32)
        evm = jnp.asarray([7, 5, 9, 100], jnp.int32)
        evt = jnp.asarray([10, 20, 30, 999], jnp.int32)
        valid = jnp.asarray([True, True, True, False])
        m2, a2 = q.apply_ack_events(match, ack, evg, evp, evm, evt, valid)
        assert int(m2[1, 2]) == 7 and int(a2[1, 2]) == 20
        assert int(m2[2, 3]) == 9
        assert int(m2[0, 0]) == 0 and int(a2[0, 0]) == 0

    def test_vote_scatter(self):
        g = jnp.zeros((2, 3), bool)
        r = jnp.zeros((2, 3), bool)
        evg = jnp.asarray([0, 1, 0], jnp.int32)
        evp = jnp.asarray([1, 2, 0], jnp.int32)
        granted = jnp.asarray([True, False, True])
        valid = jnp.asarray([True, True, False])
        g2, r2 = q.apply_vote_events(g, r, evg, evp, granted, valid)
        assert bool(g2[0, 1]) and not bool(r2[0, 1])
        assert bool(r2[1, 2]) and not bool(g2[1, 2])
        assert not bool(g2[0, 0])  # invalid dropped


def test_kernels_jit_and_batch_10k():
    """The whole point: 10k groups advance in one jitted dispatch."""
    G, P = 10000, 5
    rng = np.random.default_rng(0)
    match = jnp.asarray(rng.integers(0, 100, (G, P)), jnp.int32)
    self_mask = jnp.asarray(np.eye(P, dtype=bool)[rng.integers(0, P, G)])
    flush = jnp.asarray(rng.integers(0, 100, G), jnp.int32)
    conf = jnp.ones((G, P), bool)
    conf_old = jnp.zeros((G, P), bool)
    commit = jnp.zeros(G, jnp.int32)
    first = jnp.zeros(G, jnp.int32)
    leader = jnp.ones(G, bool)

    step = jax.jit(q.update_commit)
    out = step(match, self_mask, flush, conf, conf_old, commit, first, leader)
    out.new_commit.block_until_ready()
    assert out.new_commit.shape == (G,)
    # spot-check one group against the scalar reference
    i = 1234
    want, _ = ref.update_commit(
        [int(x) for x in np.asarray(match[i])], int(np.argmax(self_mask[i])),
        int(flush[i]), [True] * P, [False] * P, 0, 0, True)
    assert int(out.new_commit[i]) == want


def test_vote_scatter_first_reply_wins():
    """A retransmitted/flipped reply must not mark a peer as both grant and
    reject (reference ignores duplicates via responses.putIfAbsent)."""
    g = jnp.zeros((1, 3), bool)
    r = jnp.zeros((1, 3), bool)
    # first batch: peer 1 grants
    g, r = q.apply_vote_events(g, r, jnp.asarray([0], jnp.int32),
                               jnp.asarray([1], jnp.int32),
                               jnp.asarray([True]), jnp.asarray([True]))
    # second batch: stale reject from the same peer -> dropped
    g, r = q.apply_vote_events(g, r, jnp.asarray([0], jnp.int32),
                               jnp.asarray([1], jnp.int32),
                               jnp.asarray([False]), jnp.asarray([True]))
    assert bool(g[0, 1]) and not bool(r[0, 1])


def test_engine_epoch_rebase():
    """Time arrays shift uniformly when the int32 clock approaches wrap."""
    import asyncio
    from ratis_tpu.engine.engine import QuorumEngine

    async def main():
        e = QuorumEngine(max_groups=4, max_peers=3)
        slot = e.state.allocate()
        fake_now = (1 << 30) + 500
        e.clock._t0 -= fake_now / 1000.0  # pretend 12+ days of uptime
        e.state.last_ack_ms[slot, :] = fake_now - 10
        e.state.election_deadline_ms[slot] = fake_now + 150
        now = e._maybe_rebase_epoch(e.clock.now_ms())
        assert now < 4_000_000, now  # rebased near _REBASE_KEEP_MS (1 hour)
        # relative distances preserved
        assert abs(int(e.state.election_deadline_ms[slot]) - now - 150) < 50
        assert abs(now - int(e.state.last_ack_ms[slot, 0]) - 10) < 50

    asyncio.run(main())
