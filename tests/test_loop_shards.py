"""Host-runtime loop sharding (raft.tpu.server.loop-shards) and the
multi-process cluster harness.

Covers the three contracts the sharded runtime adds on top of the
single-loop one: stable division->shard placement with cross-shard request
routing, thread-safe engine event intake from worker loops, and the
subprocess cluster's lifecycle (spawn -> bring-up -> load -> teardown)."""

import asyncio
import threading

import pytest

from ratis_tpu.server.shards import LoopShardPool


# ------------------------------------------------------------- shard pool

def test_shard_pool_placement_stable_and_spread():
    pool = LoopShardPool("t", 4)
    keys = [bytes([i, i ^ 7, 3 * i % 251, 99]) * 4 for i in range(64)]
    first = [pool.shard_of(k) for k in keys]
    assert first == [pool.shard_of(k) for k in keys], "placement not stable"
    assert all(0 <= s < 4 for s in first)
    assert len(set(first)) > 1, "hash pin never spread across shards"


def test_shard_pool_run_on_executes_on_owning_loop():
    async def body():
        pool = LoopShardPool("t", 3)
        pool.start()
        try:
            primary = asyncio.get_running_loop()
            assert pool.loop(0) is primary

            async def where():
                return asyncio.get_running_loop()

            for idx in range(3):
                loop = await pool.run_on(idx, where())
                assert loop is pool.loop(idx)
            # exceptions propagate through the cross-loop hop unchanged
            async def boom():
                raise ValueError("crossed")

            with pytest.raises(ValueError, match="crossed"):
                await pool.run_on(1, boom())
        finally:
            await pool.close()
        assert not pool.started

    asyncio.run(body())


def test_shard_pool_close_joins_threads():
    async def body():
        pool = LoopShardPool("t", 3)
        pool.start()
        threads = list(pool._threads)
        assert all(t.is_alive() for t in threads)
        await pool.close()
        assert all(not t.is_alive() for t in threads)

    asyncio.run(body())


# ------------------------------------------- thread-safe engine intake

def test_engine_intake_from_worker_threads():
    """Shard loops call on_ack/on_flush/on_deadline from their own
    threads while the tick task runs on the home loop: the rings and the
    host mirror must stay coherent (no lost swaps, no torn state)."""
    from ratis_tpu.engine.engine import QuorumEngine

    async def body():
        eng = QuorumEngine(max_groups=64, max_peers=8,
                           tick_interval_s=0.001,
                           scalar_fallback_threshold=10**9)

        class Listener:
            async def on_election_timeout(self):
                pass

            async def on_commit_advance(self, c):
                pass

            async def on_leadership_stale(self):
                pass

        slots = [eng.attach(Listener()) for _ in range(8)]
        await eng.start()
        try:
            iters = 400

            def hammer(k: int) -> None:
                for i in range(iters):
                    for slot in slots:
                        eng.on_ack(slot, (k + 1) % 8, i)
                        eng.on_flush(slot, i)
                        eng.on_deadline(slot, 1 << 29)

            await asyncio.gather(
                *(asyncio.to_thread(hammer, k) for k in range(4)))
            # let the tick drain what the threads queued
            for _ in range(50):
                await asyncio.sleep(0.005)
                if not eng._ack_ring and not eng._slot_updates:
                    break
            assert not eng._ack_ring, "ack ring never drained"
            s = eng.state
            for slot in slots:
                # every slot saw the max flush the threads pushed
                assert int(s.flush_index[slot]) == iters - 1
            assert eng.metrics["acks"] == 4 * iters * len(slots), \
                "intake lost acks across threads"
        finally:
            await eng.close()
            for slot in slots:
                eng.detach(slot)

    asyncio.run(body())


def test_engine_batch_intake_from_worker_threads():
    """Same coherence contract as the scalar-intake hammer above, driven
    through the packed batch API (QuorumEngine.on_ack_batch): no lost
    rows, no torn mirror state, ring fully drained."""
    from ratis_tpu.engine.engine import QuorumEngine

    async def body():
        eng = QuorumEngine(max_groups=64, max_peers=8,
                           tick_interval_s=0.001,
                           scalar_fallback_threshold=10**9)

        class Listener:
            async def on_election_timeout(self):
                pass

            async def on_commit_advance(self, c):
                pass

            async def on_leadership_stale(self):
                pass

        slots = [eng.attach(Listener()) for _ in range(8)]
        await eng.start()
        try:
            iters = 400

            def hammer(k: int) -> None:
                for i in range(iters):
                    eng.on_ack_batch([(slot, (k + 1) % 8, i)
                                      for slot in slots])
                    for slot in slots:
                        eng.on_flush(slot, i)

            await asyncio.gather(
                *(asyncio.to_thread(hammer, k) for k in range(4)))
            for _ in range(50):
                await asyncio.sleep(0.005)
                if not eng._ack_ring and not eng._slot_updates:
                    break
            assert not eng._ack_ring, "ack ring never drained"
            s = eng.state
            for slot in slots:
                assert int(s.flush_index[slot]) == iters - 1
            assert eng.metrics["acks"] == 4 * iters * len(slots), \
                "batch intake lost acks across threads"
        finally:
            await eng.close()
            for slot in slots:
                eng.detach(slot)

    asyncio.run(body())


def test_ack_batch_bit_identical_to_scalar_intake():
    """Randomized ack/flush sequences fed through scalar on_ack vs chunked
    on_ack_batch must yield identical commit indices, identical flush
    state, and the identical inline commit-callback order (the round-8
    equivalence contract: the packed intake is a locking/batching change,
    never a math change)."""
    import random

    import numpy as np

    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.engine.state import ROLE_LEADER

    def run(batched: bool):
        eng = QuorumEngine(max_groups=32, max_peers=8,
                           scalar_fallback_threshold=10**9)
        calls: list[tuple[int, int]] = []

        class Rec:
            def __init__(self, ident: int) -> None:
                self.ident = ident

            def on_commit_advance_now(self, commit: int) -> None:
                calls.append((self.ident, commit))

            async def on_commit_advance(self, commit: int) -> None:
                self.on_commit_advance_now(commit)

            async def on_election_timeout(self) -> None:
                pass

            async def on_leadership_stale(self) -> None:
                pass

        slots = []
        st = eng.state
        for i in range(8):
            slot = eng.attach(Rec(i))
            slots.append(slot)
            cur = np.zeros(8, bool)
            cur[:3] = True  # 3-peer conf, self at column 0
            st.set_conf(slot, 0, cur, np.zeros(8, bool),
                        np.zeros(8, np.int32), 0)
            st.role[slot] = ROLE_LEADER
            st.first_leader_index[slot] = 0
        rng = random.Random(1234)
        events = []
        for _ in range(600):
            slot = slots[rng.randrange(8)]
            if rng.random() < 0.25:
                events.append(("flush", slot, rng.randrange(0, 120)))
            else:
                events.append(("ack", slot, rng.randrange(1, 3),
                               rng.randrange(0, 120)))
        i = 0
        chunk_rng = random.Random(99)
        while i < len(events):
            kind = events[i][0]
            if kind == "flush" or not batched:
                if kind == "flush":
                    eng.on_flush(events[i][1], events[i][2])
                else:
                    eng.on_ack(events[i][1], events[i][2], events[i][3])
                i += 1
                continue
            # batched: take the maximal run of consecutive acks, feed it
            # through on_ack_batch in random-size chunks
            j = i
            while j < len(events) and events[j][0] == "ack":
                j += 1
            run_rows = [(e[1], e[2], e[3]) for e in events[i:j]]
            k = 0
            while k < len(run_rows):
                n = chunk_rng.randrange(1, 17)
                eng.on_ack_batch(run_rows[k:k + n])
                k += n
            i = j
        commits = [int(st.commit_index[s]) for s in slots]
        flushes = [int(st.flush_index[s]) for s in slots]
        ring = [(g, p, m) for g, p, m, _t in eng._ack_ring]
        eng._m.unregister()
        return commits, flushes, calls, ring

    assert run(False) == run(True)


def test_cross_shard_engine_wakes_dedupe_to_one():
    """A burst of cross-thread intake wakes must schedule ONE home-loop
    call_soon_threadsafe callback, not one per caller (ISSUE 5 bugfix:
    coalesce pending notify wakes under the intake lock).  Deterministic:
    the home loop's thread is blocked in join() for the whole burst, so
    the armed wake cannot fire-and-clear mid-burst."""
    import numpy as np

    from ratis_tpu.engine.engine import QuorumEngine
    from ratis_tpu.engine.state import ROLE_LEADER
    from ratis_tpu.metrics import hops as hops_mod

    async def body():
        eng = QuorumEngine(max_groups=8, max_peers=8,
                           scalar_fallback_threshold=10**9)

        class L:  # no on_commit_advance_now: every ack wakes the tick
            async def on_election_timeout(self):
                pass

            async def on_commit_advance(self, c):
                pass

            async def on_leadership_stale(self):
                pass

        slot = eng.attach(L())
        st = eng.state
        cur = np.zeros(8, bool)
        cur[:3] = True
        st.set_conf(slot, 0, cur, np.zeros(8, bool), np.zeros(8, np.int32), 0)
        st.role[slot] = ROLE_LEADER
        eng._home_loop = asyncio.get_running_loop()

        def burst() -> None:
            for i in range(200):
                eng.on_ack(slot, 1, i + 1)

        hops_mod.reset()
        t = threading.Thread(target=burst)
        t.start()
        t.join()  # blocks the home loop: no wake can fire mid-burst
        assert hops_mod.snapshot()["engine_wake"] == 1, \
            "a 200-ack burst must schedule exactly one notify wake"
        await asyncio.sleep(0)  # let the armed wake fire and clear
        assert not eng._wake_pending
        eng._m.unregister()

    asyncio.run(body())


# -------------------------------------------------- sharded cluster e2e

def test_sharded_cluster_routes_and_pins_divisions():
    """A loop-sharded server must (a) spread divisions across shards,
    (b) run each division's machinery ON its pinned loop, and (c) serve
    cross-shard client/server traffic correctly end to end."""
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def body():
        cluster = BenchCluster(8, num_servers=3, batched=False,
                               transport="tcp", loop_shards=2)
        await cluster.start()
        try:
            s0 = cluster.servers[0]
            assert s0.shards is not None and s0.shards.n == 2
            placed = {s0.shard_of_group(g.group_id)
                      for g in cluster.groups}
            assert len(placed) > 1, "8 groups all hashed to one shard"
            for g in cluster.groups:
                d = s0.divisions[g.group_id]
                idx = s0.shard_of_group(g.group_id)
                # the apply loop (the division's standing task) lives on
                # the pinned loop
                assert d._apply_task.get_loop() is s0.shards.loop(idx)
            out = await cluster.run_load(2, concurrency=8)
            assert out["write_failures"] == 0
            assert out["commits"] == 8 * 2
        finally:
            await cluster.close()

    asyncio.run(body())


def test_sharded_client_driver_over_tcp():
    """client_shards: the load generator split across threads/loops with
    independent connections produces the same commits, and loop-shards=1
    + client_shards=1 still goes through the unsharded code path."""
    from ratis_tpu.tools.bench_cluster import run_bench

    async def body():
        out = await run_bench(4, 3, batched=False, concurrency=8,
                              transport="tcp", warmup_writes=0,
                              loop_shards=2, client_shards=2)
        assert out["write_failures"] == 0
        assert out["commits"] == 12
        assert out["client_shards"] == 2
        assert out["loop_shards"] == 2

    asyncio.run(body())


# ----------------------------------------------- multi-process harness

def test_multiproc_cluster_lifecycle():
    """Spawn a real 3-process cluster + 2 client processes, push writes
    through it, and verify the harness tears every child down."""
    from ratis_tpu.tools.bench_cluster import run_multiproc_bench

    async def body():
        out = await run_multiproc_bench(
            4, 2, num_servers=3, transport="tcp", loop_shards=2,
            client_procs=2, concurrency=8, bringup_timeout_s=420.0,
            load_timeout_s=300.0)
        assert out["write_failures"] == 0
        assert out["commits"] == 8
        assert out["mp"] == {"server_procs": 3, "client_procs": 2,
                             "loop_shards": 2}
        assert out["commits_per_sec"] > 0
        return out

    asyncio.run(body())
    # teardown proof: no stray --mp-server/--mp-client children survive
    import subprocess
    ps = subprocess.run(["ps", "ax"], capture_output=True, text=True)
    assert "--mp-server" not in ps.stdout
    assert "--mp-client" not in ps.stdout
