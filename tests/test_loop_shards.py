"""Host-runtime loop sharding (raft.tpu.server.loop-shards) and the
multi-process cluster harness.

Covers the three contracts the sharded runtime adds on top of the
single-loop one: stable division->shard placement with cross-shard request
routing, thread-safe engine event intake from worker loops, and the
subprocess cluster's lifecycle (spawn -> bring-up -> load -> teardown)."""

import asyncio
import threading

import pytest

from ratis_tpu.server.shards import LoopShardPool


# ------------------------------------------------------------- shard pool

def test_shard_pool_placement_stable_and_spread():
    pool = LoopShardPool("t", 4)
    keys = [bytes([i, i ^ 7, 3 * i % 251, 99]) * 4 for i in range(64)]
    first = [pool.shard_of(k) for k in keys]
    assert first == [pool.shard_of(k) for k in keys], "placement not stable"
    assert all(0 <= s < 4 for s in first)
    assert len(set(first)) > 1, "hash pin never spread across shards"


def test_shard_pool_run_on_executes_on_owning_loop():
    async def body():
        pool = LoopShardPool("t", 3)
        pool.start()
        try:
            primary = asyncio.get_running_loop()
            assert pool.loop(0) is primary

            async def where():
                return asyncio.get_running_loop()

            for idx in range(3):
                loop = await pool.run_on(idx, where())
                assert loop is pool.loop(idx)
            # exceptions propagate through the cross-loop hop unchanged
            async def boom():
                raise ValueError("crossed")

            with pytest.raises(ValueError, match="crossed"):
                await pool.run_on(1, boom())
        finally:
            await pool.close()
        assert not pool.started

    asyncio.run(body())


def test_shard_pool_close_joins_threads():
    async def body():
        pool = LoopShardPool("t", 3)
        pool.start()
        threads = list(pool._threads)
        assert all(t.is_alive() for t in threads)
        await pool.close()
        assert all(not t.is_alive() for t in threads)

    asyncio.run(body())


# ------------------------------------------- thread-safe engine intake

def test_engine_intake_from_worker_threads():
    """Shard loops call on_ack/on_flush/on_deadline from their own
    threads while the tick task runs on the home loop: the rings and the
    host mirror must stay coherent (no lost swaps, no torn state)."""
    from ratis_tpu.engine.engine import QuorumEngine

    async def body():
        eng = QuorumEngine(max_groups=64, max_peers=8,
                           tick_interval_s=0.001,
                           scalar_fallback_threshold=10**9)

        class Listener:
            async def on_election_timeout(self):
                pass

            async def on_commit_advance(self, c):
                pass

            async def on_leadership_stale(self):
                pass

        slots = [eng.attach(Listener()) for _ in range(8)]
        await eng.start()
        try:
            iters = 400

            def hammer(k: int) -> None:
                for i in range(iters):
                    for slot in slots:
                        eng.on_ack(slot, (k + 1) % 8, i)
                        eng.on_flush(slot, i)
                        eng.on_deadline(slot, 1 << 29)

            await asyncio.gather(
                *(asyncio.to_thread(hammer, k) for k in range(4)))
            # let the tick drain what the threads queued
            for _ in range(50):
                await asyncio.sleep(0.005)
                if not eng._ack_ring and not eng._slot_updates:
                    break
            assert not eng._ack_ring, "ack ring never drained"
            s = eng.state
            for slot in slots:
                # every slot saw the max flush the threads pushed
                assert int(s.flush_index[slot]) == iters - 1
            assert eng.metrics["acks"] == 4 * iters * len(slots), \
                "intake lost acks across threads"
        finally:
            await eng.close()
            for slot in slots:
                eng.detach(slot)

    asyncio.run(body())


# -------------------------------------------------- sharded cluster e2e

def test_sharded_cluster_routes_and_pins_divisions():
    """A loop-sharded server must (a) spread divisions across shards,
    (b) run each division's machinery ON its pinned loop, and (c) serve
    cross-shard client/server traffic correctly end to end."""
    from ratis_tpu.tools.bench_cluster import BenchCluster

    async def body():
        cluster = BenchCluster(8, num_servers=3, batched=False,
                               transport="tcp", loop_shards=2)
        await cluster.start()
        try:
            s0 = cluster.servers[0]
            assert s0.shards is not None and s0.shards.n == 2
            placed = {s0.shard_of_group(g.group_id)
                      for g in cluster.groups}
            assert len(placed) > 1, "8 groups all hashed to one shard"
            for g in cluster.groups:
                d = s0.divisions[g.group_id]
                idx = s0.shard_of_group(g.group_id)
                # the apply loop (the division's standing task) lives on
                # the pinned loop
                assert d._apply_task.get_loop() is s0.shards.loop(idx)
            out = await cluster.run_load(2, concurrency=8)
            assert out["write_failures"] == 0
            assert out["commits"] == 8 * 2
        finally:
            await cluster.close()

    asyncio.run(body())


def test_sharded_client_driver_over_tcp():
    """client_shards: the load generator split across threads/loops with
    independent connections produces the same commits, and loop-shards=1
    + client_shards=1 still goes through the unsharded code path."""
    from ratis_tpu.tools.bench_cluster import run_bench

    async def body():
        out = await run_bench(4, 3, batched=False, concurrency=8,
                              transport="tcp", warmup_writes=0,
                              loop_shards=2, client_shards=2)
        assert out["write_failures"] == 0
        assert out["commits"] == 12
        assert out["client_shards"] == 2
        assert out["loop_shards"] == 2

    asyncio.run(body())


# ----------------------------------------------- multi-process harness

def test_multiproc_cluster_lifecycle():
    """Spawn a real 3-process cluster + 2 client processes, push writes
    through it, and verify the harness tears every child down."""
    from ratis_tpu.tools.bench_cluster import run_multiproc_bench

    async def body():
        out = await run_multiproc_bench(
            4, 2, num_servers=3, transport="tcp", loop_shards=2,
            client_procs=2, concurrency=8, bringup_timeout_s=420.0,
            load_timeout_s=300.0)
        assert out["write_failures"] == 0
        assert out["commits"] == 8
        assert out["mp"] == {"server_procs": 3, "client_procs": 2,
                             "loop_shards": 2}
        assert out["commits_per_sec"] > 0
        return out

    asyncio.run(body())
    # teardown proof: no stray --mp-server/--mp-client children survive
    import subprocess
    ps = subprocess.run(["ps", "ax"], capture_output=True, text=True)
    assert "--mp-server" not in ps.stdout
    assert "--mp-client" not in ps.stdout
