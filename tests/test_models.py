"""Model state-machine tests (reference ratis-examples arithmetic/counter
suites: TestArithmetic, arithmetic/TestArithmeticLogDump)."""

import pytest

from ratis_tpu.models.arithmetic import ArithmeticStateMachine, evaluate
from tests.minicluster import run_with_new_cluster


def test_evaluate_arithmetic():
    assert evaluate("1 + 2 * 3", {}) == 7
    assert evaluate("a + b", {"a": 1.5, "b": 2.5}) == 4.0
    assert evaluate("sqrt(a**2 + b**2)", {"a": 3, "b": 4}) == 5.0
    assert evaluate("-a", {"a": 2}) == -2


def test_evaluate_rejects_unsafe():
    for bad in ("__import__('os')", "a.b", "lambda: 1", "[1,2]", "'str'",
                "open('/etc/passwd')"):
        with pytest.raises((ValueError, SyntaxError)):
            evaluate(bad, {"a": 1})


def test_evaluate_undefined_variable():
    with pytest.raises(ValueError):
        evaluate("x + 1", {})


def test_evaluate_huge_pow_fails_fast():
    """Operands are coerced to float, so tower exponents overflow instantly
    instead of grinding the event loop on a bignum."""
    import time
    t0 = time.perf_counter()
    with pytest.raises(OverflowError):
        evaluate("10**10**10", {})
    assert time.perf_counter() - t0 < 0.1


def test_arithmetic_cluster_end_to_end():
    """Pythagorean demo from the reference README: a=3, b=4, c=sqrt(a²+b²)."""

    async def _test(cluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for assignment in (b"a = 3", b"b = 4",
                               b"c = sqrt(a**2 + b**2)"):
                reply = await client.io().send(assignment)
                assert reply.success
            read = await client.io().send_read_only(b"c")
            assert float(read.message.content) == 5.0
            reply = await client.io().send(b"d = 1")  # bump commit frontier
            assert reply.success
            await cluster.wait_applied(reply.log_index)
        # replicated: every peer's map agrees
        for div in cluster.divisions():
            assert div.state_machine.variables.get("c") == 5.0

    run_with_new_cluster(3, _test, sm_factory=ArithmeticStateMachine)


def test_arithmetic_rejects_bad_assignment():
    async def _test(cluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            reply = await client.io().send(b"x = nope_undefined + 1")
            assert not reply.success
            # cluster still healthy afterwards
            ok = await client.io().send(b"y = 2")
            assert ok.success

    run_with_new_cluster(3, _test, sm_factory=ArithmeticStateMachine)


def test_arithmetic_snapshot_restart(tmp_path):
    """Variables survive a full-cluster restart via snapshot + log replay."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for i in range(5):
                reply = await client.io().send(f"v{i} = {i} * 10".encode())
                assert reply.success
            await client.snapshot_management().create()
        peer_ids = [d.member_id.peer_id for d in cluster.divisions()]
        for pid in list(peer_ids):
            await cluster.kill_server(pid)
        for pid in peer_ids:
            await cluster.restart_server(pid)
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            read = await client.io().send_read_only(b"v4")
            assert float(read.message.content) == 40.0

    run_with_new_cluster(3, _test, sm_factory=ArithmeticStateMachine,
                         storage_root=str(tmp_path))
