"""Membership change, leadership transfer, and admin API tests.

Models the reference suites RaftReconfigurationBaseTest,
TestTransferLeadership (ratis-test), LeaderElectionTests pause/resume, and
GroupManagement tests — over the simulated transport via the full
RaftClient, like the reference drives them through RaftClient sub-APIs.
"""

import asyncio

import pytest

from minicluster import MiniCluster, fast_properties, run_with_new_cluster
from ratis_tpu.protocol.admin import SetConfigurationMode
from ratis_tpu.protocol.exceptions import RaftException
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


async def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.02)
    raise TimeoutError(f"{msg} not reached within {timeout}s")


def test_client_write_read_failover():
    """RaftClient finds the leader, writes, reads, survives leader kill."""

    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(3):
                r = await client.io().send(b"INCREMENT")
                assert r.success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"3"
            leader = await cluster.wait_for_leader()
            await cluster.kill_server(leader.member_id.peer_id)
            r = await client.io().send(b"INCREMENT")
            assert r.success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"4"

    run_with_new_cluster(3, t)


def test_add_peers():
    """3 -> 5 members via staging + joint consensus (ADD mode)."""

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for _ in range(5):
                assert (await client.io().send(b"INCREMENT")).success

            new_peers = [RaftPeer(RaftPeerId.value_of(f"x{i}"),
                                  address=f"sim:x{i}") for i in range(2)]
            empty_group = RaftGroup.value_of(cluster.group.group_id, [])
            for p in new_peers:
                await cluster.add_new_server(p)
                r = await client.group_management().group_add(empty_group, p)
                assert r.success, r

            r = await client.admin().set_configuration(
                new_peers, mode=SetConfigurationMode.ADD)
            assert r.success, r

            # all five see a stable 5-member conf and replicate writes
            def stable_everywhere():
                divs = cluster.divisions()
                return (len(divs) == 5 and all(
                    d.state.configuration.is_stable()
                    and len(d.state.configuration.conf.peers) == 5
                    for d in divs))
            await _wait(stable_everywhere, msg="5-member conf everywhere")

            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"6"
            await cluster.wait_applied(r.log_index)
            for d in cluster.divisions():
                assert d.state.configuration.is_stable()

    run_with_new_cluster(3, t)


def test_remove_peer_and_survive():
    """5 -> 3: removed peers stop voting; cluster keeps committing."""

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        keep = [p for p in cluster.group.peers
                if p.id == leader.member_id.peer_id][:1]
        keep += [p for p in cluster.group.peers
                 if p.id != leader.member_id.peer_id][:2]
        async with cluster.new_client() as client:
            r = await client.admin().set_configuration(keep)
            assert r.success, r
            await _wait(lambda: leader.state.configuration.is_stable()
                        and len(leader.state.configuration.conf.peers) == 3,
                        msg="3-member conf on leader")
            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"1"
            # removed peers are no longer voting members
            kept_ids = {p.id for p in keep}
            for d in cluster.divisions():
                if d.member_id.peer_id not in kept_ids:
                    assert not d.state.configuration.contains_voting(
                        d.member_id.peer_id)

    run_with_new_cluster(5, t)


def test_remove_leader_steps_down():
    """Removing the leader commits the conf, then the leader steps down and
    a remaining member takes over (reference yield-on-removal)."""

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        remaining = [p for p in cluster.group.peers
                     if p.id != leader.member_id.peer_id]
        async with cluster.new_client() as client:
            r = await client.admin().set_configuration(remaining)
            assert r.success, r
            ids = {p.id for p in remaining}
            await _wait(lambda: any(d.is_leader()
                                    and d.member_id.peer_id in ids
                                    for d in cluster.divisions()),
                        msg="new leader among remaining members")
            assert (await client.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t)


def test_promote_listener_and_demote_voter():
    """Moving members between the voting set and the listener set flips
    Division roles: a demoted voter stops campaigning (listener), a promoted
    listener starts voting."""

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        divs = {d.member_id.peer_id: d for d in cluster.divisions()}
        voters = [p for p in cluster.group.peers]
        listener_div = next(d for d in divs.values() if d.is_listener())
        listener_peer = next(p for p in cluster.group.peers
                             if p.id == listener_div.member_id.peer_id)
        demote_div = next(d for d in divs.values()
                          if not d.is_leader() and not d.is_listener())
        demote_peer = next(p for p in voters
                           if p.id == demote_div.member_id.peer_id)

        new_voting = [p for p in voters if p.id != demote_peer.id
                      and p.id != listener_peer.id] + [listener_peer]
        async with cluster.new_client() as client:
            r = await client.admin().set_configuration(
                new_voting, listeners=[demote_peer])
            assert r.success, r
            await _wait(lambda: listener_div.is_follower()
                        or listener_div.is_leader(),
                        msg="promoted listener becomes voting")
            await _wait(lambda: demote_div.is_listener(),
                        msg="demoted voter becomes listener")
            # promoted member now grants votes / can campaign; cluster works
            assert (await client.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t, num_listeners=1)


def test_compare_and_set_precondition():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            wrong = [RaftPeer(RaftPeerId.value_of("ghost"), address="sim:g")]
            r = await client.admin().set_configuration(
                list(cluster.group.peers)[:2],
                mode=SetConfigurationMode.COMPARE_AND_SET,
                current_peers=wrong)
            assert not r.success
            assert "COMPARE_AND_SET" in str(r.exception)

    run_with_new_cluster(3, t)


def test_reject_concurrent_reconfiguration():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        from ratis_tpu.server import admin as server_admin
        # hold the single-flight slot and verify a second request bounces
        leader.pending_reconf = server_admin.PendingReconf()
        try:
            async with cluster.new_client() as client:
                r = await client.admin().set_configuration(
                    list(cluster.group.peers)[:2], timeout_ms=2000.0)
                assert not r.success
                assert "in progress" in str(r.exception)
        finally:
            leader.pending_reconf = None

    run_with_new_cluster(3, t)


def test_transfer_leadership():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        target = next(p for p in cluster.group.peers
                      if p.id != leader.member_id.peer_id)
        async with cluster.new_client() as client:
            r = await client.admin().transfer_leadership(target.id,
                                                         timeout_ms=5000.0)
            assert r.success, r
            await _wait(lambda: any(d.is_leader()
                                    and d.member_id.peer_id == target.id
                                    for d in cluster.divisions()),
                        msg=f"{target.id} leads")
            # old leader stepped down and writes still work
            assert (await client.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t)


def test_election_pause_resume():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        followers = [d for d in cluster.divisions() if not d.is_leader()]
        paused = followers[0]
        async with cluster.new_client() as client:
            r = await client.leader_election_management().pause(
                paused.member_id.peer_id)
            assert r.success
            await cluster.kill_server(leader.member_id.peer_id)
            new_leader = await cluster.wait_for_leader()
            # the paused follower may vote but must not have become leader
            assert new_leader.member_id.peer_id != paused.member_id.peer_id
            r = await client.leader_election_management().resume(
                paused.member_id.peer_id)
            assert r.success

    run_with_new_cluster(3, t)


def test_group_management_and_info():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            any_server = next(iter(cluster.servers))
            groups = await client.group_management().group_list(any_server)
            assert cluster.group.group_id in groups

            info = await client.group_management().group_info(any_server)
            assert info.group.group_id == cluster.group.group_id
            assert info.term >= 1
            assert {p.id for p in info.group.peers} \
                == {p.id for p in cluster.group.peers}

            # add + remove a second group on one server
            g2 = RaftGroup.value_of(
                RaftGroupId.random_id(),
                [RaftPeer(any_server, address=f"sim:{any_server}")])
            r = await client.group_management().group_add(g2, any_server)
            assert r.success, r
            groups = await client.group_management().group_list(any_server)
            assert g2.group_id in groups
            r = await client.group_management().group_remove(
                g2.group_id, any_server)
            assert r.success, r
            groups = await client.group_management().group_list(any_server)
            assert g2.group_id not in groups

    run_with_new_cluster(3, t)


def test_snapshot_management_create(tmp_path):
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for _ in range(4):
                assert (await client.io().send(b"INCREMENT")).success
            leader = await cluster.wait_for_leader()
            r = await client.snapshot_management().create(
                creation_gap=1, server_id=leader.member_id.peer_id)
            assert r.success, r
            assert r.log_index >= 4
            snap = leader.state_machine.get_latest_snapshot()
            assert snap is not None and snap.index == r.log_index

    run_with_new_cluster(3, t, storage_root=str(tmp_path))
