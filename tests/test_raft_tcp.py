"""Core Raft scenarios over the raw-TCP (netty-analog) transport.

Mirrors the reference per-transport suite instantiation (ratis-test
TestRaftWithNetty): the same behaviors as the gRPC suite, over the
envelope-union TCP backend (ratis_tpu.transport.tcp)."""

import asyncio

import msgpack

from minicluster import MiniCluster, free_port, run_with_new_cluster
from ratis_tpu.models.filestore import FileStoreStateMachine
from ratis_tpu.protocol.admin import SetConfigurationMode
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer

RPC = "NETTY"


def test_tcp_write_read():
    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(5):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"5"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_leader_kill_failover():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(leader.member_id.peer_id)
            await cluster.wait_for_leader()
            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"2"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_restart_rejoins():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        victim = next(d for d in cluster.divisions() if not d.is_leader())
        vid = victim.member_id.peer_id
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(vid)
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.restart_server(vid)
            r = await client.io().send(b"INCREMENT")
            assert r.success
            await cluster.wait_applied(r.log_index)

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_add_peer_and_transfer():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            p = RaftPeer(RaftPeerId.value_of("g1"),
                         address=f"127.0.0.1:{free_port()}")
            await cluster.add_new_server(p)
            empty = RaftGroup.value_of(cluster.group.group_id, [])
            assert (await client.group_management().group_add(empty, p)).success
            r = await client.admin().set_configuration(
                [p], mode=SetConfigurationMode.ADD)
            assert r.success, r
            r = await client.admin().transfer_leadership(p.id,
                                                         timeout_ms=8000.0)
            assert r.success, r
            assert (await client.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_watch_and_stale_read():
    async def t(cluster: MiniCluster):
        from ratis_tpu.protocol.requests import ReplicationLevel
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            r = await client.io().send(b"INCREMENT")
            assert r.success
            w = await client.io().watch(r.log_index, ReplicationLevel.ALL)
            assert w.success
            await cluster.wait_applied(r.log_index)
            follower = next(d for d in cluster.divisions()
                            if not d.is_leader())
            sr = await client.io().send_stale_read(
                b"GET", r.log_index, follower.member_id.peer_id)
            assert sr.success and sr.message.content == b"1"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_datastream_combo():
    """RpcType TCP + DataStream — the reference's netty/netty combination
    (MiniRaftClusterWithRpcTypeNettyAndDataStreamTypeNetty)."""

    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        payload = b"tcp-combo" * 20000
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(msgpack.packb(
                {"op": "stream", "path": "combo.bin"}, use_bin_type=True))
            await out.write_async(payload)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            assert div.state_machine.resolve("combo.bin").read_bytes() \
                == payload

    run_with_new_cluster(3, t, rpc_type=RPC, sm_factory=FileStoreStateMachine)
