"""Core Raft scenarios over the raw-TCP (netty-analog) transport.

Mirrors the reference per-transport suite instantiation (ratis-test
TestRaftWithNetty): the same behaviors as the gRPC suite, over the
envelope-union TCP backend (ratis_tpu.transport.tcp)."""

import asyncio

import msgpack

from minicluster import MiniCluster, free_port, run_with_new_cluster
from ratis_tpu.models.filestore import FileStoreStateMachine
from ratis_tpu.protocol.admin import SetConfigurationMode
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer

RPC = "NETTY"


def test_tcp_write_read():
    async def t(cluster: MiniCluster):
        async with cluster.new_client() as client:
            for _ in range(5):
                assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"5"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_leader_kill_failover():
    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(leader.member_id.peer_id)
            await cluster.wait_for_leader()
            assert (await client.io().send(b"INCREMENT")).success
            r = await client.io().send_read_only(b"GET")
            assert r.message.content == b"2"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_restart_rejoins():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        victim = next(d for d in cluster.divisions() if not d.is_leader())
        vid = victim.member_id.peer_id
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.kill_server(vid)
            assert (await client.io().send(b"INCREMENT")).success
            await cluster.restart_server(vid)
            r = await client.io().send(b"INCREMENT")
            assert r.success
            await cluster.wait_applied(r.log_index)

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_add_peer_and_transfer():
    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            assert (await client.io().send(b"INCREMENT")).success
            p = RaftPeer(RaftPeerId.value_of("g1"),
                         address=f"127.0.0.1:{free_port()}")
            await cluster.add_new_server(p)
            empty = RaftGroup.value_of(cluster.group.group_id, [])
            assert (await client.group_management().group_add(empty, p)).success
            r = await client.admin().set_configuration(
                [p], mode=SetConfigurationMode.ADD)
            assert r.success, r
            r = await client.admin().transfer_leadership(p.id,
                                                         timeout_ms=8000.0)
            assert r.success, r
            assert (await client.io().send(b"INCREMENT")).success

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_watch_and_stale_read():
    async def t(cluster: MiniCluster):
        from ratis_tpu.protocol.requests import ReplicationLevel
        await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            r = await client.io().send(b"INCREMENT")
            assert r.success
            w = await client.io().watch(r.log_index, ReplicationLevel.ALL)
            assert w.success
            await cluster.wait_applied(r.log_index)
            follower = next(d for d in cluster.divisions()
                            if not d.is_leader())
            sr = await client.io().send_stale_read(
                b"GET", r.log_index, follower.member_id.peer_id)
            assert sr.success and sr.message.content == b"1"

    run_with_new_cluster(3, t, rpc_type=RPC)


def test_tcp_datastream_combo():
    """RpcType TCP + DataStream — the reference's netty/netty combination
    (MiniRaftClusterWithRpcTypeNettyAndDataStreamTypeNetty)."""

    async def t(cluster: MiniCluster):
        await cluster.wait_for_leader()
        payload = b"tcp-combo" * 20000
        async with cluster.new_client() as client:
            out = await client.data_stream().stream(msgpack.packb(
                {"op": "stream", "path": "combo.bin"}, use_bin_type=True))
            await out.write_async(payload)
            reply = await out.close_async()
            assert reply.success, reply.exception
            await cluster.wait_applied(reply.log_index)
        for div in cluster.divisions():
            assert div.state_machine.resolve("combo.bin").read_bytes() \
                == payload

    run_with_new_cluster(3, t, rpc_type=RPC, sm_factory=FileStoreStateMachine)


def test_tcp_tls_cluster(tmp_path):
    """TLS-secured raw-TCP transport (NettyConfigKeys.Tls): the cluster
    elects and serves writes over TLS sockets, and a plaintext client
    cannot talk to the TLS endpoint — no transport is plaintext-only."""
    import subprocess

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True)

    from minicluster import fast_properties
    from ratis_tpu.conf.keys import NettyConfigKeys

    p = fast_properties()
    p.set(NettyConfigKeys.Tls.ENABLED_KEY, "true")
    p.set(NettyConfigKeys.Tls.CERT_CHAIN_KEY, str(cert))
    p.set(NettyConfigKeys.Tls.PRIVATE_KEY_KEY, str(key))
    p.set(NettyConfigKeys.Tls.TRUST_ROOT_KEY, str(cert))

    async def t(cluster: MiniCluster):
        leader = await cluster.wait_for_leader()
        async with cluster.new_client() as client:
            for i in range(1, 4):
                r = await client.io().send(b"INCREMENT")
                assert r.success
                assert r.message.content == str(i).encode()

        # a plaintext TCP client must fail against the TLS endpoint
        from ratis_tpu.protocol.exceptions import (RaftException,
                                                   TimeoutIOException)
        from ratis_tpu.protocol.ids import ClientId
        from ratis_tpu.protocol.message import Message
        from ratis_tpu.protocol.requests import (RaftClientRequest,
                                                 write_request_type)
        from ratis_tpu.transport.tcp import TcpClientTransport
        insecure = TcpClientTransport()
        req = RaftClientRequest(ClientId.random_id(),
                                leader.member_id.peer_id,
                                cluster.group.group_id, 99,
                                Message.value_of(b"INCREMENT"),
                                type=write_request_type(), timeout_ms=2000)
        srv = cluster.servers[leader.member_id.peer_id]
        try:
            await insecure.send_request(srv.address, req)
            raise AssertionError("plaintext request succeeded against TLS")
        except (RaftException, TimeoutIOException, ConnectionError, OSError):
            pass
        finally:
            await insecure.close()

    run_with_new_cluster(3, t, rpc_type="NETTY", properties=p)
