"""Metrics registry + server wiring tests (reference ratis-metrics-api
tests and the metric catalog in ratis-docs/src/site/markdown/metrics.md)."""

import asyncio

from ratis_tpu.metrics import (MetricRegistries, MetricRegistryInfo,
                               RatisMetricRegistry, Timekeeper)
from tests.minicluster import run_with_new_cluster


def test_registry_counter_gauge_timer():
    info = MetricRegistryInfo("p0", "ratis", "test", "m")
    reg = RatisMetricRegistry(info)
    c = reg.counter("requests")
    c.inc()
    c.inc(4)
    assert c.count == 5
    reg.gauge("depth", lambda: 42)
    t = reg.timer("latency")
    with t.time():
        pass
    snap = reg.snapshot()
    assert snap["requests"] == 5
    assert snap["depth"] == 42
    assert snap["latency"]["count"] == 1
    assert info.full_name == "ratis.test.p0.m"


def test_timer_percentiles():
    t = Timekeeper()
    for i in range(100):
        t.update(i / 1000.0)
    assert t.count == 100
    assert 0.0 <= t.percentile_s(0.5) <= 0.099
    assert t.percentile_s(0.99) >= t.percentile_s(0.5)
    assert t.snapshot()["max_s"] == 0.099


def test_global_registries_create_remove():
    regs = MetricRegistries.global_registries()
    info = MetricRegistryInfo("x", "ratis", "test", "create_remove")
    reg = regs.create(info)
    assert regs.create(info) is reg  # idempotent
    assert regs.get(info) is reg
    assert regs.remove(info)
    assert regs.get(info) is None
    assert not regs.remove(info)


def test_server_metrics_wiring():
    """A live cluster registers the metrics.md catalog and counts traffic."""

    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        for _ in range(3):
            reply = await cluster.send_write(b"INCREMENT")
            assert reply.success
        reply = await cluster.send_read(b"GET")
        assert reply.success

        m = leader.metrics
        assert m.num_requests.count >= 4
        assert m.write_timer.count >= 3
        assert m.read_timer.count >= 1
        # one election happened and recorded itself
        assert leader.election_metrics.election_count.count >= 1
        assert leader.sm_metrics.applied_count.count >= 3
        snap = m.snapshot()
        assert snap["commitInfos"]["appliedIndex"] >= 3
        # followers timed the replicated appends
        followers = [d for d in cluster.divisions() if d.is_follower()]
        assert any(f.metrics.follower_append_timer.count > 0
                   for f in followers)
        # registry is discoverable globally by full name
        names = [i.full_name
                 for i in MetricRegistries.global_registries()
                 .get_registry_infos()]
        assert any("raft_server" in n for n in names)

    run_with_new_cluster(3, _test)


def test_retry_cache_metrics():
    async def _test(cluster):
        leader = await cluster.wait_for_leader()
        client_id = None
        reply = await cluster.send_write(b"INCREMENT")
        assert reply.success
        misses = leader.metrics.retry_cache_miss.count
        assert misses >= 1

    run_with_new_cluster(3, _test)


def test_prometheus_exposition_and_http():
    """Prometheus text rendering + the /metrics scrape endpoint."""
    from ratis_tpu.metrics.prometheus import MetricsHttpServer, render_text

    async def body(cluster):
        await cluster.wait_for_leader()
        for _ in range(3):
            assert (await cluster.send_write()).success
        text = render_text()
        assert "# TYPE ratis_" in text
        assert 'member="' in text
        assert "_seconds_count{" in text  # timers rendered as summaries
        assert "ratis_server_" in text and "ratis_log_" in text

        server = MetricsHttpServer()
        await server.start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.bound_port)
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await asyncio.wait_for(reader.read(), 10.0)
            writer.close()
            assert b"200 OK" in data.split(b"\r\n", 1)[0]
            assert b"ratis_" in data
            # 404 for other paths
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.bound_port)
            writer.write(b"GET /nope HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await asyncio.wait_for(reader.read(), 10.0)
            writer.close()
            assert b"404" in data.split(b"\r\n", 1)[0]
        finally:
            await server.close()

    run_with_new_cluster(3, body)
