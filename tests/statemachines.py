"""Test state machines (reference SimpleStateMachine4Testing,
ratis-server/src/test/.../statemachine/impl/): records every applied entry,
supports blocking/unblocking apply and start_transaction, and snapshot
round-trips — the teaching SM the per-transport suites drive."""

from __future__ import annotations

import asyncio
import msgpack
from typing import List, Optional

from ratis_tpu.protocol.message import Message
from ratis_tpu.server.statemachine import (BaseStateMachine, SnapshotInfo,
                                           TransactionContext)


class RecordingStateMachine(BaseStateMachine):
    """Records applied payloads in order; query returns the record count,
    ``LAST`` returns the last payload."""

    def __init__(self) -> None:
        super().__init__()
        self.applied: List[bytes] = []
        self._apply_gate = asyncio.Event()
        self._apply_gate.set()
        self._txn_gate = asyncio.Event()
        self._txn_gate.set()

    # ----------------------------------------------------- fault injection

    def block_apply(self) -> None:
        self._apply_gate.clear()

    def unblock_apply(self) -> None:
        self._apply_gate.set()

    def block_start_transaction(self) -> None:
        self._txn_gate.clear()

    def unblock_start_transaction(self) -> None:
        self._txn_gate.set()

    # ------------------------------------------------------------ pipeline

    async def start_transaction(self, request) -> TransactionContext:
        await self._txn_gate.wait()
        return TransactionContext(client_request=request,
                                  log_data=request.message.content)

    async def apply_transaction(self, trx: TransactionContext) -> Message:
        await self._apply_gate.wait()
        e = trx.log_entry
        payload = (e.smlog.log_data if e is not None and e.smlog is not None
                   else (trx.log_data or b""))
        self.applied.append(payload)
        if e is not None:
            self.update_last_applied_term_index(e.term, e.index)
        return Message.value_of(str(len(self.applied)))

    async def query(self, request: Message) -> Message:
        if request.content == b"LAST":
            return Message(self.applied[-1] if self.applied else b"")
        return Message.value_of(str(len(self.applied)))

    async def query_stale(self, request: Message, min_index: int) -> Message:
        return await self.query(request)

    # ------------------------------------------------------------ snapshot

    async def take_snapshot(self) -> int:
        ti = self.get_last_applied_term_index()
        if ti.index < 0 or self._storage.directory is None:
            return -1
        path = self._storage.snapshot_path(ti.term, ti.index)
        path.write_bytes(msgpack.packb(list(self.applied), use_bin_type=True))
        return ti.index

    async def restore_from_snapshot(self,
                                    snapshot: Optional[SnapshotInfo]) -> None:
        if snapshot is None or not snapshot.files:
            return
        import pathlib
        self.applied = msgpack.unpackb(
            pathlib.Path(snapshot.files[0].path).read_bytes(), raw=False)
        self.set_last_applied_term_index(snapshot.term_index)
