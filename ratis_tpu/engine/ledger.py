"""LagLedger: the quorum engine's lag & health ledger.

Host orchestration around :mod:`ratis_tpu.ops.ledger`: every ``sample()``
uploads the engine's authoritative host-mirror arrays (the same
``GroupBatchState`` the tick advances, so this works identically in
scalar-fallback and batched mode), runs the fused pass, and fetches ONE
packed int32 vector.  Consumers — the telemetry sampler's hot-group
accounting, the watchdog's follower-lag and grey-follower detectors, the
``GET /lag`` endpoint, the flight recorder — read numpy views of that
single transfer instead of walking the division fleet in Python.

The ledger also owns the server-wide dense peer table: divisions intern
their peers' ids here (``peer_for``) and write the resulting dense ids
into ``GroupBatchState.peer_index``, which is what lets the kernel
aggregate one peer's health across every group it participates in with a
device-side scatter instead of a host-side group-by.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

import numpy as np

from ratis_tpu.engine.roles import ROLE_LEADER

LOG = logging.getLogger(__name__)

# module-level jit cache: (num_peers, mesh key) -> jitted ledger_pass.
# Shapes (G, P) key the underlying XLA cache as usual; num_peers is the
# only static python arg.  Mesh engines get the group-axis-sharded
# variant (parallel.mesh.sharded_ledger_pass) so the telemetry pass
# honors the same slice layout as the resident tick.
_JITTED: dict = {}


def _jitted_pass(num_peers: int, mesh=None):
    key = (num_peers,
           None if mesh is None else
           (tuple(d.id for d in mesh.devices.flat), mesh.axis_names))
    fn = _JITTED.get(key)
    if fn is None:
        if mesh is not None:
            from ratis_tpu.parallel.mesh import sharded_ledger_pass
            fn = sharded_ledger_pass(mesh, num_peers)
        else:
            import functools

            import jax

            from ratis_tpu.ops import ledger as ops
            fn = jax.jit(functools.partial(ops.ledger_pass,
                                           num_peers=num_peers))
        _JITTED[key] = fn
    return fn


@dataclasses.dataclass
class LedgerSample:
    """One fetched ledger pass: numpy views over the single packed
    transfer plus the host-mirror scalars consumers pair with it."""

    now_ms: int
    capacity: int
    peer_names: list
    commit: np.ndarray        # [G] engine commit at the pass
    pending: np.ndarray       # [G] mirrored leader pending-queue depths
    gen: np.ndarray           # [G] slot allocation generation
    leader_mask: np.ndarray   # [G] bool
    gap: np.ndarray           # [G] commit - applied
    delta: np.ndarray         # [G] commit advance since the last pass
    worst_lag: np.ndarray     # [G] laggiest follower link (-1 = none)
    worst_peer: np.ndarray    # [G] dense peer id of that link (-1 = none)
    hist: np.ndarray          # [num_peers, LAG_BUCKETS] log2 lag counts
    peer_links: np.ndarray    # [num_peers] follower links per peer
    peer_up: np.ndarray       # [num_peers] links acked within up-window
    peer_laggy: np.ndarray    # [num_peers] links >= lag_threshold behind
    peer_active: np.ndarray   # [num_peers] up links of advancing groups
    peer_laggy_active: np.ndarray  # [num_peers] laggy among active
    peer_max_lag: np.ndarray  # [num_peers] worst link lag (-1 = none)
    leading: int
    gap_total: int
    fetch_ms: float


class LagLedger:
    """Engine-attached; always constructed (a ledger nobody samples costs
    nothing).  ``lag_threshold`` / ``up_window_ms`` are plain attributes
    — the server seeds them from ``raft.tpu.lag.*`` and tests/chaos
    harnesses retune them live, exactly like the watchdog thresholds."""

    def __init__(self, engine, prefix: str):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo)
        self.engine = engine
        keys = RaftServerConfigKeys.Lag
        self.lag_threshold = keys.THRESHOLD_DEFAULT
        self.up_window_ms = int(keys.UP_WINDOW_DEFAULT.to_ms())
        self._peer_idx: dict[str, int] = {}
        self.peer_names: list[str] = []
        self._prev_commit = np.full(engine.state.capacity, -1, np.int32)
        self._prev_gen = np.full(engine.state.capacity, -1, np.int32)
        self.last_sample: Optional[LedgerSample] = None
        info = MetricRegistryInfo(prefix=prefix, application="ratis",
                                  component="engine", name="lag_ledger")
        self.registry = MetricRegistries.global_registries().create(info)
        r = self.registry
        self.samples = r.counter("ledgerSamples")
        # upload + fused kernel + the one device->host fetch, wall clock
        self.fetch_timer = r.timer("ledgerFetchCost")
        r.gauge("ledgerPeersTracked", lambda: len(self.peer_names))
        r.gauge("ledgerWorstLag",
                lambda: (int(self.last_sample.worst_lag.max())
                         if self.last_sample is not None else -1))
        r.gauge("ledgerGapTotal",
                lambda: (self.last_sample.gap_total
                         if self.last_sample is not None else 0))

    def unregister(self) -> None:
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self.registry.info)

    # ------------------------------------------------------- peer table

    def peer_for(self, peer_id) -> int:
        """Dense server-wide id for a peer (interned on first sight;
        peers are never forgotten — the table is bounded by the fleet)."""
        name = str(peer_id)
        idx = self._peer_idx.get(name)
        if idx is None:
            idx = len(self.peer_names)
            self._peer_idx[name] = idx
            self.peer_names.append(name)
        return idx

    def _table_width(self) -> int:
        """Static kernel width: next power of two >= the peer count (min
        8), so the table growing by one peer rarely costs a recompile."""
        n = max(8, len(self.peer_names))
        return 1 << (n - 1).bit_length()

    # --------------------------------------------------------- sampling

    def _sync_capacity(self, cap: int) -> None:
        if len(self._prev_commit) != cap:
            pc = np.full(cap, -1, np.int32)
            pg = np.full(cap, -1, np.int32)
            n = min(cap, len(self._prev_commit))
            pc[:n] = self._prev_commit[:n]
            pg[:n] = self._prev_gen[:n]
            self._prev_commit, self._prev_gen = pc, pg

    def sample(self) -> LedgerSample:
        """One fused pass + one fetch.  Same read discipline as the
        watchdog: plain reads of the host mirrors, tolerating concurrent
        mutation (a torn row is one sample of noise, never a tear)."""
        st = self.engine.state
        cap = st.capacity
        self._sync_capacity(cap)
        names = list(self.peer_names)
        width = self._table_width()
        now = self.engine.clock.now_ms()
        commit = st.commit_index.copy()
        pending = st.pending_count.copy()
        gen = st.alloc_gen.copy()
        leader_mask = st.role == ROLE_LEADER
        prev_valid = self._prev_gen == gen
        from ratis_tpu.ops.ledger import LAG_BUCKETS, pack_slices
        t0 = time.perf_counter()
        packed = np.asarray(_jitted_pass(width, self.engine.mesh)(
            st.role, st.match_index, commit, st.applied_index,
            st.conf_cur, st.conf_old, st.self_mask, st.last_ack_ms,
            st.peer_index, self._prev_commit, prev_valid,
            np.int32(now), np.int32(self.lag_threshold),
            np.int32(self.up_window_ms)))
        elapsed_s = time.perf_counter() - t0
        self.fetch_timer.update(elapsed_s)
        self._prev_commit = commit
        self._prev_gen = np.where(leader_mask, gen, -1).astype(np.int32)
        sl = pack_slices(cap, width)
        scalars = packed[sl["scalars"]]
        s = LedgerSample(
            now_ms=now, capacity=cap, peer_names=names,
            commit=commit, pending=pending, gen=gen,
            leader_mask=leader_mask,
            gap=packed[sl["gap"]], delta=packed[sl["delta"]],
            worst_lag=packed[sl["worst_lag"]],
            worst_peer=packed[sl["worst_peer"]],
            hist=packed[sl["hist"]].reshape(width, LAG_BUCKETS),
            peer_links=packed[sl["peer_links"]],
            peer_up=packed[sl["peer_up"]],
            peer_laggy=packed[sl["peer_laggy"]],
            peer_active=packed[sl["peer_active"]],
            peer_laggy_active=packed[sl["peer_laggy_active"]],
            peer_max_lag=packed[sl["peer_max_lag"]],
            leading=int(scalars[0]), gap_total=int(scalars[1]),
            fetch_ms=round(elapsed_s * 1e3, 3))
        self.samples.inc()
        self.last_sample = s
        return s
