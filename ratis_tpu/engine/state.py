"""GroupBatchState: struct-of-arrays consensus state for every hosted group.

This replaces the reference's per-division mutable objects
(FollowerInfo nextIndex/matchIndex/lastRpcTime, LeaderStateImpl's
commit bookkeeping, FollowerState's election deadline) with ``[G, P]`` numpy
arrays managed by a slot free-list, so the whole server's consensus state is
one tensor batch — the multi-Raft fan-in point (RaftServerProxy.ImplMap,
RaftServerProxy.java:89) becomes an array axis.

Times are int32 milliseconds since engine start.  Indices are int32.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

# role codes (device-friendly int8; see engine.roles — shared with kernels)
from ratis_tpu.engine.roles import (ROLE_CANDIDATE, ROLE_FOLLOWER,  # noqa: F401
                                    ROLE_LEADER, ROLE_LISTENER, ROLE_UNUSED)

NO_DEADLINE = np.iinfo(np.int32).max


class GroupBatchState:
    def __init__(self, max_groups: int = 1024, max_peers: int = 8,
                 n_slices: int = 1):
        g, p = max_groups, max_peers
        # Mesh slicing (ratis_tpu.parallel.mesh): the capacity is split into
        # ``n_slices`` contiguous row ranges, one per mesh device, and each
        # group is pinned to a slot WITHIN its owning slice so the device
        # that holds the rows also receives the group's packed events.
        # With one slice (the default) allocation is exactly the old single
        # free list.
        self.n_slices = max(1, int(n_slices))
        if g % self.n_slices:
            raise ValueError(
                f"capacity {g} not divisible by {self.n_slices} slices "
                f"(pad with parallel.mesh.pad_to_mesh)")
        self.slice_rows = g // self.n_slices
        self.capacity = g
        self.max_peers = p
        self.role = np.zeros(g, np.int8)
        self.self_slot = np.zeros(g, np.int8)
        self.self_mask = np.zeros((g, p), bool)
        self.conf_cur = np.zeros((g, p), bool)
        self.conf_old = np.zeros((g, p), bool)
        self.priority = np.zeros((g, p), np.int32)
        self.self_priority = np.zeros(g, np.int32)
        self.match_index = np.full((g, p), -1, np.int32)
        self.next_index = np.zeros((g, p), np.int32)
        self.flush_index = np.full(g, -1, np.int32)
        self.commit_index = np.full(g, -1, np.int32)
        self.first_leader_index = np.zeros(g, np.int32)
        self.last_ack_ms = np.zeros((g, p), np.int32)
        self.election_deadline_ms = np.full(g, NO_DEADLINE, np.int32)
        # Candidate vote-round state (batched elections, SURVEY §3.3 HOT
        # LOOP #2): grant/reject masks + round deadline; NO_DEADLINE means
        # no round in flight for the slot.  Tallied for every candidate in
        # one ops.quorum.tally_votes dispatch per engine tick, replacing
        # the reference's per-division waitForResults loop
        # (LeaderElection.java:498-592).
        self.vote_grants = np.zeros((g, p), bool)
        self.vote_rejects = np.zeros((g, p), bool)
        self.vote_deadline_ms = np.full(g, NO_DEADLINE, np.int32)
        # Lag-ledger inputs (engine/ledger.py): last applied index and
        # leader pending-queue depth mirrored from the division, the
        # server-wide dense peer id per [slot, column] (-1 = unmapped),
        # and a per-slot allocation generation so delta baselines from a
        # released slot never bleed into its next tenant.
        self.applied_index = np.full(g, -1, np.int32)
        self.pending_count = np.zeros(g, np.int32)
        self.peer_index = np.full((g, p), -1, np.int32)
        self.alloc_gen = np.zeros(g, np.int32)
        # One free list per slice over its contiguous row range (popped
        # low-to-high, matching the historical single-list order).
        self._free: list[list[int]] = [
            list(range((i + 1) * self.slice_rows - 1,
                       i * self.slice_rows - 1, -1))
            for i in range(self.n_slices)]
        self.active: set[int] = set()
        # Slots whose host-side state changed since the last engine tick.
        # The device-resident tick uploads ONLY these rows (plus packed ack
        # events); the scalar tick re-runs commit math only for these.
        self.dirty: set[int] = set()

    def mark_dirty(self, slot: int) -> None:
        if slot >= 0:
            self.dirty.add(slot)

    def slice_of_slot(self, slot: int) -> int:
        return slot // self.slice_rows

    def allocate(self, slice_idx: int = -1) -> int:
        """Take a free slot.  ``slice_idx`` pins the slot to one mesh
        slice's row range; -1 fills the lowest slice with room first —
        sequential slot order 0,1,2,..., bit-identical to the unsliced
        engine's historical allocation (mesh-vs-single identity tests
        rely on this; production divisions always pass an explicit
        slice)."""
        if slice_idx < 0:
            slice_idx = next(
                (i for i in range(self.n_slices) if self._free[i]), 0)
        free = self._free[slice_idx]
        if not free:
            if self.n_slices == 1:
                self._grow()
            else:
                # Sliced capacity is FIXED at bring-up: the slot->slice map
                # is positional, so growing would re-home every row.  The
                # server auto-pads capacity to the mesh at construction;
                # running out means the deployment is undersized.
                raise RuntimeError(
                    f"slice {slice_idx} out of group slots "
                    f"({self.slice_rows} rows/slice, {self.n_slices} "
                    f"slices); raise raft.tpu.engine.max-groups")
        slot = free.pop()
        self.active.add(slot)
        self.alloc_gen[slot] += 1
        self.mark_dirty(slot)
        return slot

    def release(self, slot: int) -> None:
        self.active.discard(slot)
        self.role[slot] = ROLE_UNUSED
        self.conf_cur[slot] = False
        self.conf_old[slot] = False
        self.self_mask[slot] = False
        self.match_index[slot] = -1
        self.flush_index[slot] = -1
        self.commit_index[slot] = -1
        self.election_deadline_ms[slot] = NO_DEADLINE
        self.vote_grants[slot] = False
        self.vote_rejects[slot] = False
        self.vote_deadline_ms[slot] = NO_DEADLINE
        self.applied_index[slot] = -1
        self.pending_count[slot] = 0
        self.peer_index[slot] = -1
        self._free[self.slice_of_slot(slot)].append(slot)
        self.mark_dirty(slot)

    def _grow(self) -> None:
        """Double capacity (pad arrays); jit caches per shape, and doubling
        keeps the number of distinct compiled shapes logarithmic."""
        old = self.capacity
        new = old * 2
        for name in ("role", "self_slot", "flush_index", "commit_index",
                     "first_leader_index", "election_deadline_ms",
                     "self_priority", "vote_deadline_ms", "applied_index",
                     "pending_count", "alloc_gen"):
            a = getattr(self, name)
            b = np.zeros(new, a.dtype)
            b[:old] = a
            if name in ("flush_index", "commit_index", "applied_index"):
                b[old:] = -1
            if name in ("election_deadline_ms", "vote_deadline_ms"):
                b[old:] = NO_DEADLINE
            setattr(self, name, b)
        for name in ("self_mask", "conf_cur", "conf_old", "priority",
                     "match_index", "next_index", "last_ack_ms",
                     "vote_grants", "vote_rejects", "peer_index"):
            a = getattr(self, name)
            b = np.zeros((new, self.max_peers), a.dtype)
            b[:old] = a
            if name in ("match_index", "peer_index"):
                b[old:] = -1
            setattr(self, name, b)
        self._free[0].extend(range(new - 1, old - 1, -1))
        self.capacity = new
        self.slice_rows = new

    # -- per-group setters used by divisions --------------------------------

    def set_conf(self, slot: int, self_slot: int, cur_mask, old_mask,
                 priorities, self_priority: int) -> None:
        self.self_slot[slot] = self_slot
        self.self_mask[slot] = False
        self.self_mask[slot, self_slot] = True
        self.conf_cur[slot] = cur_mask
        self.conf_old[slot] = old_mask
        self.priority[slot] = priorities
        self.self_priority[slot] = self_priority
        self.mark_dirty(slot)
