"""QuorumEngine: one tick loop advances every group's consensus math.

This is the replacement for the reference's thread-per-division daemons
(FollowerState timeout thread FollowerState.java:64, LeaderStateImpl
EventProcessor LeaderStateImpl.java:108-190): a single asyncio task per
server drains packed ack events and, in one pass over the group batch,

- advances leader commit indexes (ops.quorum.update_commit),
- fires follower election timeouts (ops.quorum.election_timeout),
- detects stale leadership (ops.quorum.check_leadership),

then invokes per-division callbacks for the few groups whose state changed.
Below ``scalar_fallback_threshold`` active groups the same math runs through
:mod:`ratis_tpu.ops.reference` (no device dispatch); above it, the jitted
kernels take over (the 10k-group path).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import zlib
from typing import Callable, Optional, Protocol

import numpy as np

from ratis_tpu.engine.state import (GroupBatchState, NO_DEADLINE,
                                    ROLE_CANDIDATE, ROLE_FOLLOWER,
                                    ROLE_LEADER, ROLE_LISTENER, ROLE_UNUSED)
from ratis_tpu.metrics.hops import hop
from ratis_tpu.ops import reference as ref
from ratis_tpu.trace.tracer import STAGE_ENGINE, TRACER

# keep in sync with ops.quorum.PACK_SENTINEL (not imported here: engine
# import must not eagerly pull in jax)
_PACK_SENTINEL = -(2 ** 31)

LOG = logging.getLogger(__name__)

_SHARED_STEP = None
_SHARED_FAST_STEP = None
_SHARED_TALLY = None
# (device ids, axis names) -> (refresh, fast) sharded jitted steps
_SHARDED_STEPS: dict = {}


def _shared_tally():
    """Process-wide jitted vote tally: ALL candidate rounds on a server
    tallied in one ops.quorum.tally_votes dispatch per tick."""
    global _SHARED_TALLY
    if _SHARED_TALLY is None:
        import jax

        from ratis_tpu.ops import quorum as q
        _SHARED_TALLY = jax.jit(q.tally_votes)
    return _SHARED_TALLY


def _shared_step():
    """Process-wide jitted resident step (see QuorumEngine._kernels)."""
    global _SHARED_STEP
    if _SHARED_STEP is None:
        import jax

        from ratis_tpu.ops import quorum as q
        # Donating the DeviceState keeps the [G, P] batch resident on
        # device: each tick consumes the old buffers and returns new ones
        # without a host round-trip.
        _SHARED_STEP = jax.jit(q.engine_step_resident, donate_argnums=(0,))
    return _SHARED_STEP


def _shared_fast_step():
    """Zero-dirty steady-state variant: packed events in, packed outs back."""
    global _SHARED_FAST_STEP
    if _SHARED_FAST_STEP is None:
        import jax

        from ratis_tpu.ops import quorum as q
        _SHARED_FAST_STEP = jax.jit(q.engine_step_resident_fast,
                                    donate_argnums=(0,))
    return _SHARED_FAST_STEP


# Why the sweep gate let a batched dispatch through — the dispatch count at
# scale is THE batched-mode cost driver, so its composition is a first-class
# labeled counter set instead of a guess.
DISPATCH_REASONS = ("upload", "commit", "dirty", "votes", "sweep", "backlog")


class EngineMetrics:
    """The engine's observability surface: a real ``RatisMetricRegistry``
    ("engine" component) instead of the plain dict of earlier rounds.

    Carries what the dict could not express: a per-sweep dispatch-latency
    timer (host -> XLA -> host wall per batched dispatch), batch
    lane-occupancy gauges (live rows vs padded capacity per packed tensor
    — the "are we actually batching" TPU signal), an ack-batch size
    histogram, and the per-reason dispatch counters as labeled counters.
    The old dict keys stay readable through :class:`_EngineMetricsView`
    (``engine.metrics``) for bench/test compatibility."""

    def __init__(self, engine: "QuorumEngine", prefix: str) -> None:
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo, labeled)
        info = MetricRegistryInfo(prefix=prefix, application="ratis",
                                  component="engine", name="quorum_engine")
        self.registry = MetricRegistries.global_registries().create(info)
        r = self.registry
        # the historical dict keys, now real counters (names preserved so
        # the scrape and the dict view agree)
        self.ticks = r.counter("ticks")
        self.acks = r.counter("acks")
        self.commit_advances = r.counter("commit_advances")
        self.batched_dispatches = r.counter("batched_dispatches")
        self.refresh_rows = r.counter("refresh_rows")
        self.fast_ticks = r.counter("fast_ticks")
        self.refresh_ticks = r.counter("refresh_ticks")
        self.idle_skips = r.counter("idle_skips")
        self.reasons = {reason: r.counter(labeled("dispatches",
                                                  reason=reason))
                        for reason in DISPATCH_REASONS}
        # host->XLA->host wall clock of one batched dispatch (upload +
        # kernel + output download), and the packed ack batch it carried
        self.dispatch_timer = r.timer("dispatchLatency")
        self.ack_batch = r.histogram("ackBatchSize")
        # Lane occupancy: live rows vs padded lane capacity for the two
        # packed tensors the kernel consumes — the [G, P] group batch and
        # the [7, E] event pack of the LAST dispatch.  Occupancy near 0
        # means the server pays full-width dispatches for a few live lanes.
        r.gauge("laneGroupsLive", lambda: len(engine.state.active))
        r.gauge("laneGroupsCapacity", lambda: engine.state.capacity)
        r.gauge("laneOccupancyGroups",
                lambda: len(engine.state.active) / engine.state.capacity)
        r.gauge("laneEventsLastDispatch", lambda: engine._last_event_rows)
        r.gauge("laneEventCapacityLastDispatch",
                lambda: engine._last_event_cap)
        r.gauge("laneOccupancyEvents",
                lambda: (engine._last_event_rows / engine._last_event_cap
                         if engine._last_event_cap else 0.0))

    def unregister(self) -> None:
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self.registry.info)


class _EngineMetricsView:
    """Dict-shaped read view over :class:`EngineMetrics` — the
    ``engine.metrics`` the bench and tests already consume.  Supports
    ``m["ticks"]``, ``m.get``, iteration, and ``m[k] = v`` (tests reset
    counters through it); the per-reason dispatch counters appear under
    their historical ``dispatch_<reason>`` keys only once non-zero, like
    the dict they replace."""

    _PLAIN = ("ticks", "acks", "commit_advances", "batched_dispatches",
              "refresh_rows", "fast_ticks", "refresh_ticks", "idle_skips")

    def __init__(self, em: EngineMetrics) -> None:
        self._em = em

    def _counter(self, key: str):
        if key in self._PLAIN:
            return getattr(self._em, key)
        if key.startswith("dispatch_"):
            return self._em.reasons.get(key[len("dispatch_"):])
        return None

    def __getitem__(self, key: str) -> int:
        c = self._counter(key)
        if c is None:
            raise KeyError(key)
        return c.count

    def __setitem__(self, key: str, value: int) -> None:
        c = self._counter(key)
        if c is None:
            raise KeyError(key)
        c._value = int(value)

    def get(self, key: str, default=None):
        c = self._counter(key)
        return default if c is None else c.count

    def __contains__(self, key: str) -> bool:
        return self._counter(key) is not None

    def keys(self) -> list[str]:
        return [*self._PLAIN,
                *(f"dispatch_{r}" for r, c in self._em.reasons.items()
                  if c.count)]

    def __iter__(self):
        return iter(self.keys())

    def items(self):
        return [(k, self[k]) for k in self.keys()]

    def __len__(self) -> int:
        return len(self.keys())

    def __repr__(self) -> str:
        return repr(dict(self.items()))


class EngineListener(Protocol):
    """What a division implements to be driven by the engine."""

    async def on_election_timeout(self) -> None: ...

    async def on_commit_advance(self, new_commit: int) -> None: ...

    async def on_leadership_stale(self) -> None: ...


class Clock:
    """Millisecond clock relative to a movable epoch (int32-friendly).

    The epoch advances when the engine rebases (see
    QuorumEngine._maybe_rebase_epoch), keeping now_ms well inside int32 for
    arbitrarily long uptimes."""

    def __init__(self):
        self._t0 = time.monotonic()

    def now_ms(self) -> int:
        return int((time.monotonic() - self._t0) * 1000)

    def advance_epoch(self, delta_ms: int) -> None:
        self._t0 += delta_ms / 1000.0


class QuorumEngine:
    # Which engine (if any) owns the process-wide jax profiler session:
    # jax.profiler.start_trace is a singleton, and co-hosted servers each
    # build an engine, so only the first profiled engine starts the trace.
    _profiling_owner = None

    def __init__(self, max_groups: int = 1024, max_peers: int = 8,
                 tick_interval_s: float = 0.002,
                 scalar_fallback_threshold: int = 16,
                 leadership_timeout_ms: int = 300,
                 use_device: bool = False,
                 mesh=None, profile_dir: Optional[str] = None,
                 name: str = ""):
        # Optional jax.sharding.Mesh: the PRODUCTION resident tick
        # (engine_step_resident / _fast_sliced, donated DeviceState) runs
        # sharded over the group axis — each device owns one contiguous
        # SLICE of G/n rows, packed events are routed per slice ([7, S, E],
        # slice axis sharded) so a device only scans events for rows it
        # holds, and the row-local quorum math keeps the step
        # collective-free (ratis_tpu.parallel.mesh).
        self.mesh = mesh
        n_slices = 1
        if mesh is not None:
            n_slices = int(mesh.devices.size)
            # auto-pad: mesh size no longer needs to divide max-groups —
            # padded rows stay ROLE_UNUSED and cost nothing
            from ratis_tpu.parallel.mesh import pad_to_mesh
            max_groups = pad_to_mesh(max_groups, n_slices)
        # SURVEY §5 tracing hook: when set, the engine runs inside a
        # jax.profiler trace (XLA device ops + named tick steps) written to
        # this directory for TensorBoard/xprof — raft.tpu.engine.profile-dir.
        self.profile_dir = profile_dir
        self.state = GroupBatchState(max_groups, max_peers,
                                     n_slices=n_slices)
        self.clock = Clock()
        self.tick_interval_s = tick_interval_s
        self.scalar_fallback_threshold = scalar_fallback_threshold
        self.leadership_timeout_ms = leadership_timeout_ms
        self.use_device = use_device
        self._listeners: dict[int, EngineListener] = {}
        self._ack_ring: list[tuple[int, int, int, int]] = []  # (slot, peer, match, t)
        self._vote_ring: list[tuple[int, int, bool]] = []  # (slot, peer, granted)
        self._vote_rounds: dict[int, asyncio.Future] = {}
        # slot -> [flush | SENTINEL, deadline | SENTINEL]: high-rate scalar
        # mutations packed into the fast tick instead of dirty-row refreshes
        self._slot_updates: dict[int, list] = {}
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._running = False
        # Device-resident copy of the batch state (ops.quorum.DeviceState);
        # None until the first batched tick, invalidated on rebase/regrow.
        self._dev = None
        # Next time the scalar path sweeps leaders for staleness; the batched
        # kernel checks every tick for free, the scalar path throttles the
        # O(leaders) python sweep to timeout/4.
        self._next_staleness_ms = 0
        # Batched-path dispatch gate: when a tick has NO events to ship and
        # the next follower deadline / staleness sweep is not due yet, the
        # device dispatch is skipped entirely (the dominant idle cost at
        # high group counts is the fixed per-dispatch overhead, not the
        # kernel).  0 forces the first dispatch.
        self._next_sweep_ms = 0
        # A listener without the sync commit hook has an undelivered commit
        # riding the tick path; the sweep gate must not skip while set.
        self._tick_commit_pending = False
        # largest compiled event bucket (lowered by prewarm): dispatch
        # chunks never exceed it, so no fresh jit shape mid-run
        self._event_bucket_cap = self._MAX_EVENT_BUCKET
        # last-dispatch packed-event lane fill (read by the occupancy
        # gauges; see EngineMetrics)
        self._last_event_rows = 0
        self._last_event_cap = 0
        # monotonic time of the last completed tick (engine freshness for
        # the /health endpoint); None until the loop runs once
        self.last_tick_monotonic: Optional[float] = None
        # Real metric registry ("engine" component); engine.metrics keeps
        # the historical dict read surface over it.
        self._m = EngineMetrics(
            self, name or f"engine-{id(self):x}")
        self.metrics = _EngineMetricsView(self._m)
        # Lag & health ledger over the same host mirrors (one fused pass +
        # one fetch per telemetry tick; engine/ledger.py).  Lazy import:
        # the engine module must stay importable without jax.
        from ratis_tpu.engine.ledger import LagLedger
        self.ledger = LagLedger(self, name or f"engine-{id(self):x}")
        # Cross-shard intake safety (raft.tpu.server.loop-shards): divisions
        # pinned to worker event loops call the intake methods from their
        # own threads while the tick task reads/swaps the same rings and
        # mirror on the engine's home loop.  An RLock (re-entrant: an
        # inline-commit callback may re-enter intake synchronously)
        # serializes the mutation windows; the home loop lets off-loop
        # intake wake the tick via call_soon_threadsafe.  With one loop
        # (the default) every acquisition is uncontended.
        self._lock = threading.RLock()
        # off-loop wake already scheduled and not yet fired (guarded by
        # the intake lock): dedupes call_soon_threadsafe notify storms
        self._wake_pending = False
        self._home_loop: Optional[asyncio.AbstractEventLoop] = None
        # slot -> loop the listener's division runs on (for cross-shard
        # callback dispatch); absent/same-loop listeners take the direct
        # await path, identical to the unsharded runtime.
        self._listener_loops: dict[int, asyncio.AbstractEventLoop] = {}

    # -- registration --------------------------------------------------------

    def slice_of(self, key: bytes) -> int:
        """Owning mesh slice for a group id: the same crc32 pin as
        LoopShardPool.shard_of, taken modulo the slice count — so whenever
        the mesh size divides loop-shards, one slice maps to a whole
        shard-set and intake for a slice's groups arrives from a stable
        subset of loops."""
        return zlib.crc32(key) % self.state.n_slices

    def attach(self, listener: EngineListener,
               slice_idx: int = -1) -> int:
        """Register a listener; ``slice_idx`` pins the group's slot inside
        one mesh slice's row range (divisions pass slice_of(group id);
        -1 = lowest slice with room, the non-mesh default)."""
        with self._lock:
            slot = self.state.allocate(slice_idx)
            self._listeners[slot] = listener
        try:
            self._listener_loops[slot] = asyncio.get_running_loop()
        except RuntimeError:
            pass  # attached outside a loop (tests): direct-await path
        return slot

    def detach(self, slot: int) -> None:
        self.end_vote_round(slot)
        with self._lock:
            self._listeners.pop(slot, None)
            self._listener_loops.pop(slot, None)
            self.state.release(slot)

    # -- event intake (transport/appender threads call these) ---------------

    def on_ack(self, slot: int, peer_slot: int, match_index: int) -> None:
        """Record a follower ack: update the host mirror eagerly, try the
        O(P) commit advance INLINE, and queue the packed event for the
        device (which applies the same scatter-max at the next tick, so
        host and device stay in agreement).

        The inline commit is the latency-critical redesign: commits used to
        advance only inside the engine tick task, and under load that task
        is one of thousands competing for the event loop — profiling at
        1024 groups measured it scheduled ~50x/s, putting 100ms+ of pure
        queueing delay into EVERY commit (and the client pipelines that
        wait on them).  The per-ack math is a [P]-element majority-min
        (P <= 8); the device keeps the work that actually batches — the
        O(G) timeout/staleness/lease sweeps."""
        with self._lock:
            self._on_ack_locked(slot, peer_slot, match_index,
                                self.clock.now_ms())

    def on_ack_batch(self, rows) -> None:
        """Packed ack intake: ``rows`` is a sequence of
        ``(slot, peer_slot, match_index)`` rows (list of tuples or an
        ``[N, 3]`` int array).  Applies exactly the per-row operations of
        :meth:`on_ack` — mirror scatter-max, ring append, inline commit —
        in row order, under ONE intake-lock acquisition, so a follower
        reply frame carrying N co-hosted groups' acks costs one lock
        round-trip and (via the wake dedupe in :meth:`_wake_set`) at most
        one tick wake instead of N.  Commit advancement is bit-identical
        to feeding the same rows through scalar ``on_ack`` one by one
        (asserted in tests/test_loop_shards.py)."""
        if rows is None or len(rows) == 0:
            return
        if isinstance(rows, np.ndarray):
            rows = rows.tolist()
        with self._lock:
            now = self.clock.now_ms()
            for slot, peer_slot, match_index in rows:
                self._on_ack_locked(int(slot), int(peer_slot),
                                    int(match_index), now)

    def _on_ack_locked(self, slot: int, peer_slot: int, match_index: int,
                       now: int) -> None:
        s = self.state
        if s.match_index[slot, peer_slot] < match_index:
            s.match_index[slot, peer_slot] = match_index
        if s.last_ack_ms[slot, peer_slot] < now:
            s.last_ack_ms[slot, peer_slot] = now
        self._ack_ring.append((slot, peer_slot, match_index, now))
        self._try_commit_inline(slot, match_index)

    def _try_commit_inline(self, slot: int, hint: int) -> None:
        """Advance ``slot``'s commit from the host mirror if possible and
        deliver the (synchronous) listener callback immediately.  Listeners
        without the sync hook keep the tick-driven path: their mirror is
        left untouched so the device/tick dispatch still fires for them."""
        s = self.state
        if s.role[slot] != ROLE_LEADER:
            return
        if hint <= int(s.commit_index[slot]):
            return  # the triggering value cannot raise the majority-min
        listener = self._listeners.get(slot)
        cb = getattr(listener, "on_commit_advance_now", None)
        if cb is None:
            # tick path owns this listener's commits: force the next tick
            # through the dispatch (the sweep gate must not skip it)
            self._tick_commit_pending = True
            self._wake_set()
            return
        new_commit, did = ref.update_commit(
            s.match_index[slot].tolist(), int(s.self_slot[slot]),
            int(s.flush_index[slot]), s.conf_cur[slot].tolist(),
            s.conf_old[slot].tolist(), int(s.commit_index[slot]),
            int(s.first_leader_index[slot]), True)
        if did:
            s.commit_index[slot] = new_commit
            self._m.commit_advances.inc()
            cb(new_commit)

    def on_flush(self, slot: int, flush_index: int) -> None:
        """A log's flush frontier advanced: update the mirror and queue a
        packed slot update for the fast tick path (these fire on every
        append — routing them through mark_dirty would force the dirty-row
        refresh on every tick)."""
        with self._lock:
            self._on_flush_locked(slot, flush_index)

    def on_flush_batch(self, rows) -> None:
        """Packed flush intake (envelope sweep intake): ``rows`` is a
        sequence of ``(slot, flush_index)`` rows — one multi-group append
        frame's flush advances.  Applies exactly the per-row operations of
        :meth:`on_flush`, in row order, under ONE intake-lock acquisition,
        so a frame carrying N co-hosted groups' appends costs one lock
        round-trip (and, via the wake dedupe, at most one tick wake)
        instead of N."""
        if not rows:
            return
        with self._lock:
            for slot, flush_index in rows:
                self._on_flush_locked(int(slot), int(flush_index))

    def _on_flush_locked(self, slot: int, flush_index: int) -> None:
        s = self.state
        if flush_index < int(s.flush_index[slot]):
            # regression (follower truncate): rare — take the refresh
            # path, the device-side scatter-max would ignore a lower
            # value
            s.flush_index[slot] = flush_index
            s.mark_dirty(slot)
            self._wake_set()
            return
        s.flush_index[slot] = flush_index
        u = self._slot_updates.get(slot)
        if u is None:
            self._slot_updates[slot] = [flush_index, _PACK_SENTINEL]
        elif u[0] == _PACK_SENTINEL or flush_index > u[0]:
            u[0] = flush_index
        # A leader's own flush counts toward quorum: try the commit
        # inline (single-peer groups commit on flush alone).
        self._try_commit_inline(slot, flush_index)

    def on_deadline(self, slot: int, deadline_ms: int) -> None:
        """(Re-)arm a follower election deadline; same packed-update route.
        No wake: a postponed deadline needs no immediate tick."""
        with self._lock:
            s = self.state
            s.election_deadline_ms[slot] = deadline_ms
            if deadline_ms < self._next_sweep_ms:
                self._next_sweep_ms = deadline_ms  # earlier than planned
            u = self._slot_updates.get(slot)
            if u is None:
                self._slot_updates[slot] = [_PACK_SENTINEL, deadline_ms]
            else:
                u[1] = deadline_ms

    # -- cross-loop plumbing (loop sharding) ---------------------------------

    def _wake_set(self) -> None:
        """Wake the tick loop from any thread: direct on the home loop,
        call_soon_threadsafe from a shard loop (asyncio.Event.set is not
        thread-safe).  Off-loop wakes are DEDUPED under the intake lock:
        a burst of cross-shard acks/flushes schedules ONE home-loop
        callback, not one per caller — profiling showed notify storms
        queueing thousands of redundant call_soon_threadsafe callbacks
        behind the very tick they all wanted to wake."""
        home = self._home_loop
        if home is not None:
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not home:
                with self._lock:
                    if self._wake_pending:
                        return  # a scheduled wake already covers this burst
                    self._wake_pending = True
                try:
                    hop("engine_wake")
                    home.call_soon_threadsafe(self._wake_fire)
                except RuntimeError:
                    # home loop closing: nothing left to wake
                    with self._lock:
                        self._wake_pending = False
                return
        if not self._wake.is_set():
            hop("engine_wake")
        self._wake.set()

    def _wake_fire(self) -> None:
        """Home-loop half of the deduped off-loop wake: clear the pending
        latch FIRST (a wake requested after this point must schedule a
        fresh callback — the event below may be consumed immediately),
        then set the event."""
        with self._lock:
            self._wake_pending = False
        self._wake.set()

    @staticmethod
    def _resolve_future(fut: asyncio.Future, result: str) -> None:
        """set_result on the future's OWN loop (vote futures are created on
        the division's shard loop; the tick resolves them from the home
        loop)."""
        floop = fut.get_loop()
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if floop is running:
            if not fut.done():
                fut.set_result(result)
            return

        def _set() -> None:
            if not fut.done():
                fut.set_result(result)

        try:
            floop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # owner loop closed: the round's division is gone

    @staticmethod
    def _cancel_future(fut: asyncio.Future) -> None:
        floop = fut.get_loop()
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if floop is running:
            if not fut.done():
                fut.cancel()
            return

        def _cancel() -> None:
            if not fut.done():
                fut.cancel()

        try:
            floop.call_soon_threadsafe(_cancel)
        except RuntimeError:
            pass

    # -- batched vote rounds (SURVEY §3.3 HOT LOOP #2) -----------------------

    @property
    def tally_batched(self) -> bool:
        """Whether candidate vote rounds run through the engine's batched
        tally (the per-division scalar loop stays below the threshold —
        same policy as the commit/timeout math)."""
        return (self.use_device
                or len(self.state.active) >= self.scalar_fallback_threshold)

    def begin_vote_round(self, slot: int, deadline_ms: int) -> asyncio.Future:
        """Open a vote round for ``slot``: reset the grant/reject masks
        (self-grant pre-set), arm the round deadline, and return a future
        the tick resolves with "PASSED" / "REJECTED" / "TIMEOUT".  The
        conf masks and priorities were already synced via set_conf."""
        with self._lock:
            s = self.state
            s.vote_grants[slot] = False
            s.vote_rejects[slot] = False
            s.vote_grants[slot, s.self_slot[slot]] = True
            s.vote_deadline_ms[slot] = deadline_ms
            old = self._vote_rounds.pop(slot, None)
            if old is not None:
                self._cancel_future(old)
            fut = asyncio.get_running_loop().create_future()
            self._vote_rounds[slot] = fut
        self._wake_set()
        return fut

    def on_vote_reply(self, slot: int, peer_slot: int, granted: bool) -> None:
        with self._lock:
            if slot not in self._vote_rounds:
                return
            self._vote_ring.append((slot, peer_slot, granted))
        self._wake_set()

    def end_vote_round(self, slot: int) -> None:
        """Abandon a round (candidate stopped / stepped down / special
        reply handled inline): cancel its future and disarm the deadline."""
        with self._lock:
            self.state.vote_deadline_ms[slot] = NO_DEADLINE
            fut = self._vote_rounds.pop(slot, None)
        if fut is not None:
            self._cancel_future(fut)

    def expire_vote_round(self, slot: int) -> None:
        """Every peer has replied or failed: pull the round deadline to now
        so the next tick resolves it through the timeout-path tally — the
        outstanding==0 early exit of the reference's waitForResults (a
        majority gated only on a SILENT higher-priority peer must not wait
        out the full randomized deadline once that peer's RPC has failed)."""
        with self._lock:
            if slot not in self._vote_rounds:
                return
            s = self.state
            now = np.int32(self.clock.now_ms())
            if s.vote_deadline_ms[slot] > now:
                s.vote_deadline_ms[slot] = now
        self._wake_set()

    def _vote_pass(self, now: int) -> list[tuple[asyncio.Future, str]]:
        """Apply queued vote replies and tally EVERY open round in one
        jitted dispatch; returns (future, result) pairs to resolve."""
        s = self.state
        events, self._vote_ring = self._vote_ring, []
        for slot, peer, granted in events:
            if slot not in self._vote_rounds:
                continue
            if s.vote_grants[slot, peer] or s.vote_rejects[slot, peer]:
                continue  # first reply wins (waitForResults putIfAbsent)
            if granted:
                s.vote_grants[slot, peer] = True
            else:
                s.vote_rejects[slot, peer] = True
        if not self._vote_rounds:
            return []
        import jax.numpy as jnp
        res = _shared_tally()(
            jnp.asarray(s.vote_grants), jnp.asarray(s.vote_rejects),
            jnp.asarray(s.conf_cur), jnp.asarray(s.conf_old),
            jnp.asarray(s.priority), jnp.asarray(s.self_priority))
        passed = np.asarray(res.passed)
        passed_on_timeout = np.asarray(res.passed_on_timeout)
        rejected = np.asarray(res.rejected)
        out: list[tuple[asyncio.Future, str]] = []
        for slot, fut in list(self._vote_rounds.items()):
            if fut.done():
                self._vote_rounds.pop(slot)
                continue
            if rejected[slot]:
                result = "REJECTED"
            elif passed[slot]:
                result = "PASSED"
            elif now >= s.vote_deadline_ms[slot]:
                result = ("PASSED" if passed_on_timeout[slot] else "TIMEOUT")
            else:
                continue  # round still open
            self._vote_rounds.pop(slot)
            s.vote_deadline_ms[slot] = NO_DEADLINE
            out.append((fut, result))
        return out

    def regress_match(self, slot: int, peer_slot: int, match_index: int) -> None:
        """A follower provably lost acked entries (volatile-log restart):
        lower the mirror AND clamp any acks for this (group, peer) still
        queued in the ring — otherwise the next tick's scatter-max replays a
        pre-restart ack and silently restores the lost match."""
        with self._lock:
            self._ack_ring = [
                (g, p,
                 min(m, match_index) if (g, p) == (slot, peer_slot) else m, t)
                for g, p, m, t in self._ack_ring]
            self.state.match_index[slot, peer_slot] = match_index
            self.state.mark_dirty(slot)

    def notify(self) -> None:
        """Wake the tick loop early (e.g. flush index advanced)."""
        self._wake_set()

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._home_loop = asyncio.get_running_loop()
        if self.profile_dir and QuorumEngine._profiling_owner is None:
            import jax
            try:
                jax.profiler.start_trace(self.profile_dir)
                QuorumEngine._profiling_owner = self
                LOG.info("engine profiling -> %s", self.profile_dir)
            except Exception:
                LOG.exception("could not start jax profiler trace")
        self._task = asyncio.create_task(self._run(), name="quorum-engine")

    async def close(self) -> None:
        self._running = False
        if QuorumEngine._profiling_owner is self:
            import jax
            QuorumEngine._profiling_owner = None
            try:
                jax.profiler.stop_trace()
            except Exception:
                LOG.exception("could not stop jax profiler trace")
        if self._task is not None:
            self._wake.set()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # drop the engine registry from the global scrape surface; the
        # counters stay readable through engine.metrics (tests inspect a
        # closed cluster's engines)
        self._m.unregister()
        self.ledger.unregister()

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while self._running:
            if self._wake.is_set():
                # busy: events already queued — tick now, skip the timer
                # allocation wait_for would make (hot at high group counts).
                # NOTE: pacing dispatches at a tick_interval floor was tried
                # here and measured ~2.5x WORSE end-to-end at 1024 groups:
                # commit latency compounds through the sequential per-group
                # write pipelines, so ticking at the front of the loop
                # backlog beats amortizing dispatch overhead.
                await asyncio.sleep(0)
            else:
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           self.tick_interval_s)
                except asyncio.TimeoutError:
                    pass
            self._wake.clear()
            t0 = loop.time()
            if QuorumEngine._profiling_owner is self:
                # named step in the xprof timeline (one per dispatch).
                # ONLY the owning engine annotates: co-hosted engines share
                # one process-wide trace, and three interleaved step_num
                # sequences would make xprof's per-step view meaningless.
                import jax
                with jax.profiler.StepTraceAnnotation(
                        "engine_tick", step_num=self._m.ticks.count):
                    await self.tick()
            else:
                await self.tick()
            self.last_tick_monotonic = loop.time()
            cost = loop.time() - t0
            if cost > self.tick_interval_s:
                # Self-pacing: a dispatch that cost more than the tick
                # interval (big [G, P] batch on a slow backend) must not
                # monopolize the event loop — sleeping roughly one tick-cost
                # bounds the engine's duty cycle at ~50% and lets more acks
                # accumulate per dispatch.  Capped: one pathological tick
                # (wedged backend, measured wall-clock inflated by other
                # coroutines) must not impose an unbounded second stall on
                # every group's quorum/election processing.
                await asyncio.sleep(min(cost, 8 * self.tick_interval_s))

    # -- the tick ------------------------------------------------------------

    # Rebase when now_ms passes this (half of int32 max, lots of margin).
    _REBASE_THRESHOLD_MS = 1 << 30
    _REBASE_KEEP_MS = 3_600_000  # keep the last hour of history meaningful

    def _maybe_rebase_epoch(self, now: int) -> int:
        """Shift the clock epoch forward and subtract the delta from every
        stored time so int32 never wraps (see ops.quorum time convention)."""
        if now < self._REBASE_THRESHOLD_MS:
            return now
        s = self.state
        delta = now - self._REBASE_KEEP_MS
        self.clock.advance_epoch(delta)
        s.last_ack_ms -= np.int32(delta)
        np.maximum(s.last_ack_ms, 0, out=s.last_ack_ms)
        mask = s.election_deadline_ms != NO_DEADLINE
        s.election_deadline_ms[mask] -= np.int32(delta)
        vmask = s.vote_deadline_ms != NO_DEADLINE
        s.vote_deadline_ms[vmask] -= np.int32(delta)
        self._ack_ring = [(g, p, m, max(0, t - delta))
                          for g, p, m, t in self._ack_ring]
        for u in self._slot_updates.values():
            if u[1] != _PACK_SENTINEL and u[1] != NO_DEADLINE:
                u[1] = max(0, u[1] - delta)
        self._next_staleness_ms = 0
        self._next_sweep_ms = 0  # pre-rebase timestamp would gate forever
        self._dev = None  # wholesale time shift: re-upload the device state
        return now - delta

    # Ack/flush backlog bound for the sweep-gated batched path: beyond this
    # many queued events, ship them even with no sweep due (keeps the ring
    # far below the chunking cap and the device's staleness inputs fresh).
    _EVENT_BACKLOG_MAX = 8192

    async def tick(self) -> None:
        # The math pass runs under the intake lock: shard-loop intake
        # (on_ack/on_flush/...) and the tick swap/read the same rings and
        # host mirror.  The lock is released BEFORE listener callbacks —
        # holding a threading lock across awaits would stall every shard's
        # intake for the duration of division code.
        with self._lock:
            changed, votes = self._tick_locked()
        for fut, result in votes:
            self._resolve_future(fut, result)

        # dispatch callbacks outside the math pass; a listener pinned to a
        # different shard loop gets its callback ON that loop
        running = asyncio.get_running_loop()
        for slot, kind, value in changed:
            listener = self._listeners.get(slot)
            if listener is None:
                continue
            if kind == "commit":
                self._m.commit_advances.inc()
                coro = listener.on_commit_advance(value)
            elif kind == "timeout":
                coro = listener.on_election_timeout()
            else:  # "stale"
                if getattr(listener, "hibernating", False):
                    continue  # requested silence; cheap skip, no coroutine
                coro = listener.on_leadership_stale()
            lloop = self._listener_loops.get(slot)
            if lloop is None or lloop is running:
                await coro
            else:
                try:
                    await asyncio.wrap_future(
                        asyncio.run_coroutine_threadsafe(coro, lloop))
                except RuntimeError:
                    coro.close()  # shard loop gone (server closing)

    def _tick_locked(self) -> tuple[list, list]:
        """One tick's math pass (caller holds the intake lock).  Returns
        (changed listener events, resolved vote futures)."""
        s = self.state
        now = self._maybe_rebase_epoch(self.clock.now_ms())
        self._m.ticks.inc()

        active = s.active
        if not active:
            self._ack_ring.clear()
            s.dirty.clear()
            self._slot_updates.clear()
            self._dev = None
            return [], []

        use_batched = (self.use_device
                       or len(active) >= self.scalar_fallback_threshold)
        if use_batched and self._dev is not None \
                and not self._tick_commit_pending \
                and not s.dirty and not self._vote_rounds \
                and not self._vote_ring and now < self._next_sweep_ms \
                and (len(self._ack_ring) + len(self._slot_updates)
                     < self._EVENT_BACKLOG_MAX):
            # Nothing the device could DECIDE right now: commits already
            # advanced inline at intake, and no deadline/staleness sweep is
            # due.  Let events accumulate — the next dispatch carries a
            # bigger packed batch (the shape the kernel wants) and the
            # engine's dispatch rate drops from per-tick to per-sweep.
            self._m.idle_skips.inc()
            return [], []
        if use_batched:
            # why did the gate let this dispatch through? (the labeled
            # dispatches{reason=...} counters; see EngineMetrics)
            reasons = self._m.reasons
            if self._dev is None:
                reasons["upload"].inc()
            elif self._tick_commit_pending:
                reasons["commit"].inc()
            elif s.dirty:
                reasons["dirty"].inc()
            elif self._vote_rounds or self._vote_ring:
                reasons["votes"].inc()
            elif now >= self._next_sweep_ms:
                reasons["sweep"].inc()
            else:
                reasons["backlog"].inc()

        acks = self._ack_ring
        self._ack_ring = []
        self._m.acks.inc(len(acks))

        # The host mirror was updated eagerly at ack intake (on_ack), where
        # the commit advance now happens inline; the events still travel to
        # the device below so the resident state applies the same
        # scatter-max and stays in agreement without ever downloading the
        # [G, P] arrays.
        touched: set[int] = set(s.dirty)
        touched.update(a[0] for a in acks)

        if use_batched:
            self._tick_commit_pending = False
            changed = self._tick_batched(acks, now)
            self._next_sweep_ms = self._compute_next_sweep(now)
        else:
            # flush advances queued as packed updates still need their
            # slots' commit math in the scalar pass (mirror already has the
            # values)
            touched.update(self._slot_updates)
            self._slot_updates.clear()
            # host-only mutations make any retained device copy stale; drop
            # it so a later crossing back over the threshold re-uploads
            s.dirty.clear()
            self._dev = None
            self._tick_commit_pending = False
            changed = self._tick_scalar(touched, now)

        votes = (self._vote_pass(now)
                 if (self._vote_rounds or self._vote_ring) else [])
        return changed, votes

    def _compute_next_sweep(self, now: int) -> int:
        """Earliest time the device must be consulted again with no new
        events: the soonest armed follower deadline, bounded by the
        staleness-sweep cadence (timeout/4, matching the scalar path) —
        but only when this server leads anything (a follower-only or idle
        server has no leaderships to check for staleness)."""
        s = self.state
        dl = np.where(s.role == ROLE_FOLLOWER, s.election_deadline_ms,
                      NO_DEADLINE)
        nxt = int(dl.min()) if dl.size else NO_DEADLINE
        if bool((s.role == ROLE_LEADER).any()):
            nxt = min(nxt, now + max(1, self.leadership_timeout_ms // 4))
        return nxt

    # -- scalar path ---------------------------------------------------------

    def _tick_scalar(self, touched: set[int], now: int
                     ) -> list[tuple[int, str, int]]:
        """Python fallback for small group counts: commit math only for
        slots with new acks / flush advances (``touched``); the O(leaders)
        staleness sweep runs at most every leadership_timeout/4."""
        s = self.state
        changed: list[tuple[int, str, int]] = []
        check_stale = now >= self._next_staleness_ms
        if check_stale:
            self._next_staleness_ms = now + max(
                1, self.leadership_timeout_ms // 4)
        trace_t0 = (TRACER.now() if touched
                    and TRACER.enabled and TRACER.sample() else 0)

        for slot in list(s.active):
            role = s.role[slot]
            if role == ROLE_LEADER:
                if slot in touched:
                    new_commit, did = ref.update_commit(
                        s.match_index[slot].tolist(), int(s.self_slot[slot]),
                        int(s.flush_index[slot]), s.conf_cur[slot].tolist(),
                        s.conf_old[slot].tolist(), int(s.commit_index[slot]),
                        int(s.first_leader_index[slot]), True)
                    if did:
                        s.commit_index[slot] = new_commit
                        changed.append((slot, "commit", new_commit))
                if check_stale and ref.check_leadership(
                        s.last_ack_ms[slot].tolist(), int(s.self_slot[slot]),
                        s.conf_cur[slot].tolist(), s.conf_old[slot].tolist(),
                        now, self.leadership_timeout_ms, True):
                    changed.append((slot, "stale", 0))
            elif role == ROLE_FOLLOWER and now >= s.election_deadline_ms[slot]:
                s.election_deadline_ms[slot] = NO_DEADLINE  # re-armed by div
                changed.append((slot, "timeout", 0))
        if trace_t0:
            TRACER.record(0, STAGE_ENGINE, trace_t0, TRACER.now(),
                          tag=len(touched))
        return changed

    # -- batched path --------------------------------------------------------

    def _kernels(self):
        # One process-wide jitted step: the kernel is pure and every engine
        # in the process (one per co-hosted server) shares shapes, so a
        # shared wrapper compiles each shape bucket once instead of once
        # per server.  With a mesh, the per-engine sharded variants are
        # used instead (same kernels, group axis partitioned).
        if self.mesh is not None:
            return self._mesh_steps()[0]
        return _shared_step()

    def _fast_kernel(self):
        if self.mesh is not None:
            return self._mesh_steps()[1]
        return _shared_fast_step()

    def _mesh_steps(self):
        # Process-wide like _shared_step: co-hosted servers build EQUAL
        # meshes over the same devices, so keying by (devices, axes) lets
        # one compile serve every engine (prewarming servers[0] covers the
        # trio) instead of each engine landing its own synchronous compile
        # mid-run.
        key = (tuple(d.id for d in self.mesh.devices.flat),
               self.mesh.axis_names)
        steps = _SHARDED_STEPS.get(key)
        if steps is None:
            from ratis_tpu.parallel.mesh import (
                sharded_resident_fast_step_sliced, sharded_resident_step)
            # fast path: the SLICED variant — events pre-routed per device
            # ([7, S, E]) instead of replicated; refresh path keeps
            # replicated inputs (dirty rows are rare and whole-row)
            steps = (sharded_resident_step(self.mesh),
                     sharded_resident_fast_step_sliced(self.mesh))
            _SHARDED_STEPS[key] = steps
        return steps

    def prewarm(self, group_counts=(64, 256, 1024),
                event_counts=(64, 256, 1024)) -> None:
        """Compile the batched kernel for the standard pad buckets up front.

        XLA compiles per shape signature; without prewarming, the first tick
        that hits a new (dirty-rows, events) bucket stalls the event loop for
        the compile — long enough on slow backends to fire election timeouts
        and churn leadership mid-benchmark.  Runs the real tick path against
        the current (zero/idle) state; listeners never fire because outputs
        are filtered by the active set."""
        s = self.state
        now = self.clock.now_ms()
        saved_dirty = set(s.dirty)
        # backlog chunking must stay inside what this call compiles — a
        # bigger batch mid-run would be a fresh shape = a synchronous
        # multi-second compile on the event loop
        self._event_bucket_cap = max(self._bucket(ec) for ec in event_counts)
        for dc in group_counts:
            if dc > s.capacity:
                continue
            for ec in event_counts:
                s.dirty = set(range(dc))
                acks = [(0, 0, -1, now)] * ec
                self._tick_batched(acks, now)
        # fast path (zero dirty rows): one compile per event bucket
        for ec in event_counts:
            s.dirty = set()
            self._tick_batched([(0, 0, -1, now)] * ec, now)
        # vote tally: one compile for the [G, P] shape (fires during
        # bring-up election storms otherwise)
        import jax.numpy as jnp
        _shared_tally()(
            jnp.asarray(s.vote_grants), jnp.asarray(s.vote_rejects),
            jnp.asarray(s.conf_cur), jnp.asarray(s.conf_old),
            jnp.asarray(s.priority), jnp.asarray(s.self_priority))
        s.dirty = saved_dirty
        self._dev = None  # drop the prewarm device copy; re-upload on use

    def _upload_device_state(self):
        import jax.numpy as jnp
        from ratis_tpu.ops import quorum as q
        s = self.state
        dev = q.DeviceState(
            jnp.asarray(s.match_index), jnp.asarray(s.last_ack_ms),
            jnp.asarray(s.self_mask), jnp.asarray(s.conf_cur),
            jnp.asarray(s.conf_old), jnp.asarray(s.role),
            jnp.asarray(s.flush_index), jnp.asarray(s.commit_index),
            jnp.asarray(s.first_leader_index),
            jnp.asarray(s.election_deadline_ms))
        if self.mesh is not None:
            from ratis_tpu.parallel.mesh import shard_device_state
            dev = shard_device_state(self.mesh, dev)
        return dev

    @staticmethod
    def _pow2(n: int) -> int:
        return 1 << (max(1, n) - 1).bit_length()

    @staticmethod
    def _bucket(n: int) -> int:
        """Pad size for event/dirty batches: 64 * 4^k.  Coarser than plain
        pow2 so the jit compiles O(few) shape buckets instead of one per
        power of two — padding costs bytes, recompiles cost tens of
        milliseconds (CPU) to tens of seconds (remote TPU)."""
        b = 64
        while b < n:
            b *= 4
        return b

    def _pack_tick(self, acks, updates: dict) -> np.ndarray:
        """Pack acks + slot updates into the [7, E] fast-tick array (column
        layout documented at ops.quorum.engine_step_resident_fast)."""
        n = len(acks) + len(updates)
        ecap = self._bucket(n)
        self._last_event_rows, self._last_event_cap = n, ecap
        evp = np.full((7, ecap), _PACK_SENTINEL, np.int32)
        evp[0] = 0
        evp[1] = 0
        evp[4] = 0
        if acks:
            a = np.asarray(acks, np.int32)  # [E, 4]
            k = len(acks)
            evp[:4, :k] = a.T
            evp[4, :k] = 1
        if updates:
            k = len(acks)
            for i, (slot, (flush, deadline)) in enumerate(updates.items()):
                evp[0, k + i] = slot
                evp[5, k + i] = flush
                evp[6, k + i] = deadline
        return evp

    def _pack_tick_sliced(self, acks, updates: dict) -> np.ndarray:
        """Slice-routed fast-tick packing: [7, S, E] with SLICE-LOCAL row
        indices (ops.quorum.engine_step_resident_fast_sliced).  Each mesh
        device receives only its slice's [7, 1, E] plane; E is the bucket
        of the FULLEST slice, so a balanced intake ships ~1/S of the flat
        pack's columns per device."""
        s = self.state
        n_slices, rows = s.n_slices, s.slice_rows
        na = len(acks)
        a = np.asarray(acks, np.int32).reshape(na, 4)  # slot,peer,match,t
        asl = a[:, 0] // rows
        ack_counts = np.bincount(asl, minlength=n_slices)
        counts = ack_counts.copy()
        for slot in updates:
            counts[slot // rows] += 1
        n = int(counts.max()) if n_slices else 0
        ecap = self._bucket(n)
        self._last_event_rows, self._last_event_cap = n, ecap
        evp = np.full((7, n_slices, ecap), _PACK_SENTINEL, np.int32)
        evp[0] = 0
        evp[1] = 0
        evp[4] = 0
        if na:
            order = np.argsort(asl, kind="stable")
            srt, ssl = a[order], asl[order]
            starts = np.concatenate(
                ([0], np.cumsum(ack_counts)[:-1])).astype(np.int64)
            col = np.arange(na) - starts[ssl]
            evp[0, ssl, col] = srt[:, 0] % rows
            evp[1, ssl, col] = srt[:, 1]
            evp[2, ssl, col] = srt[:, 2]
            evp[3, ssl, col] = srt[:, 3]
            evp[4, ssl, col] = 1
        cur = ack_counts.copy()
        for slot, (flush, deadline) in updates.items():
            sl = slot // rows
            c = int(cur[sl])
            cur[sl] += 1
            evp[0, sl, c] = slot % rows
            evp[5, sl, c] = flush
            evp[6, sl, c] = deadline
        return evp

    # Hard ceiling on one dispatch's event bucket (64 * 4^4).  A backlog
    # tick must NEVER exceed the largest COMPILED bucket: the next bucket
    # would be a brand-new jit shape, and that compile (measured minutes
    # on the CPU backend at E=65536, 12.9s at E=8192->16384) lands
    # synchronously on the event loop mid-run.  Oversized batches are
    # processed as bounded-shape chunks instead; prewarm() lowers the
    # effective cap to the largest bucket it actually compiled.
    _MAX_EVENT_BUCKET = 16384

    def _tick_batched(self, acks, now: int) -> list[tuple[int, str, int]]:
        cap = min(self._MAX_EVENT_BUCKET, self._event_bucket_cap)
        if len(acks) + len(self._slot_updates) <= cap:
            return self._tick_batched_pass(acks, now)
        # Pathological backlog (the loop was stalled long enough for >16k
        # events to queue): run bounded chunks through the same kernels.
        # Duplicate commit events self-suppress in _collect_changed (device
        # value vs mirror) and deadline disarms persist on device, so the
        # chunk merge is a plain concatenation.
        changed: list[tuple[int, str, int]] = []
        updates_all, self._slot_updates = self._slot_updates, {}
        idx = 0
        first = True
        while first or idx < len(acks) or updates_all:
            first = False
            chunk = acks[idx:idx + cap]
            idx += cap
            room = cap - len(chunk)
            upd: dict[int, list] = {}
            while room > 0 and updates_all:
                k, v = updates_all.popitem()
                upd[k] = v
                room -= 1
            self._slot_updates = upd
            changed.extend(self._tick_batched_pass(chunk, now))
        return changed

    def _tick_batched_pass(self, acks, now: int) -> list[tuple[int, str, int]]:
        # dispatch-latency timer: host -> XLA -> host wall for this sweep
        # (pack + upload + kernel + output download), recorded even on an
        # exception path so a wedged backend shows up in the p99
        with self._m.dispatch_timer.time():
            self._m.ack_batch.update(len(acks))
            return self._tick_batched_dispatch(acks, now)

    def _tick_batched_dispatch(self, acks, now: int
                               ) -> list[tuple[int, str, int]]:
        import jax.numpy as jnp

        s = self.state
        self._m.batched_dispatches.inc()
        # engine.dispatch host-path span (process-level, sampled): the
        # device round-trip cost per dispatch, tag = packed event count
        trace_t0 = (TRACER.now()
                    if TRACER.enabled and TRACER.sample() else 0)

        if self._dev is None or self._dev.match_index.shape != s.match_index.shape:
            # first batched tick / capacity regrow / epoch rebase: one full
            # upload, after which only dirty rows and events travel.
            self._dev = self._upload_device_state()
            s.dirty.clear()
            self._slot_updates.clear()  # the full upload carried them

        if not s.dirty:
            # Fast path (the steady state under load): two packed uploads,
            # one packed download — profiling showed the unpacked step's 18
            # small transfers costing more than the quorum math itself.
            # Flush advances and deadline re-arms travel as packed updates
            # alongside the acks, so routine traffic never needs a refresh.
            self._m.fast_ticks.inc()
            step = self._fast_kernel()
            updates, self._slot_updates = self._slot_updates, {}
            # mesh: slice-routed [7, S, E] planes for the sliced kernel;
            # single device: the flat [7, E] pack
            ev = (self._pack_tick_sliced(acks, updates)
                  if self.mesh is not None
                  else self._pack_tick(acks, updates))
            res = step(self._dev, jnp.asarray(ev),
                       jnp.asarray(np.array(
                           [now, self.leadership_timeout_ms], np.int32)))
            self._dev = res.state
            out = np.asarray(res.out)
            changed = self._collect_changed(out[0], out[1] != 0, out[2] != 0,
                                            out[3] != 0)
            if trace_t0:
                TRACER.record(0, STAGE_ENGINE, trace_t0, TRACER.now(),
                              tag=len(acks))
            return changed

        # dirty-row refresh: O(changed slots) host->device.  Slots with
        # queued packed updates fold in here — the mirror already holds
        # their values, so the row refresh carries them.
        self._m.refresh_ticks.inc()
        dirty = sorted(s.dirty | set(self._slot_updates))
        self._slot_updates.clear()
        s.dirty.clear()
        self._m.refresh_rows.inc(len(dirty))
        dcap = self._bucket(len(dirty))
        # padded entries point one past the end -> dropped by the scatter
        rf_idx = np.full(dcap, s.capacity, np.int32)
        rf_idx[:len(dirty)] = dirty
        gi = np.minimum(rf_idx, s.capacity - 1)  # in-range gather indices

        # packed ack events: O(events) host->device
        ecap = self._bucket(len(acks))
        self._last_event_rows, self._last_event_cap = len(acks), ecap
        evg = np.zeros(ecap, np.int32)
        evp = np.zeros(ecap, np.int32)
        evm = np.zeros(ecap, np.int32)
        evt = np.zeros(ecap, np.int32)
        evv = np.zeros(ecap, bool)
        for i, (slot, peer, match, t) in enumerate(acks):
            evg[i], evp[i], evm[i], evt[i], evv[i] = slot, peer, match, t, True

        step = self._kernels()
        res = step(
            self._dev,
            jnp.asarray(rf_idx), jnp.asarray(s.match_index[gi]),
            jnp.asarray(s.last_ack_ms[gi]), jnp.asarray(s.self_mask[gi]),
            jnp.asarray(s.conf_cur[gi]), jnp.asarray(s.conf_old[gi]),
            jnp.asarray(s.role[gi]), jnp.asarray(s.flush_index[gi]),
            jnp.asarray(s.commit_index[gi]),
            jnp.asarray(s.first_leader_index[gi]),
            jnp.asarray(s.election_deadline_ms[gi]),
            jnp.asarray(evg), jnp.asarray(evp), jnp.asarray(evm),
            jnp.asarray(evt), jnp.asarray(evv),
            jnp.int32(now), jnp.int32(self.leadership_timeout_ms))
        self._dev = res.state

        # downloads: only the [G] outputs (masks + commit values), never the
        # [G, P] state
        changed = self._collect_changed(
            np.asarray(res.new_commit), np.asarray(res.commit_changed),
            np.asarray(res.timeouts), np.asarray(res.stale))
        if trace_t0:
            TRACER.record(0, STAGE_ENGINE, trace_t0, TRACER.now(),
                          tag=len(acks))
        return changed

    def _collect_changed(self, new_commit_np, commit_changed_np, timeouts_np,
                         stale_np) -> list[tuple[int, str, int]]:
        s = self.state
        changed: list[tuple[int, str, int]] = []
        for slot in np.nonzero(commit_changed_np)[0]:
            i = int(slot)
            if i in s.active:
                v = int(new_commit_np[i])
                # The inline ack path usually advanced the mirror (and fired
                # the listener) before this tick; the device event is then a
                # duplicate and must not re-fire.  Fire only when the device
                # is genuinely ahead (e.g. a dirty-row refresh carried state
                # the inline path never saw).
                if v > int(s.commit_index[i]):
                    s.commit_index[i] = v
                    changed.append((i, "commit", v))
        for slot in np.nonzero(timeouts_np)[0]:
            i = int(slot)
            # the kernel disarmed the deadline on device; mirror that here
            # (direct write, NOT mark_dirty: host and device already agree)
            if i in s.active:
                s.election_deadline_ms[i] = NO_DEADLINE
                changed.append((i, "timeout", 0))
        for slot in np.nonzero(stale_np)[0]:
            i = int(slot)
            if i in s.active:
                changed.append((i, "stale", 0))
        return changed
