"""Device-side role codes, shared by the host state arrays and the kernels.

Dependency-free on purpose: ops.quorum (jax) and engine.state (numpy) both
import from here, so importing the host server stack never pays jax init.
Distinct from protocol.peer.RaftPeerRole, whose values are wire-stable
(Raft.proto RaftPeerRole) — these are the int8 codes stored in the [G] role
array the kernels match on.
"""

ROLE_UNUSED = 0
ROLE_FOLLOWER = 1
ROLE_CANDIDATE = 2
ROLE_LEADER = 3
ROLE_LISTENER = 4
