"""ratis-tpu: a TPU-native multi-Raft consensus framework.

A ground-up re-design of the capabilities of Apache Ratis (reference:
/root/reference, pure Java) for TPU hosts:

- One asyncio host process serves thousands of independent Raft groups
  ("multi-Raft", cf. reference RaftServerProxy.java:81) behind a single
  transport endpoint.
- All per-group mutable consensus scalars (term, role, commitIndex,
  matchIndex[peers], vote grants, timeout deadlines, lease clocks) live in
  ``[num_groups, ...]`` device arrays.  Commit advancement
  (LeaderStateImpl.updateCommit, reference LeaderStateImpl.java:907), vote
  tallies (LeaderElection.waitForResults, reference LeaderElection.java:498)
  and failure detection run as single jitted XLA dispatches across the whole
  group axis instead of per-group threads.
- Durable state (segmented log files, raft-meta, snapshots) and the network
  (simulated in-memory queues or gRPC) stay on the host, feeding the device
  engine with packed event tensors.

Public API mirrors the reference's layering:

- :mod:`ratis_tpu.conf`      — RaftProperties-style configuration.
- :mod:`ratis_tpu.protocol`  — ids, peers, groups, requests, exceptions.
- :mod:`ratis_tpu.ops`       — the batched quorum kernels (the point).
- :mod:`ratis_tpu.server`    — RaftServer / Division runtime.
- :mod:`ratis_tpu.client`    — RaftClient APIs.
- :mod:`ratis_tpu.transport` — pluggable RPC (simulated, grpc).
"""

__version__ = "0.1.0"

from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer, RaftPeerRole
from ratis_tpu.protocol.group import RaftGroup, RaftGroupMemberId
from ratis_tpu.protocol.message import Message
from ratis_tpu.conf.properties import RaftProperties

__all__ = [
    "ClientId",
    "Message",
    "RaftGroup",
    "RaftGroupId",
    "RaftGroupMemberId",
    "RaftPeer",
    "RaftPeerId",
    "RaftPeerRole",
    "RaftProperties",
    "__version__",
]
