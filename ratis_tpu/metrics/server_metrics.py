"""Per-division metric facades over the registry.

Capability parity with the reference server metric impls
(ratis-server/src/main/java/org/apache/ratis/server/metrics/ and
impl/StateMachineMetrics.java): ``RaftServerMetrics`` (retry-cache
hit/miss, request queue size, watch/read timers, commitInfo gauges),
``LeaderElectionMetrics`` (election count/time, last leader elapsed),
``SegmentedRaftLogMetrics`` (flush/sync timers + queue gauges),
``LogAppenderMetrics`` (per-follower next/match/rpcTime gauges),
``StateMachineMetrics`` (appliedIndex gauge, takeSnapshot timer).
Metric names follow the catalog in
ratis-docs/src/site/markdown/metrics.md:19-140 so dashboards written for
the reference carry over.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ratis_tpu.metrics.registry import (MetricRegistries, MetricRegistryInfo,
                                        RatisMetricRegistry)

RATIS_APPLICATION_NAME = "ratis"


def _create(component: str, prefix: str, name: str) -> RatisMetricRegistry:
    info = MetricRegistryInfo(prefix=prefix,
                              application=RATIS_APPLICATION_NAME,
                              component=component, name=name)
    return MetricRegistries.global_registries().create(info)


class _MetricsBase:
    component = "server"
    name = "metrics"

    def __init__(self, member_id) -> None:
        self.registry = _create(self.component, str(member_id), self.name)

    def unregister(self) -> None:
        MetricRegistries.global_registries().remove(self.registry.info)

    def snapshot(self) -> dict:
        return self.registry.snapshot()


class RaftServerMetrics(_MetricsBase):
    """server component catalog (metrics.md "server" table)."""

    component = "server"
    name = "raft_server"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.retry_cache_hit = r.counter("numRetryCacheHits")
        self.retry_cache_miss = r.counter("numRetryCacheMisses")
        self.num_requests = r.counter("numRaftClientRequests")
        self.num_failed = r.counter("numFailedClientRequests")
        self.watch_timer = r.timer("watchRequestLatency")
        self.read_timer = r.timer("readRequestLatency")
        self.write_timer = r.timer("writeRequestLatency")
        self.follower_append_timer = r.timer("follower_append_entry_latency")

    def add_commit_info_gauge(self, supplier: Callable[[], dict]) -> None:
        self.registry.gauge("commitInfos", supplier)

    def add_queue_gauge(self, supplier: Callable[[], int]) -> None:
        self.registry.gauge("numPendingRequestInQueue", supplier)


class LeaderElectionMetrics(_MetricsBase):
    component = "leader_election"
    name = "leader_election"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.election_count = r.counter("electionCount")
        self.timeout_count = r.counter("timeoutCount")  # election timeouts
        self.election_timer = r.timer("electionTime")
        self.transfer_count = r.counter("transferLeadershipCount")
        # timeout_count ← Division.on_election_timeout;
        # transfer_count ← server.admin.transfer_leadership
        self._last_leader_time: Optional[float] = None
        r.gauge("lastLeaderElapsedTime", self._elapsed_since_leader)

    def on_new_leader_elected(self) -> None:
        self._last_leader_time = time.monotonic()

    def _elapsed_since_leader(self) -> float:
        if self._last_leader_time is None:
            return -1.0
        return time.monotonic() - self._last_leader_time


class LogWorkerMetrics(_MetricsBase):
    """Shared per-storage-device worker catalog
    (metrics.md log_worker table: flushTime/flushCount/syncTime)."""

    component = "log_worker"
    name = "log_worker"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.flush_timer = r.timer("flushTime")
        self.flush_count = r.counter("flushCount")
        self.sync_timer = r.timer("syncTime")
        # actual fsync() calls — flushCount is per drain batch; with many
        # files per batch the two diverge, and syncCount/commits is the
        # fsyncs-per-commit figure the shared log plane exists to shrink
        self.sync_count = r.counter("syncCount")

    def add_queue_gauges(self, pending_supplier: Callable[[], int]) -> None:
        self.registry.gauge("numPendingIO", pending_supplier)

    def add_sweep_gauge(self, supplier: Callable[[], float]) -> None:
        """Decayed average of fsyncs issued per drain sweep (1.0 when every
        division shares one segment file, ~N with per-group files)."""
        self.registry.gauge("fsyncsPerSweep", supplier)


class SharedLogMetrics(_MetricsBase):
    """Per-shard shared-log store catalog (segment footprint, flush
    backlog, compaction reclaim)."""

    component = "log_worker"
    name = "shared_log"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.compaction_count = r.counter("compactionCount")
        self.compaction_reclaimed = r.counter("compactionReclaimedBytes")

    def add_store_gauges(self, bytes_supplier: Callable[[], int],
                         pending_supplier: Callable[[], int]) -> None:
        self.registry.gauge("sharedSegmentBytes", bytes_supplier)
        self.registry.gauge("logPendingFlushDepth", pending_supplier)


class SegmentedRaftLogMetrics(_MetricsBase):
    """Per-division segmented-log catalog (append/truncate/purge)."""

    component = "log_worker"
    name = "segmented_raft_log"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.append_timer = r.timer("appendEntryLatency")
        self.truncate_count = r.counter("truncateLogCount")
        self.purge_count = r.counter("purgeLogCount")
        # entry-cache eviction + read-through (reference raft_log cache
        # hit/miss counters, SegmentedRaftLogMetrics.java)
        self.cache_hit_count = r.counter("cacheHitCount")
        self.cache_miss_count = r.counter("cacheMissCount")
        self.cache_evict_count = r.counter("cacheEvictCount")


class LogAppenderMetrics(_MetricsBase):
    component = "log_appender"
    name = "log_appender"

    def add_follower_gauges(self, peer_id, next_index: Callable[[], int],
                            match_index: Callable[[], int],
                            rpc_elapsed: Callable[[], float]) -> None:
        self.registry.gauge(f"follower_{peer_id}_next_index", next_index)
        self.registry.gauge(f"follower_{peer_id}_match_index", match_index)
        self.registry.gauge(f"follower_{peer_id}_rpc_elapsed_s", rpc_elapsed)

    def remove_follower_gauges(self, peer_id) -> None:
        for suffix in ("next_index", "match_index", "rpc_elapsed_s"):
            self.registry.remove(f"follower_{peer_id}_{suffix}")


class DataStreamMetrics(_MetricsBase):
    """DataStream server packet/stream counters + latency (reference
    NettyServerStreamRpcMetrics, ratis-netty/.../metrics/)."""

    component = "datastream"
    name = "netty_stream_server"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.request_timer = r.timer("streamRequestLatency")
        self.num_requests = r.counter("numRequests")
        self.num_failed = r.counter("numFailedRequests")
        self.bytes_written = r.counter("numBytesWritten")
        self.streams_started = r.counter("numStreamsStarted")
        self.streams_closed = r.counter("numStreamsClosed")


class StateMachineMetrics(_MetricsBase):
    component = "state_machine"
    name = "state_machine"

    def __init__(self, member_id) -> None:
        super().__init__(member_id)
        r = self.registry
        self.snapshot_timer = r.timer("takeSnapshot")
        self.applied_count = r.counter("appliedTransactionCount")

    def add_applied_index_gauge(self, supplier: Callable[[], int]) -> None:
        self.registry.gauge("appliedIndex", supplier)
