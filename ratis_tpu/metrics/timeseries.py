"""Continuous telemetry: a per-server background time-series sampler.

PR 4 gave every server a point-in-time introspection plane; this module
adds *history*.  Reference analog: the per-server rate/percentile
registries of ratis-metrics (``RaftServerMetricsImpl`` keeps dropwizard
meters exactly so operators can see trends, not samples); the TPU-native
equivalent is one background task per server
(``raft.tpu.telemetry.*``) that takes counter deltas of the registries
the server already maintains at a fixed cadence into a bounded ring of
samples, derives rates (commits/s, acks/s, rewinds/s) and log2-bucket
latency quantiles, and feeds a **space-saving top-k hot-group sketch**
(commits + pending per group) — the zipf hot-group imbalance ROADMAP
item 4's admission control must react to is invisible without per-group
accounting over time.

Design constraints (all asserted by tests/test_telemetry.py):

- **off = zero cost**: the sampler only exists when
  ``raft.tpu.telemetry.enabled`` is set; nothing on any request path.
- **bounded memory**: the sample ring holds ``window / interval``
  entries, the sketch exactly ``k`` counters (Metwally et al.'s
  space-saving: an untracked key evicts the minimum counter and
  inherits its count as error bound — the classical top-k guarantee in
  O(k) space), the latency histogram 64 log2 buckets.
- **torn-snapshot free**: one pass reads live division/engine state the
  same way the stall watchdog does (synchronous reads, ``list()`` over
  the division map, per-division failures swallowed) so group
  register/unregister churn mid-pass never corrupts a sample.

Served at ``GET /timeseries`` (JSON; ``?since=<seq>`` returns only newer
samples so pollers — ``shell top``, the flight recorder — read
incrementally) and ``GET /hotgroups``.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import math
import time
from typing import Optional

import numpy as np

LOG = logging.getLogger(__name__)


def legacy_division_walk(server, last_commit: dict,
                         sketch=None) -> tuple[int, int]:
    """The PR 8 per-division sampling walk, kept verbatim as the
    measured baseline the lag ledger replaced (bench and the pass-cost
    test run it back-to-back against ``TelemetrySampler.sample``).
    Returns (pending_total, leading)."""
    pending_total = 0
    seen = set()
    for div in list(server.divisions.values()):
        try:
            if not div.is_leader() or div.leader_ctx is None:
                continue
            gid = div.group_id
            seen.add(gid)
            commit = int(div.state.log.get_last_committed_index())
            pending = len(div.leader_ctx.pending)
        except Exception:
            continue  # unregistering mid-pass: skip, never tear
        pending_total += pending
        delta = commit - last_commit.get(gid, commit)
        last_commit[gid] = commit
        if (delta > 0 or pending > 0) and sketch is not None:
            sketch.offer(gid, max(0, delta), aux=pending)
    if len(last_commit) > len(seen):
        for gid in list(last_commit):
            if gid not in seen:
                last_commit.pop(gid, None)
    return pending_total, len(seen)


def log2_bucket(value_s: float) -> int:
    """Bucket index for a latency value: bucket i spans
    [2^(i-40), 2^(i-39)) seconds, i.e. bucket 0 ≈ 0.9ns and bucket 63
    ≈ 8e6s — the full range any host-side latency can take."""
    if value_s <= 0:
        return 0
    return max(0, min(63, int(math.log2(value_s) + 40)))


def bucket_upper_s(i: int) -> float:
    """Upper bound of bucket ``i`` in seconds."""
    return 2.0 ** (i - 39)


class Log2Buckets:
    """64-bucket log2 latency histogram with quantile readout.

    Unlike the registry ``Timekeeper`` reservoir (uniform over the whole
    stream), this accumulates the sampler's *windowed* latency
    observations, so quantiles answer "over the telemetry window" — and
    the bucket array is what makes merging across processes a plain
    element-wise sum."""

    __slots__ = ("counts", "total")

    def __init__(self) -> None:
        self.counts = [0] * 64
        self.total = 0

    def update(self, value_s: float, n: int = 1) -> None:
        if n <= 0:
            return
        self.counts[log2_bucket(value_s)] += n
        self.total += n

    def quantile_s(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (log2
        resolution: within 2x of the true value, which is what a trend
        view needs)."""
        if self.total <= 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return bucket_upper_s(i)
        return bucket_upper_s(63)

    def snapshot(self) -> dict:
        return {"count": self.total,
                "p50_ms": round(self.quantile_s(0.50) * 1e3, 3),
                "p90_ms": round(self.quantile_s(0.90) * 1e3, 3),
                "p99_ms": round(self.quantile_s(0.99) * 1e3, 3),
                # sparse encoding: {bucket index: count}, mergeable by sum
                "buckets": {str(i): c for i, c in enumerate(self.counts)
                            if c}}


class SpaceSavingSketch:
    """Metwally-style space-saving heavy hitters over group commit load.

    Exactly ``k`` tracked keys.  ``offer(key, inc)`` either bumps a
    tracked counter or evicts the current minimum, the newcomer
    inheriting its count as the per-key overestimate bound (``err``).
    Guarantees: every key with true count > total/k is tracked, and
    ``count - err <= true <= count``."""

    def __init__(self, k: int) -> None:
        self.k = max(1, int(k))
        # key -> [count, err, aux]; aux carries the last-seen pending
        # depth for the /hotgroups payload (not part of the sketch math)
        self._entries: dict = {}
        self.total = 0

    def offer(self, key, inc: int = 1, aux=None) -> None:
        self.total += max(0, inc)
        e = self._entries.get(key)
        if e is not None:
            e[0] += max(0, inc)
            if aux is not None:
                e[2] = aux
            return
        if len(self._entries) < self.k:
            # room: admit even a zero-delta key (a group with PENDING
            # load but no commits yet is exactly a queue worth watching)
            self._entries[key] = [max(0, inc), 0, aux]
            return
        if inc <= 0:
            return  # never evict a tracked hitter for a zero-delta key
        # evict the minimum counter; the newcomer inherits its count
        victim = min(self._entries, key=lambda x: self._entries[x][0])
        floor = self._entries.pop(victim)[0]
        self._entries[key] = [floor + inc, floor, aux]

    def top(self, n: Optional[int] = None) -> list[dict]:
        items = sorted(self._entries.items(), key=lambda kv: -kv[1][0])
        if n is not None:
            items = items[:n]
        return [{"key": k, "count": c, "err": err, "aux": aux}
                for k, (c, err, aux) in items]

    def __len__(self) -> int:
        return len(self._entries)


class TelemetrySampler:
    """One per server (``RaftServer`` creates it behind
    ``raft.tpu.telemetry.enabled``): samples counter deltas into the
    ring, maintains the latency buckets and the hot-group sketch."""

    def __init__(self, server, interval_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 top_k: Optional[int] = None):
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        keys = RaftServerConfigKeys.Telemetry
        p = server.properties
        self.server = server
        self.interval_s = (interval_s if interval_s is not None
                           else keys.interval(p).seconds)
        window = (window_s if window_s is not None
                  else keys.window(p).seconds)
        self.window_s = window
        self.capacity = max(2, int(round(window / max(1e-3,
                                                      self.interval_s))))
        self.samples: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.sketch = SpaceSavingSketch(
            top_k if top_k is not None else keys.hot_groups(p))
        self.latency = Log2Buckets()
        self._seq = 0
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._t_start = time.monotonic()
        self._last_mono: Optional[float] = None
        self._last_counts: dict = {}
        self._last_timer: tuple = (0, 0.0)   # dispatchLatency (count, sum)
        # Per-slot commit baselines for the hot-group deltas, generation-
        # guarded: a slot's baseline is valid only while alloc_gen matches
        # AND the slot led at the previous pass — first sight as leader
        # anchors at the current commit (delta 0), exactly the old dict
        # walk's anchor/prune semantics, but O(1) numpy instead of O(G).
        self._prev_commit = np.full(server.engine.state.capacity, -1,
                                    np.int32)
        self._prev_gen = np.full(server.engine.state.capacity, -1,
                                 np.int32)
        # own registry so the sampler's cost/coverage is itself scraped
        from ratis_tpu.metrics.registry import (MetricRegistries,
                                                MetricRegistryInfo)
        self._info = MetricRegistryInfo(
            prefix=str(server.peer_id), application="ratis",
            component="server", name="telemetry")
        reg = MetricRegistries.global_registries().create(self._info)
        self.registry = reg
        self._samples_taken = reg.counter("telemetrySamples")
        self._sample_cost = reg.timer("telemetrySampleCost")
        reg.gauge("telemetrySeriesLen", lambda: len(self.samples))
        reg.gauge("telemetryHotGroupsTracked", lambda: len(self.sketch))

    @property
    def tracked_groups(self) -> int:
        """Slots with a live commit baseline (led at the last pass)."""
        return int((self._prev_gen >= 0).sum())

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(
            self._run(), name=f"telemetry-{self.server.peer_id}")

    async def close(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        from ratis_tpu.metrics.registry import MetricRegistries
        MetricRegistries.global_registries().remove(self._info)

    async def _run(self) -> None:
        while self._running:
            await asyncio.sleep(self.interval_s)
            try:
                self.sample()
            except asyncio.CancelledError:
                raise
            except Exception:
                # telemetry must never take the server down with it
                LOG.exception("%s telemetry sample failed",
                              self.server.peer_id)

    # ------------------------------------------------------------- sampling

    def sample(self) -> dict:
        """One sampling pass (synchronous reads only; public so tests and
        harnesses can force a pass).  Returns the appended sample."""
        with self._sample_cost.time():
            s = self._sample_locked()
        self._samples_taken.inc()
        return s

    def _counter_reads(self) -> dict:
        srv = self.server
        em = srv.engine.metrics
        rm = srv.replication.metrics
        return {
            "commits": em.get("commit_advances", 0),
            "acks": em.get("acks", 0),
            "ticks": em.get("ticks", 0),
            "dispatches": em.get("batched_dispatches", 0),
            "rewinds": (rm.get("rewinds", 0)
                        + rm.get("windowed_rewinds", 0)),
            "events": (srv.watchdog.event_count()
                       if srv.watchdog is not None else 0),
            "fsyncs": self._fsync_reads(),
            "shed": (srv.serving.admission.shed_total
                     if getattr(srv, "serving", None) is not None else 0),
            # upkeep plane (raft.tpu.upkeep.enabled; 0s when off): sweeps
            # that found nothing due — the idle-cost signal the vectorized
            # plane exists to maximize — and total vectorized sweeps
            "upkeep_idle_skips": sum(pl.idle_skips
                                     for pl in getattr(srv, "upkeep", [])),
            "upkeep_sweeps": sum(pl.sweeps
                                 for pl in getattr(srv, "upkeep", [])),
        }

    def _fsync_reads(self) -> int:
        """Cumulative fsync count across this server's log workers (per
        device in memory mode, per shard for the shared durable store)."""
        from ratis_tpu.server.log.segmented import LogWorker
        prefix = f"{self.server.peer_id}:"
        total = 0
        for name, worker in list(LogWorker._instances.items()):
            if name.startswith(prefix):
                total += worker.sync_count
        return total

    def _sample_locked(self) -> dict:
        now_mono = time.monotonic()
        counts = self._counter_reads()
        dt = (now_mono - self._last_mono
              if self._last_mono is not None else self.interval_s)
        dt = max(1e-6, dt)
        rates = {f"{k}_per_s": round(
            max(0, counts[k] - self._last_counts.get(k, 0)) / dt, 3)
            for k in ("commits", "acks", "rewinds", "dispatches",
                      "fsyncs", "shed")}
        # dispatch latency over THIS interval: timer (count, sum) delta
        # feeds the windowed log2 buckets the quantiles read from
        timer = self.server.engine._m.dispatch_timer
        t_count, t_sum = timer.count, timer.mean_s * timer.count
        dc = t_count - self._last_timer[0]
        if dc > 0:
            self.latency.update((t_sum - self._last_timer[1]) / dc, dc)
        self._last_timer = (t_count, t_sum)
        # Per-group commit deltas -> hot-group sketch; pending depth is
        # queue state the admission-control round reads.  The ledger pass
        # replaces the per-division Python walk from PR 8 (str()/attribute
        # chasing over 1024 divisions measured ~14ms/pass): one fused
        # device pass + one fetch, then O(1) numpy here.  LEADER rows only
        # — the leader is where a group's load lands (and where pending
        # queues); a follower walk would triple-count every commit across
        # replicas — and gid OBJECTS as keys (payloads stringify).
        engine = self.server.engine
        led = engine.ledger.sample()
        if len(self._prev_commit) != led.capacity:
            pc = np.full(led.capacity, -1, np.int32)
            pg = np.full(led.capacity, -1, np.int32)
            n = min(led.capacity, len(self._prev_commit))
            pc[:n] = self._prev_commit[:n]
            pg[:n] = self._prev_gen[:n]
            self._prev_commit, self._prev_gen = pc, pg
        anchored = (self._prev_gen == led.gen) & led.leader_mask
        delta = np.where(anchored, led.commit - self._prev_commit, 0)
        pending = np.where(led.leader_mask, led.pending, 0)
        self._prev_commit = led.commit
        self._prev_gen = np.where(led.leader_mask, led.gen,
                                  -1).astype(np.int32)
        # python touches ONLY the slots with something to offer
        for slot in np.nonzero(led.leader_mask
                               & ((delta > 0) | (pending > 0)))[0]:
            listener = engine._listeners.get(int(slot))
            if listener is None:
                continue  # detached mid-pass
            self.sketch.offer(listener.group_id,
                              max(0, int(delta[slot])),
                              aux=int(pending[slot]))
        pending_total = int(pending.sum())
        try:
            occupancy = round(
                len(engine.state.active) / max(1, engine.state.capacity), 4)
        except Exception:
            occupancy = 0.0
        sample = {
            "seq": self._seq,
            "t": round(time.time(), 3),
            "up_s": round(now_mono - self._t_start, 3),
            "rates": rates,
            "totals": counts,
            "occupancy": occupancy,
            "pending": pending_total,
            "divisions": len(self.server.divisions),
            "leading": led.leading,
            "lag": int(max(0, int(led.worst_lag.max()))
                       if led.worst_lag.size else 0),
            "latency": {"p50_ms": round(
                self.latency.quantile_s(0.50) * 1e3, 3),
                "p99_ms": round(self.latency.quantile_s(0.99) * 1e3, 3)},
        }
        self._seq += 1
        self._last_mono = now_mono
        self._last_counts = counts
        self.samples.append(sample)
        return sample

    # ------------------------------------------------------------- payloads

    def maybe_sample(self) -> None:
        """Freshness fill for scrape handlers: take one synchronous pass
        when the newest sample is at least a full interval old (a
        rung-end scraper must see the load it just drove, not a sample
        from before it), without ever beating the background cadence."""
        now = time.monotonic()
        if self._last_mono is None or now - self._last_mono \
                >= self.interval_s:
            try:
                self.sample()
            except Exception:
                LOG.exception("%s telemetry on-demand sample failed",
                              self.server.peer_id)

    def series(self, since: Optional[int] = None) -> list[dict]:
        """Samples with ``seq > since``, oldest first (None = all held)."""
        if since is None:
            return list(self.samples)
        return [s for s in self.samples if s["seq"] > since]

    def timeseries_info(self, query: Optional[dict] = None) -> dict:
        """``GET /timeseries[?since=<seq>]`` payload."""
        self.maybe_sample()
        since = None
        if query:
            try:
                since = int(query.get("since", [None])[0])
            except (TypeError, ValueError):
                since = None
        samples = self.series(since)
        return {
            "peer": str(self.server.peer_id),
            "pid": __import__("os").getpid(),
            "interval_s": self.interval_s,
            "window_s": self.window_s,
            "seq": self._seq - 1,           # newest sample's seq (-1 none)
            "count": len(samples),
            "latency": self.latency.snapshot(),
            "samples": samples,
        }

    def hotgroups_info(self, query: Optional[dict] = None) -> dict:
        """``GET /hotgroups`` payload: the sketch's top-k with the
        space-saving error bound and each group's share of tracked
        commit load."""
        self.maybe_sample()
        n = None
        if query:
            try:
                n = int(query.get("n", [None])[0])
            except (TypeError, ValueError):
                n = None
        total = max(1, self.sketch.total)
        srv = self.server
        groups = []
        for e in self.sketch.top(n):
            gid = e["key"]
            div = srv.divisions.get(gid)
            groups.append({
                "group": str(gid),
                "commits": e["count"],
                "err": e["err"],
                "pending": e["aux"] or 0,
                "share": round(e["count"] / total, 4),
                # guaranteed lower bound (count - err)/total: under
                # uniform load this reads ~0 while `share` reads the
                # sketch's ~1/k overestimate floor — share_min is the
                # honest skew signal
                "share_min": round(
                    max(0, e["count"] - e["err"]) / total, 4),
                # placement facts: does THIS server lead the group, and
                # on which loop shard does it live here
                "led": div is not None and div.is_leader(),
                "shard": srv.shard_of_group(gid),
            })
        return {
            "peer": str(self.server.peer_id),
            "pid": __import__("os").getpid(),
            "k": self.sketch.k,
            "tracked": len(self.sketch),
            "total_commits": self.sketch.total,
            "groups": groups,
        }
