"""Prometheus text exposition + the per-server introspection endpoint.

Reference analog: ratis-metrics exposes dropwizard registries through
reporters (console/JMX, ratis-metrics-default); operators today scrape
Prometheus, so this renders every registry in
:class:`~ratis_tpu.metrics.registry.MetricRegistries` in text exposition
format 0.0.4 and (optionally) serves it over a tiny dependency-free
asyncio HTTP endpoint.

Naming: ``ratis_<component>_<metric>`` with the registry prefix (the group
member id) as a ``member`` label, e.g.::

    ratis_server_numRequests_total{member="s0@group-1234"} 42
    ratis_log_worker_flushTime_seconds{member="...",quantile="0.99"} 0.003

Exposition conformance (asserted in tests/test_observability.py):

- counters carry the ``_total`` suffix and ``# TYPE ... counter``;
- all samples of one metric family are CONSECUTIVE (the 0.0.4 format
  requires it; the naive per-registry walk interleaved families when two
  members shared a catalog);
- label values escape backslash, double-quote, and newline;
- registry names of the form ``name{k="v"}`` (see
  :func:`ratis_tpu.metrics.registry.labeled`) merge their labels with the
  ``member`` label — the framework's labeled-counter convention;
- timers render as ``summary`` in seconds, histograms (dimensionless
  reservoirs) as ``summary`` without a unit suffix.

Beyond ``/metrics`` the HTTP server takes extra JSON routes (``/health``,
``/divisions``, ``/events`` when wired by
:class:`~ratis_tpu.server.server.RaftServer`): the per-server
introspection surface of the cluster observability plane.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
from typing import Callable, Dict, Optional

from ratis_tpu.metrics.registry import MetricRegistries

LOG = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _escape_label(value: str) -> str:
    return value.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _split_labels(metric: str) -> tuple[str, str]:
    """``name{k="v"}`` -> (name, 'k="v"'); plain names -> (name, "")."""
    if "{" in metric and metric.endswith("}"):
        base, _, rest = metric.partition("{")
        return base, rest[:-1]
    return metric, ""


class _Families:
    """Collects samples grouped by metric family so one family's samples
    render consecutively regardless of how many registries feed it."""

    def __init__(self) -> None:
        self._order: list[str] = []
        self._kind: dict[str, str] = {}
        self._samples: dict[str, list[str]] = {}

    def add(self, family: str, kind: str, sample: str) -> None:
        if family not in self._samples:
            self._order.append(family)
            self._kind[family] = kind
            self._samples[family] = []
        self._samples[family].append(sample)

    def render(self) -> str:
        lines: list[str] = []
        for family in self._order:
            lines.append(f"# TYPE {family} {self._kind[family]}")
            lines.extend(self._samples[family])
        return "\n".join(lines) + "\n"


def render_text(registries: Optional[MetricRegistries] = None) -> str:
    """All registries in Prometheus text exposition format."""
    regs = registries or MetricRegistries.global_registries()
    fams = _Families()
    for info in regs.get_registry_infos():
        reg = regs.get(info)
        if reg is None:
            continue  # unregistered between listing and render (scrape race)
        member = _escape_label(info.prefix)
        base = f"{_sanitize(info.application)}_{_sanitize(info.component)}"
        for metric, (kind, value) in sorted(reg.typed_snapshot().items()):
            mbare, extra = _split_labels(metric)
            mname = f"{base}_{_sanitize(mbare)}"
            labels = f'member="{member}"' + (f",{extra}" if extra else "")
            if kind == "timer":
                fam = f"{mname}_seconds"
                fams.add(fam, "summary",
                         f'{fam}_count{{{labels}}} {value.get("count", 0)}')
                total = value.get("mean_s", 0.0) * value.get("count", 0)
                fams.add(fam, "summary",
                         f'{fam}_sum{{{labels}}} {_fmt(total)}')
                for key, q in (("p50_s", "0.5"), ("p99_s", "0.99")):
                    if key in value:
                        fams.add(fam, "summary",
                                 f'{fam}{{{labels},quantile="{q}"}} '
                                 f'{_fmt(value[key])}')
            elif kind == "histogram":
                fams.add(mname, "summary",
                         f'{mname}_count{{{labels}}} {value.get("count", 0)}')
                total = value.get("mean", 0.0) * value.get("count", 0)
                fams.add(mname, "summary",
                         f'{mname}_sum{{{labels}}} {_fmt(total)}')
                for key, q in (("p50", "0.5"), ("p99", "0.99")):
                    if key in value:
                        fams.add(mname, "summary",
                                 f'{mname}{{{labels},quantile="{q}"}} '
                                 f'{_fmt(value[key])}')
            elif kind == "counter":
                fam = (mname if mname.endswith("_total")
                       else f"{mname}_total")
                fams.add(fam, "counter", f'{fam}{{{labels}}} {_fmt(value)}')
            elif isinstance(value, dict):
                # structured gauge (e.g. the commitInfos index map): flatten
                # numeric sub-keys into per-key gauges
                for sub, sval in sorted(value.items()):
                    num = _as_number(sval)
                    if num is None:
                        continue
                    sub_name = f"{mname}_{_sanitize(str(sub))}"
                    fams.add(sub_name, "gauge",
                             f'{sub_name}{{{labels}}} {_fmt(num)}')
            else:
                num = _as_number(value)
                if num is None:
                    continue  # non-numeric gauge (e.g. an error string)
                fams.add(mname, "gauge", f'{mname}{{{labels}}} {_fmt(num)}')
    return fams.render()


def _fmt(num: float) -> str:
    """Full-precision rendering: integers verbatim (a counter past 1e9 must
    not collapse to 1e+09 and stall rate() queries), floats via repr."""
    if isinstance(num, int) or (isinstance(num, float) and num.is_integer()):
        return str(int(num))
    return repr(float(num))


def _as_number(value) -> Optional[float]:
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        return float(value)
    return None


# A JSON route returns any json.dumps-able object; exceptions become 500.
# A route taking a parameter receives the parsed query dict
# ({key: [values]}, urllib.parse.parse_qs) — /events?since= and
# /timeseries?since= poll incrementally through it.
JsonRoute = Callable[..., object]


class MetricsHttpServer:
    """Minimal asyncio HTTP introspection endpoint.

    Dependency-free on purpose (the environment bakes no prometheus
    client); the exposition format is line-oriented text, so a tiny
    handwritten responder is all a scraper needs.  ``GET /metrics`` (and
    ``/``) serve the Prometheus text; every entry in ``json_routes``
    (path -> supplier) serves ``application/json`` — the server wires
    ``/health``, ``/divisions``, and ``/events`` there."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registries: Optional[MetricRegistries] = None,
                 json_routes: Optional[Dict[str, JsonRoute]] = None):
        self.host = host
        self.port = port
        self.registries = registries
        self.json_routes: Dict[str, JsonRoute] = dict(json_routes or {})
        self._server: Optional[asyncio.AbstractServer] = None
        self.bound_port: Optional[int] = None

    @property
    def address(self) -> Optional[str]:
        if self.bound_port is None:
            return None
        return f"{self.host}:{self.bound_port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.bound_port = self._server.sockets[0].getsockname()[1]
        LOG.info("metrics endpoint on http://%s:%d/metrics",
                 self.host, self.bound_port)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _render(self, path: str,
                query: Optional[dict] = None) -> tuple[bytes, bytes]:
        """(content-type, body) for ``path``; raises on handler bugs."""
        if path in ("/metrics", "/"):
            return (b"text/plain; version=0.0.4; charset=utf-8",
                    render_text(self.registries).encode())
        route = self.json_routes.get(path)
        if route is None:
            raise KeyError(path)
        import inspect
        try:
            takes_query = bool(inspect.signature(route).parameters)
        except (TypeError, ValueError):
            takes_query = False
        payload = route(query or {}) if takes_query else route()
        return (b"application/json",
                json.dumps(payload, default=str).encode())

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10.0)
            # drain headers
            while True:
                line = await asyncio.wait_for(reader.readline(), 10.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request_line.decode("latin-1").split()
            target = parts[1] if len(parts) >= 2 else "/"
            path, _, qs = target.partition("?")
            from urllib.parse import parse_qs
            query = parse_qs(qs) if qs else {}
            try:
                ctype, body = self._render(path, query)
            except KeyError:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\nConnection: close\r\n\r\n")
            except Exception:
                # a rendering bug must be loud (the endpoint is how
                # operators see the server) and still answer HTTP
                LOG.warning("metrics endpoint: render failed for %s", path,
                            exc_info=True)
                writer.write(b"HTTP/1.1 500 Internal Server Error\r\n"
                             b"Content-Length: 0\r\n"
                             b"Connection: close\r\n\r\n")
            else:
                head = (b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: " + ctype +
                        b"\r\nContent-Length: " + str(len(body)).encode() +
                        b"\r\nConnection: close\r\n\r\n")
                writer.write(head + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        except Exception:
            # e.g. LimitOverrunError/ValueError from an oversized header
            # line: never let a bad scraper leak task exceptions
            LOG.debug("metrics endpoint: bad request", exc_info=True)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass
