"""Prometheus text exposition for the metrics registries.

Reference analog: ratis-metrics exposes dropwizard registries through
reporters (console/JMX, ratis-metrics-default); operators today scrape
Prometheus, so this renders every registry in
:class:`~ratis_tpu.metrics.registry.MetricRegistries` in text exposition
format 0.0.4 and (optionally) serves it over a tiny dependency-free
asyncio HTTP endpoint at ``/metrics``.

Naming: ``ratis_<component>_<metric>`` with the registry prefix (the group
member id) as a ``member`` label, e.g.::

    ratis_server_numRequests{member="s0@group-1234"} 42
    ratis_log_worker_flushTime_seconds{member="...",quantile="0.99"} 0.003

Timers emit count/total plus p50/p99 quantile samples from their bounded
reservoir (the dropwizard histogram analog).
"""

from __future__ import annotations

import asyncio
import logging
import re
from typing import Optional

from ratis_tpu.metrics.registry import MetricRegistries

LOG = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _escape_label(value: str) -> str:
    return value.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def render_text(registries: Optional[MetricRegistries] = None) -> str:
    """All registries in Prometheus text exposition format."""
    regs = registries or MetricRegistries.global_registries()
    lines: list[str] = []
    seen_types: set[str] = set()
    for info in regs.get_registry_infos():
        reg = regs.get(info)
        if reg is None:
            continue
        member = _escape_label(info.prefix)
        base = f"{_sanitize(info.application)}_{_sanitize(info.component)}"
        for metric, value in sorted(reg.snapshot().items()):
            mname = f"{base}_{_sanitize(metric)}"
            if isinstance(value, dict) and "p50_s" in value:
                # a Timekeeper snapshot (count/mean_s/max_s/p50_s/p99_s)
                if mname not in seen_types:
                    lines.append(f"# TYPE {mname}_seconds summary")
                    seen_types.add(mname)
                count = value.get("count", 0)
                total = value.get("mean_s", 0.0) * count
                lines.append(f'{mname}_seconds_count{{member="{member}"}} '
                             f'{count}')
                lines.append(f'{mname}_seconds_sum{{member="{member}"}} '
                             f'{_fmt(total)}')
                for key, q in (("p50_s", "0.5"), ("p99_s", "0.99")):
                    if key in value:
                        lines.append(
                            f'{mname}_seconds{{member="{member}",'
                            f'quantile="{q}"}} {_fmt(value[key])}')
            elif isinstance(value, dict):
                # structured gauge (e.g. the commitInfos index map): flatten
                # numeric sub-keys into per-key gauges
                for sub, sval in sorted(value.items()):
                    num = _as_number(sval)
                    if num is None:
                        continue
                    sub_name = f"{mname}_{_sanitize(str(sub))}"
                    if sub_name not in seen_types:
                        lines.append(f"# TYPE {sub_name} gauge")
                        seen_types.add(sub_name)
                    lines.append(
                        f'{sub_name}{{member="{member}"}} {_fmt(num)}')
            else:
                num = _as_number(value)
                if num is None:
                    continue  # non-numeric gauge (e.g. an error string)
                if mname not in seen_types:
                    kind = "counter" if metric.lower().endswith(
                        ("count", "total")) else "gauge"
                    lines.append(f"# TYPE {mname} {kind}")
                    seen_types.add(mname)
                lines.append(f'{mname}{{member="{member}"}} {_fmt(num)}')
    return "\n".join(lines) + "\n"


def _fmt(num: float) -> str:
    """Full-precision rendering: integers verbatim (a counter past 1e9 must
    not collapse to 1e+09 and stall rate() queries), floats via repr."""
    if isinstance(num, int) or (isinstance(num, float) and num.is_integer()):
        return str(int(num))
    return repr(float(num))


def _as_number(value) -> Optional[float]:
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        return float(value)
    return None


class MetricsHttpServer:
    """Minimal asyncio HTTP scrape endpoint: GET /metrics.

    Dependency-free on purpose (the environment bakes no prometheus
    client); the exposition format is line-oriented text, so a tiny
    handwritten responder is all a scraper needs."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registries: Optional[MetricRegistries] = None):
        self.host = host
        self.port = port
        self.registries = registries
        self._server: Optional[asyncio.AbstractServer] = None
        self.bound_port: Optional[int] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.bound_port = self._server.sockets[0].getsockname()[1]
        LOG.info("metrics endpoint on http://%s:%d/metrics",
                 self.host, self.bound_port)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10.0)
            # drain headers
            while True:
                line = await asyncio.wait_for(reader.readline(), 10.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request_line.decode("latin-1").split()
            path = parts[1] if len(parts) >= 2 else "/"
            if path.split("?")[0] in ("/metrics", "/"):
                try:
                    body = render_text(self.registries).encode()
                except Exception:
                    # a rendering bug must be loud (the endpoint is how
                    # operators see the server) and still answer HTTP
                    LOG.warning("metrics endpoint: render failed",
                                exc_info=True)
                    writer.write(b"HTTP/1.1 500 Internal Server Error\r\n"
                                 b"Content-Length: 0\r\n"
                                 b"Connection: close\r\n\r\n")
                else:
                    head = (b"HTTP/1.1 200 OK\r\n"
                            b"Content-Type: text/plain; version=0.0.4; "
                            b"charset=utf-8\r\n"
                            b"Content-Length: " + str(len(body)).encode() +
                            b"\r\nConnection: close\r\n\r\n")
                    writer.write(head + body)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\nConnection: close\r\n\r\n")
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        except Exception:
            # e.g. LimitOverrunError/ValueError from an oversized header
            # line: never let a bad scraper leak task exceptions
            LOG.debug("metrics endpoint: bad request", exc_info=True)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass
