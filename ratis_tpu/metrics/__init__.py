"""Metrics subsystem (reference ratis-metrics-api / ratis-metrics-default).

Registry core in :mod:`ratis_tpu.metrics.registry`; per-division facades in
:mod:`ratis_tpu.metrics.server_metrics`; a periodic console reporter in
:func:`start_console_reporter` (MetricsReporting.java:34-61 analog).
"""

from __future__ import annotations

import asyncio
import json
import sys

from ratis_tpu.metrics.registry import (Counter, Histogram,
                                        MetricRegistries,
                                        MetricRegistryInfo,
                                        RatisMetricRegistry, Timekeeper,
                                        labeled)
from ratis_tpu.metrics.server_metrics import (DataStreamMetrics,
                                              LeaderElectionMetrics,
                                              LogAppenderMetrics,
                                              LogWorkerMetrics,
                                              RaftServerMetrics,
                                              SegmentedRaftLogMetrics,
                                              SharedLogMetrics,
                                              StateMachineMetrics)

__all__ = [
    "Counter", "Histogram", "labeled", "MetricRegistries",
    "MetricRegistryInfo",
    "RatisMetricRegistry", "Timekeeper", "RaftServerMetrics",
    "LeaderElectionMetrics", "SegmentedRaftLogMetrics", "LogWorkerMetrics",
    "SharedLogMetrics", "LogAppenderMetrics", "StateMachineMetrics",
    "DataStreamMetrics", "start_console_reporter",
]


def start_console_reporter(period_s: float = 60.0,
                           stream=None) -> asyncio.Task:
    """Periodically dump every registry snapshot as JSON lines
    (console-reporter analog; cancel the returned task to stop)."""
    out = stream or sys.stderr

    async def _report_loop():
        regs = MetricRegistries.global_registries()
        while True:
            await asyncio.sleep(period_s)
            print(json.dumps(regs.snapshot_all(), default=str), file=out)

    return asyncio.create_task(_report_loop(), name="metrics-reporter")
