"""Metrics registry: counters, gauges, timers with 3-level naming.

Capability parity with the reference metrics SPI
(ratis-metrics-api/src/main/java/org/apache/ratis/metrics/):
``MetricRegistryInfo`` (app/component/name 3-level naming),
``RatisMetricRegistry`` (counter/gauge/timer accessors),
``Timekeeper`` (timer contexts), and the ``MetricRegistries`` process-global
singleton that creates/removes registries and serves reporters (the
reference discovers the implementation via ServiceLoader,
MetricRegistries.java; here the in-process implementation is direct).

TPU-first note: metrics are plain host-side Python — they observe the
asyncio runtime and kernel-dispatch cadence, never device code.  Timers
keep a bounded reservoir so p50/p99 snapshots are O(1) memory, matching
what the dropwizard histogram gives the reference.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Iterable, Optional


@dataclasses.dataclass(frozen=True)
class MetricRegistryInfo:
    """3-level metric naming (MetricRegistryInfo.java): app.component.name."""

    prefix: str          # e.g. a group-member id ("s0@group-1234")
    application: str     # "ratis"
    component: str       # "server", "log_worker", "leader_election", ...
    name: str            # metrics class name

    @property
    def full_name(self) -> str:
        return ".".join((self.application, self.component, self.prefix,
                         self.name))


class Counter:
    """Monotonic (but resettable) counter.

    Lock-free on purpose: hot paths (append handling, apply loop) inc these
    thousands of times per second from the event loop, and profiling at
    1024 groups showed a per-inc Lock costing ~5% of total runtime.  A
    bare ``+=`` is GIL-coherent; the worst cross-thread race loses an
    occasional increment, which is an accepted trade for observability
    counters (the reference's dropwizard LongAdder makes the same
    accuracy-for-speed trade in reverse)."""

    __slots__ = ("_value",)

    def __init__(self) -> None:
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    def dec(self, n: int = 1) -> None:
        self._value -= n

    @property
    def count(self) -> int:
        return self._value


def labeled(name: str, **labels: str) -> str:
    """Canonical registry name for a labeled metric: ``name{k="v",...}``
    with keys sorted.  The Prometheus renderer splits this form back into
    base name + label set and merges the registry's ``member`` label in."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Timekeeper:
    """Timer with count/total and a bounded reservoir for percentiles
    (reference Timekeeper + dropwizard Timer)."""

    RESERVOIR = 512

    def __init__(self) -> None:
        self._count = 0
        self._total_s = 0.0
        self._max_s = 0.0
        self._samples: list[float] = []

    class Context:
        __slots__ = ("_timer", "_start")

        def __init__(self, timer: "Timekeeper") -> None:
            self._timer = timer
            self._start = time.perf_counter()

        def stop(self) -> float:
            elapsed = time.perf_counter() - self._start
            self._timer.update(elapsed)
            return elapsed

        def __enter__(self) -> "Timekeeper.Context":
            return self

        def __exit__(self, *exc) -> None:
            self.stop()

    def time(self) -> "Timekeeper.Context":
        return Timekeeper.Context(self)

    def update(self, elapsed_s: float) -> None:
        # Lock-free for the same reason as Counter.inc (hot-path cost);
        # cross-thread races at worst skew the bounded reservoir slightly.
        self._count += 1
        self._total_s += elapsed_s
        if elapsed_s > self._max_s:
            self._max_s = elapsed_s
        if len(self._samples) < self.RESERVOIR:
            self._samples.append(elapsed_s)
        else:  # Vitter's algorithm R — uniform over the stream
            import random
            j = random.randrange(self._count)
            if j < self.RESERVOIR:
                self._samples[j] = elapsed_s

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean_s(self) -> float:
        return self._total_s / self._count if self._count else 0.0

    def percentile_s(self, q: float) -> float:
        samples = list(self._samples)  # snapshot vs concurrent updates
        if not samples:
            return 0.0
        ordered = sorted(samples)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]

    def snapshot(self) -> dict:
        return {"count": self._count, "mean_s": self.mean_s,
                "max_s": self._max_s, "p50_s": self.percentile_s(0.50),
                "p99_s": self.percentile_s(0.99)}


class Histogram(Timekeeper):
    """Value histogram over the same bounded reservoir: batch sizes, queue
    depths — dimensionless quantities, not durations (the snapshot keys
    carry no ``_s`` suffix and the Prometheus renderer emits no unit)."""

    def snapshot(self) -> dict:
        return {"count": self._count, "mean": self.mean_s,
                "max": self._max_s, "p50": self.percentile_s(0.50),
                "p99": self.percentile_s(0.99)}


class RatisMetricRegistry:
    """One named registry of counters/gauges/timers/histograms
    (RatisMetricRegistry.java / impl/RatisMetricRegistryImpl.java)."""

    def __init__(self, info: MetricRegistryInfo) -> None:
        self.info = info
        self._counters: Dict[str, Counter] = {}
        self._timers: Dict[str, Timekeeper] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._gauges: Dict[str, Callable[[], object]] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def timer(self, name: str) -> Timekeeper:
        with self._lock:
            return self._timers.setdefault(name, Timekeeper())

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram())

    def gauge(self, name: str, supplier: Callable[[], object]) -> None:
        with self._lock:
            self._gauges[name] = supplier

    def remove(self, name: str) -> bool:
        with self._lock:
            return (self._counters.pop(name, None) is not None
                    or self._timers.pop(name, None) is not None
                    or self._histograms.pop(name, None) is not None
                    or self._gauges.pop(name, None) is not None)

    def metric_names(self) -> list[str]:
        with self._lock:
            return sorted([*self._counters, *self._timers,
                           *self._histograms, *self._gauges])

    def snapshot(self) -> dict:
        """Flat {metric: value} view (console/JMX reporter analog)."""
        return {name: value for name, (_kind, value)
                in self.typed_snapshot().items()}

    def typed_snapshot(self) -> dict:
        """{metric: (kind, value)} where kind is one of counter/timer/
        histogram/gauge — the Prometheus renderer needs the kind (counters
        get the ``_total`` suffix, histogram quantiles carry no unit)."""
        out: dict = {}
        with self._lock:
            counters = dict(self._counters)
            timers = dict(self._timers)
            histograms = dict(self._histograms)
            gauges = dict(self._gauges)
        for name, c in counters.items():
            out[name] = ("counter", c.count)
        for name, t in timers.items():
            out[name] = ("timer", t.snapshot())
        for name, h in histograms.items():
            out[name] = ("histogram", h.snapshot())
        for name, g in gauges.items():
            try:
                out[name] = ("gauge", g())
            except Exception as e:  # gauge suppliers must never break reports
                out[name] = ("gauge", f"<error: {e}>")
        return out


class MetricRegistries:
    """Process-global registry-of-registries (MetricRegistries.global())."""

    _global: Optional["MetricRegistries"] = None
    _global_lock = threading.Lock()

    def __init__(self) -> None:
        self._registries: Dict[MetricRegistryInfo, RatisMetricRegistry] = {}
        self._lock = threading.Lock()
        self._reporters: list[Callable[[RatisMetricRegistry], None]] = []
        self._stop_reporters: list[Callable[[RatisMetricRegistry], None]] = []

    @classmethod
    def global_registries(cls) -> "MetricRegistries":
        with cls._global_lock:
            if cls._global is None:
                cls._global = MetricRegistries()
            return cls._global

    def create(self, info: MetricRegistryInfo) -> RatisMetricRegistry:
        with self._lock:
            reg = self._registries.get(info)
            if reg is None:
                reg = RatisMetricRegistry(info)
                self._registries[info] = reg
                for reporter in self._reporters:
                    reporter(reg)
            return reg

    def remove(self, info: MetricRegistryInfo) -> bool:
        with self._lock:
            reg = self._registries.pop(info, None)
            if reg is not None:
                for stop in self._stop_reporters:
                    stop(reg)
            return reg is not None

    def get(self, info: MetricRegistryInfo) -> Optional[RatisMetricRegistry]:
        with self._lock:
            return self._registries.get(info)

    def get_registry_infos(self) -> Iterable[MetricRegistryInfo]:
        with self._lock:
            return list(self._registries)

    def add_reporter_registration(
            self, reporter: Callable[[RatisMetricRegistry], None],
            stop_reporter: Callable[[RatisMetricRegistry], None]) -> None:
        with self._lock:
            self._reporters.append(reporter)
            self._stop_reporters.append(stop_reporter)

    def clear(self) -> None:
        with self._lock:
            self._registries.clear()

    def snapshot_all(self) -> dict:
        with self._lock:
            regs = dict(self._registries)
        return {info.full_name: reg.snapshot() for info, reg in regs.items()}
