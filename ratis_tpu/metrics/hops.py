"""Scheduling-hop accounting for the replication/reply plane.

The round-6/7 traced decomposition located the commit wall in event-loop
scheduling hops (`server.route`/`server.reply`/`server.respond`, ~100µs
each under load), not in serialization (docs/perf.md).  The round-8
batching work collapses those hops; this module makes the collapse a
standing measured artifact instead of a one-off trace read: every site
the batching targets counts the scheduling operations it issues, and
``hops-per-commit`` (reply-plane hops / engine commit advances) rides the
bench line (``secondary.obs``) and the per-server registry.

Process-wide by design, like :data:`ratis_tpu.trace.tracer.TRACER` and
the codec's ``FANOUT_STATS``: co-hosted servers in one process share the
counters, and the bench's cluster-wide hops line up with its
cluster-wide commit count.  Sites:

- ``sender_wake``  — a PeerSender flush-loop wakeup (legacy) or one armed
  cross-group sweep pass (sweep mode) in the replication scheduler.
- ``engine_wake``  — an engine tick wake actually scheduled
  (``call_soon_threadsafe`` issued / event set); the intake-lock dedupe
  collapses ack bursts to one.
- ``reply_future`` — one per-request pending-reply future resolution
  waking the parked write-handler task (the legacy commit->reply wakeup
  the waterline fan-out removes).
- ``reply_window`` — one per-request ordered-window future resolution
  carrying a real reply (second wakeup of the legacy chain; absent when
  the client skips the sliding window).
- ``reply_send``   — one per-request reply handed to the transport's
  per-request send/drain path (the handler task suspends for the
  flush/drain; third wakeup of the legacy chain on socket transports).
- ``reply_flush``  — one per-connection reply-drain callback armed by
  the transport's deferred-reply batcher (sweep mode's replacement for
  ALL of the above: one scheduled callback per connection per burst).
- ``reply_batch``  — one waterline fan-out pass resolving a whole batch
  of committed requests.  NOT a hop (the pass is a synchronous call the
  apply loop was running anyway); counted for batch-size observability
  (deliveries / passes = the average fan-out batch).

The reply-plane metric counts the SCHEDULED operations between a commit
advancing and its reply reaching the wire; the final client-waiter
wakeup (transport reply hand-back) exists identically in both modes and
is excluded as common cost, as is the connection coalescer's flush task
(identical per-batch cost both modes).
"""

from __future__ import annotations

HOP_SITES = ("sender_wake", "engine_wake", "reply_future", "reply_window",
             "reply_send", "reply_batch", "reply_flush")

# reply-plane subset: the SCHEDULED hops between a commit advancing and
# its reply reaching the transport — the surface the fan-out collapse
# targets (reply_batch is a synchronous pass, not a hop; see above)
REPLY_SITES = ("reply_future", "reply_window", "reply_send", "reply_flush")

_counts: dict[str, int] = {s: 0 for s in HOP_SITES}


def hop(site: str) -> None:
    """Count one scheduling operation at ``site`` (hot path: one dict
    increment; sites are fixed, an unknown site is a programming error)."""
    _counts[site] += 1


def snapshot() -> dict[str, int]:
    return dict(_counts)


def reply_plane_hops() -> int:
    return sum(_counts[s] for s in REPLY_SITES)


def total_hops() -> int:
    return sum(_counts.values())


def reset() -> None:
    for s in HOP_SITES:
        _counts[s] = 0
