"""Cross-process metrics aggregation for the observability plane.

PR 3 split the bench cluster into one process per peer; each child owns
its own metric registries, tracer rings, and engine counters, and nothing
merged them back into one cluster view.  This module is the merge point:
a dependency-free async HTTP scraper for the per-server introspection
endpoint (:class:`~ratis_tpu.metrics.prometheus.MetricsHttpServer`) and a
snapshot merger that folds every child's scrape into ONE cluster snapshot
— per-process summaries keyed by pid plus cluster-wide totals.  The
multi-process bench embeds the merged snapshot in its per-process
decomposition report; ``python -m ratis_tpu.shell health`` pretty-prints
the same scrapes for an operator.
"""

from __future__ import annotations

import asyncio
import re
from typing import Optional

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def _split_address(address: str) -> tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host or "127.0.0.1", int(port)


async def http_get(address: str, path: str, timeout_s: float = 10.0) -> bytes:
    """Tiny HTTP/1.1 GET against the introspection endpoint (close-delim
    bodies; the endpoint always sends Connection: close)."""
    host, port = _split_address(address)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout_s)
    try:
        writer.write(f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                     f"Connection: close\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout_s)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    status = status_line.split()
    if len(status) < 2 or status[1] != b"200":
        raise RuntimeError(f"GET {address}{path}: "
                           f"{status_line.decode('latin-1', 'replace')}")
    return body


async def fetch_json(address: str, path: str,
                     timeout_s: float = 10.0) -> object:
    import json
    return json.loads(await http_get(address, path, timeout_s))


async def fetch_text(address: str, path: str,
                     timeout_s: float = 10.0) -> str:
    return (await http_get(address, path, timeout_s)).decode()


def parse_prometheus_text(text: str) -> dict:
    """{'name{labels}': float} over every sample line (TYPE/HELP lines
    skipped) — enough structure to merge counters across processes."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group(1) + (m.group(2) or "")
        try:
            out[name] = float(m.group(3))
        except ValueError:
            continue
    return out


async def scrape_server(address: str, timeout_s: float = 10.0) -> dict:
    """One child's full introspection scrape: health + divisions + events
    + parsed /metrics samples, plus the scrape address for re-scraping.

    Partial-failure tolerant: each route is fetched independently
    (an earlier bare ``asyncio.gather`` let ONE failing route poison the
    whole server's scrape).  A route that fails lands in ``errors``
    with empty data of the right shape; only when EVERY route fails —
    the endpoint is actually dead — does the scrape raise, so
    :func:`scrape_cluster` classifies the server unreachable."""
    results = await asyncio.gather(
        fetch_json(address, "/health", timeout_s),
        fetch_json(address, "/divisions", timeout_s),
        fetch_json(address, "/events", timeout_s),
        fetch_text(address, "/metrics", timeout_s),
        return_exceptions=True)
    paths = ("/health", "/divisions", "/events", "/metrics")
    empties = ({}, [], {}, "")
    errors = {}
    clean = []
    for path, res, empty in zip(paths, results, empties):
        if isinstance(res, BaseException):
            errors[path] = str(res) or type(res).__name__
            clean.append(empty)
        else:
            clean.append(res)
    if len(errors) == len(paths):
        raise RuntimeError(f"all routes failed: {errors['/health']}")
    health, divisions, events, metrics_text = clean
    out = {
        "address": address,
        "health": health,
        "divisions": divisions,
        "events": events,
        "metrics": parse_prometheus_text(metrics_text),
    }
    if errors:
        out["errors"] = errors
    return out


def _summarize_proc(scrape: dict) -> dict:
    """Compact per-process block of a merged snapshot (the full division
    list and raw samples stay out of the bench artifact)."""
    health = scrape.get("health", {})
    divisions = scrape.get("divisions", [])
    events = scrape.get("events", {})
    roles: dict = {}
    lag_max = 0
    pending = 0
    for d in divisions:
        roles[d.get("role", "?")] = roles.get(d.get("role", "?"), 0) + 1
        pending += d.get("pendingRequests", 0)
        for f in (d.get("followers") or {}).values():
            lag_max = max(lag_max, f.get("lag", 0))
    metrics = scrape.get("metrics", {})

    def g(name: str, default=0.0):
        return metrics.get(name, default)

    chaos = health.get("chaos") or {}
    active_faults = (len(chaos.get("activeLinkFaults", []))
                     + len(chaos.get("activeInjections", [])))
    return {
        "chaosActiveFaults": active_faults,
        "chaosInjections": chaos.get("activeInjections", []),
        "address": scrape.get("address"),
        # a half-dead server (some routes down) keeps its address as the
        # display name and reads degraded, never "ok"
        "peer": health.get("peer") or scrape.get("address"),
        "status": ("degraded" if scrape.get("errors")
                   else health.get("status")),
        "routeErrors": scrape.get("errors") or {},
        "divisions": len(divisions),
        "roles": roles,
        "pendingRequests": pending,
        "followerLagMax": lag_max,
        "engineTicks": (health.get("engine") or {}).get("ticks", 0),
        "laneOccupancyGroups": g("ratis_engine_laneOccupancyGroups"),
        "watchdogEvents": events.get("count", 0),
        "eventKinds": sorted({e.get("kind")
                              for e in events.get("events", [])}),
        "shedRequests": (health.get("serving") or {}).get("shedTotal", 0),
    }


def merge_cluster_snapshot(scrapes: list[dict]) -> dict:
    """Fold per-child scrapes into one cluster snapshot: a per-pid
    summary map + cluster totals (counter families summed across
    processes, gauges left per-process)."""
    procs: dict = {}
    totals: dict = {}
    events = 0
    unhealthy = []
    for scrape in scrapes:
        health = scrape.get("health", {})
        pid = str(health.get("pid", f"unknown-{len(procs)}"))
        if pid in procs:
            # co-hosted servers share a pid (in-process clusters): keep
            # every server visible instead of last-writer-wins
            pid = f"{pid}:{health.get('peer')}"
        procs[pid] = _summarize_proc(scrape)
        if procs[pid]["status"] != "ok":
            unhealthy.append(procs[pid]["peer"])
        events += procs[pid]["watchdogEvents"]
        for name, value in scrape.get("metrics", {}).items():
            if name.split("{", 1)[0].endswith("_total"):
                totals[name.split("{", 1)[0]] = \
                    totals.get(name.split("{", 1)[0], 0.0) + value
    return {
        "procs": procs,
        "servers": len(scrapes),
        "healthy": len(scrapes) - len(unhealthy),
        "unhealthy_peers": unhealthy,
        "watchdog_events": events,
        "counter_totals": {k: totals[k] for k in sorted(totals)},
    }


async def scrape_cluster(addresses: list[str],
                         timeout_s: float = 10.0) -> dict:
    """Scrape every address concurrently and merge; a dead endpoint
    becomes an ``unreachable`` proc entry instead of failing the merge
    (the parent must report a half-dead cluster, not crash on it)."""
    results = await asyncio.gather(
        *(scrape_server(a, timeout_s) for a in addresses),
        return_exceptions=True)
    scrapes = []
    unreachable = []
    for address, res in zip(addresses, results):
        if isinstance(res, BaseException):
            # e.g. asyncio.TimeoutError stringifies empty: keep the type
            unreachable.append({"address": address,
                                "error": str(res) or type(res).__name__})
        else:
            scrapes.append(res)
    merged = merge_cluster_snapshot(scrapes)
    if unreachable:
        merged["unreachable"] = unreachable
    return merged


# ------------------------------------------------- continuous telemetry

def merge_timeseries(payloads: list[dict]) -> dict:
    """Fold per-process ``/timeseries`` payloads into one pid-keyed view
    (the way chrome traces already merge): per-pid latest sample + series
    length, cluster-wide rates as the element-wise sum of each process's
    newest sample, and the log2 latency buckets summed across processes
    (the bucket encoding exists exactly so this merge is a plain add)."""
    procs: dict = {}
    rate_totals: dict = {}
    lat_buckets: dict = {}
    lat_total = 0
    for p in payloads:
        pid = str(p.get("pid", f"unknown-{len(procs)}"))
        if pid in procs:  # co-hosted servers share a pid
            pid = f"{pid}:{p.get('peer')}"
        samples = p.get("samples", [])
        last = samples[-1] if samples else {}
        procs[pid] = {
            "peer": p.get("peer"),
            "seq": p.get("seq", -1),
            "count": len(samples),
            "interval_s": p.get("interval_s"),
            "last": last,
        }
        for k, v in (last.get("rates") or {}).items():
            rate_totals[k] = round(rate_totals.get(k, 0.0) + v, 3)
        lat = p.get("latency") or {}
        lat_total += lat.get("count", 0)
        for b, c in (lat.get("buckets") or {}).items():
            lat_buckets[b] = lat_buckets.get(b, 0) + c
    return {"procs": procs, "rates": rate_totals,
            "latency": {"count": lat_total, "buckets": lat_buckets}}


def merge_hotgroups(payloads: list[dict], n: int = 16) -> dict:
    """Fold per-process ``/hotgroups`` payloads into one cluster top-n:
    per-group commits/err/pending summed across processes (each process
    accounts its own replicas; the leader's commits dominate), ranked by
    merged commit count."""
    by_group: dict = {}
    total = 0
    for p in payloads:
        total += p.get("total_commits", 0)
        for g in p.get("groups", []):
            e = by_group.setdefault(g["group"],
                                    {"commits": 0, "err": 0, "pending": 0})
            e["commits"] += g.get("commits", 0)
            e["err"] += g.get("err", 0)
            e["pending"] += g.get("pending", 0)
    ranked = sorted(by_group.items(), key=lambda kv: -kv[1]["commits"])[:n]
    return {
        "total_commits": total,
        "groups": [{"group": k, **v,
                    "share": round(v["commits"] / max(1, total), 4),
                    "share_min": round(
                        max(0, v["commits"] - v["err"]) / max(1, total), 4)}
                   for k, v in ranked],
    }


async def scrape_cluster_timeseries(addresses: list[str],
                                    timeout_s: float = 10.0,
                                    since: "dict | None" = None) -> dict:
    """Scrape ``/timeseries`` + ``/hotgroups`` from every address and
    merge (``since``: address -> last-seen seq for incremental polls).
    Unreachable or telemetry-less endpoints degrade to an
    ``unreachable`` entry, never an exception — same contract as
    :func:`scrape_cluster`."""
    async def one(addr: str):
        path = "/timeseries"
        if since and since.get(addr) is not None:
            path += f"?since={since[addr]}"
        ts = await fetch_json(addr, path, timeout_s)
        hot = await fetch_json(addr, "/hotgroups", timeout_s)
        return ts, hot

    results = await asyncio.gather(*(one(a) for a in addresses),
                                   return_exceptions=True)
    ts_payloads, hot_payloads, unreachable = [], [], []
    addr_of: dict = {}
    for addr, res in zip(addresses, results):
        if isinstance(res, BaseException):
            unreachable.append({"address": addr,
                                "error": str(res) or type(res).__name__})
            continue
        ts, hot = res
        addr_of[str(ts.get("pid"))] = addr
        ts_payloads.append(ts)
        hot_payloads.append(hot)
    merged = merge_timeseries(ts_payloads)
    merged["hotgroups"] = merge_hotgroups(hot_payloads)
    merged["addresses"] = addr_of
    if unreachable:
        merged["unreachable"] = unreachable
    return merged


async def scrape_cluster_lag(addresses: list[str],
                             timeout_s: float = 10.0) -> dict:
    """Scrape ``GET /lag`` from every address: one ledger payload per
    live server (`shell lag` renders the peers x servers heatmap from
    them), unreachable endpoints degrade to an ``unreachable`` entry —
    same contract as :func:`scrape_cluster`."""
    results = await asyncio.gather(
        *(fetch_json(a, "/lag", timeout_s) for a in addresses),
        return_exceptions=True)
    servers, unreachable = [], []
    for addr, res in zip(addresses, results):
        if isinstance(res, BaseException):
            unreachable.append({"address": addr,
                                "error": str(res) or type(res).__name__})
            continue
        res["address"] = addr
        servers.append(res)
    out = {"servers": servers}
    if unreachable:
        out["unreachable"] = unreachable
    return out
