"""Cluster flight recorder: the last window of telemetry, dumped on
failure.

A stall, a chaos failure, or a bench regression used to leave only the
final snapshot — the history that explains it was gone by the time
anyone looked.  The flight recorder pairs with the telemetry sampler
(:mod:`ratis_tpu.metrics.timeseries`): its artifact is the last N
seconds of samples + the stall watchdog's journal (with monotonic
``seq`` ids) + recent trace spans (when the host-path tracer is on) +
the hot-group sketch, serialized as one replayable JSON document.

Dump triggers (all wired by :class:`~ratis_tpu.server.server.RaftServer`
and the chaos runner):

- **watchdog degradation**: any organic detection (commit-stall,
  election-churn, follower-lag, stuck-lane) dumps once per episode
  (debounced — a stall that journals five kinds of fallout must not
  write five artifacts);
- **chaos scenario failure**: the scenario runner attaches every live
  server's flight snapshot to the existing (seed, scenario, journal)
  replay artifact;
- **SIGTERM**: a terminating server writes its final window so a kill
  during an incident preserves the incident;
- **explicit request**: ``GET /flightrecorder`` serves the same payload
  over the introspection endpoint (``?dump=1`` also writes the file).

Artifacts only write when ``raft.tpu.telemetry.flight-dir`` is set; the
HTTP route serves regardless (telemetry on is the only requirement).
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import signal
import time
from typing import Optional

LOG = logging.getLogger(__name__)

ARTIFACT_VERSION = 1
# recent trace spans attached per dump: newest per stage, bounded so a
# 4096-deep ring cannot balloon the artifact
SPANS_PER_STAGE = 64


def _recent_spans(limit_per_stage: int = SPANS_PER_STAGE) -> list[dict]:
    """Newest spans per stage from the process tracer (empty when
    tracing is off) as JSON-safe rows."""
    from ratis_tpu.trace import get_tracer
    from ratis_tpu.trace.tracer import STAGE_NAMES
    tracer = get_tracer()
    if not tracer.enabled:
        return []
    by_stage: dict = {}
    for tid, stage, t0, dur, tag, _origin in tracer.snapshot():
        by_stage.setdefault(stage, []).append((t0, tid, dur, tag))
    out = []
    for stage, rows in sorted(by_stage.items()):
        for t0, tid, dur, tag in sorted(rows)[-limit_per_stage:]:
            out.append({"stage": STAGE_NAMES[stage], "trace_id": tid,
                        "t0_ns": t0, "dur_ns": dur, "tag": tag})
    return out


class FlightRecorder:
    """One per telemetry-enabled server."""

    def __init__(self, server, sampler, dump_dir: str = "",
                 min_dump_interval_s: float = 10.0):
        self.server = server
        self.sampler = sampler
        self.dump_dir = dump_dir
        self.min_dump_interval_s = min_dump_interval_s
        self._last_dump_mono: Optional[float] = None
        self.dumps = sampler.registry.counter("flightDumps")

    # ------------------------------------------------------------- payload

    def snapshot(self, reason: str) -> dict:
        """The full flight artifact as a JSON-safe dict."""
        watchdog = self.server.watchdog
        return {
            "version": ARTIFACT_VERSION,
            "reason": reason,
            "t": round(time.time(), 3),
            "peer": str(self.server.peer_id),
            "pid": os.getpid(),
            "interval_s": self.sampler.interval_s,
            "window_s": self.sampler.window_s,
            "samples": list(self.sampler.samples),
            "events": (watchdog.events() if watchdog is not None else []),
            "hot_groups": self.sampler.hotgroups_info(),
            "lag_ledger": self._lag_block(),
            "spans": _recent_spans(),
        }

    def _lag_block(self) -> Optional[dict]:
        """The lag & health ledger at dump time (same payload as GET
        /lag); None only if the engine is mid-teardown — a flight dump
        must never fail over its own observability."""
        try:
            return self.server.lag_info()
        except Exception:
            LOG.exception("%s flight: lag ledger snapshot failed",
                          self.server.peer_id)
            return None

    def flightrecorder_info(self, query: Optional[dict] = None) -> dict:
        """``GET /flightrecorder[?dump=1]``: the live payload; with
        ``dump=1`` (and a configured flight-dir) also write the file and
        report its path."""
        snap = self.snapshot("request")
        if query and query.get("dump", ["0"])[0] not in ("0", "", "false"):
            path = self.dump("request", force=True)
            snap["dumped_to"] = str(path) if path else None
        return snap

    # --------------------------------------------------------------- dumps

    def dump(self, reason: str,
             path: "str | None" = None,
             force: bool = False) -> Optional[pathlib.Path]:
        """Write one artifact; returns its path (None when no flight-dir
        is configured and no explicit ``path`` given, or when debounced).
        ``force`` skips the debounce (SIGTERM, explicit requests)."""
        if path is None:
            if not self.dump_dir:
                return None
            now = time.monotonic()
            if (not force and self._last_dump_mono is not None
                    and now - self._last_dump_mono
                    < self.min_dump_interval_s):
                return None
            self._last_dump_mono = now
            d = pathlib.Path(self.dump_dir)
            d.mkdir(parents=True, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)
            out = d / (f"flight-{self.server.peer_id}-{safe}-"
                       f"{int(time.time() * 1e3)}.json")
        else:
            out = pathlib.Path(path)
            out.parent.mkdir(parents=True, exist_ok=True)
        try:
            out.write_text(json.dumps(self.snapshot(reason), indent=1,
                                      sort_keys=True))
        except OSError as e:
            LOG.warning("%s flight recorder: dump failed: %s",
                        self.server.peer_id, e)
            return None
        self.dumps.inc()
        LOG.warning("%s flight recorder: dumped %s artifact to %s",
                    self.server.peer_id, reason, out)
        return out

    def on_watchdog_event(self, record: dict) -> None:
        """Watchdog emit hook: organic degradations dump (debounced);
        chaos-injected fault journaling does not — the scenario runner
        attaches flight snapshots to its own artifact instead."""
        from ratis_tpu.server.watchdog import (KIND_FAULT_RECOVERED,
                                               KIND_INJECTED_FAULT)
        if record.get("kind") in (KIND_INJECTED_FAULT,
                                  KIND_FAULT_RECOVERED):
            return
        self.dump(f"watchdog-{record.get('kind', 'event')}")


# --------------------------------------------------------------- SIGTERM

_SIGTERM_RECORDERS: list = []
_SIGTERM_ARMED = False
_SIGTERM_PREV = None


def _on_sigterm(signum, frame) -> None:
    for rec in list(_SIGTERM_RECORDERS):
        try:
            rec.dump("sigterm", force=True)
        except Exception:
            LOG.exception("flight recorder: sigterm dump failed")
    prev = _SIGTERM_PREV
    if callable(prev):
        prev(signum, frame)
    else:
        # restore default disposition and re-deliver so the process
        # still terminates the way the sender asked
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def install_sigterm_dump(recorder: FlightRecorder) -> bool:
    """Register ``recorder`` for a last-gasp dump on SIGTERM.  Safe to
    call per server (one process-wide handler fans out to every
    registered recorder); returns False when handlers cannot be
    installed (non-main thread)."""
    global _SIGTERM_ARMED, _SIGTERM_PREV
    if recorder in _SIGTERM_RECORDERS:
        return True
    if not _SIGTERM_ARMED:
        try:
            _SIGTERM_PREV = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:          # not the main thread
            return False
        _SIGTERM_ARMED = True
    _SIGTERM_RECORDERS.append(recorder)
    return True


def uninstall_sigterm_dump(recorder: FlightRecorder) -> None:
    try:
        _SIGTERM_RECORDERS.remove(recorder)
    except ValueError:
        pass
