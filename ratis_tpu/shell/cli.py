"""Admin shell: the operator CLI over the client admin APIs.

Capability parity with the reference ratis-shell
(ratis-shell/src/main/java/org/apache/ratis/shell/cli/sh/RatisShell.java:60
and its command tree): ``election {transfer,stepDown,pause,resume}``,
``group {info,list}``, ``peer {add,remove,setPriority}``,
``snapshot create``, and the offline ``local raftMetaConf`` rewriter.

Usage (mirrors the reference flags):
  python -m ratis_tpu.shell election transfer -peers s0=h:p,s1=h:p -peerId s1
  python -m ratis_tpu.shell group info -peers s0=h:p,s1=h:p [-groupid UUID]
  python -m ratis_tpu.shell peer add -peers ... -peerId s3 -address h:p
  python -m ratis_tpu.shell local raftMetaConf -path <dir> -peers s0=h:p,...

``-peers`` entries are ``id=host:port`` (or bare ``host:port``, id derived
from the address like the reference's getPeerId).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import List, Optional

from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


def parse_peers(spec: str) -> List[RaftPeer]:
    peers = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            pid, _, address = part.partition("=")
        else:
            address = part
            pid = address.replace(":", "_").replace(".", "_")
        peers.append(RaftPeer(RaftPeerId.value_of(pid), address=address))
    if not peers:
        raise ValueError(f"no peers in {spec!r}")
    return peers


def _new_client(peers: List[RaftPeer], group_id: Optional[RaftGroupId]):
    from ratis_tpu.client import RaftClient
    from ratis_tpu.transport import grpc as _grpc  # noqa: F401 (registers)
    from ratis_tpu.transport.base import TransportFactory
    factory = TransportFactory.get("GRPC")
    group = RaftGroup.value_of(group_id or RaftGroupId.empty_id(), peers)
    return (RaftClient.builder()
            .set_raft_group(group)
            .set_transport(factory.new_client_transport())
            .build())


async def _resolve_group(args) -> tuple:
    """(peers, group_id): use -groupid, else ask a server for its groups
    (reference GroupListCommand-assisted default)."""
    peers = parse_peers(args.peers)
    if args.groupid:
        return peers, RaftGroupId.value_of(args.groupid)
    async with _new_client(peers, None) as probe:
        groups = await probe.group_management().group_list(peers[0].id)
    if len(groups) != 1:
        raise SystemExit(
            f"server hosts {len(groups)} groups "
            f"({', '.join(str(g) for g in groups)}); pass -groupid")
    return peers, groups[0]


def _target_peer_id(args, peers) -> RaftPeerId:
    if getattr(args, "peerId", None):
        return RaftPeerId.value_of(args.peerId)
    if getattr(args, "address", None):
        for p in peers:
            if p.address == args.address:
                return p.id
        raise SystemExit(f"address {args.address} not in -peers")
    raise SystemExit("pass -peerId or -address")


# ------------------------------------------------------------- commands

async def cmd_group_list(args) -> int:
    peers = parse_peers(args.peers)
    target = _target_peer_id(args, peers) if (args.peerId or args.address) \
        else peers[0].id
    async with _new_client(peers, None) as client:
        groups = await client.group_management().group_list(target)
    print(f"{target}: {len(groups)} group(s)")
    for gid in groups:
        print(f"  {gid.uuid}")
    return 0


async def cmd_group_info(args) -> int:
    peers, gid = await _resolve_group(args)
    async with _new_client(peers, gid) as client:
        info = await client.group_management().group_info(peers[0].id, gid)
    print(f"group id: {info.group.group_id.uuid}")
    print(f"leader: {info.leader_id or '<none>'} (term {info.term})")
    print(f"commit index: {info.commit_index}  "
          f"applied index: {info.applied_index}")
    for p in info.group.peers:
        print(f"  peer {p.id} | {p.address} | priority={p.priority}"
              f"{' | LISTENER' if p.is_listener() else ''}")
    return 0


async def cmd_election_transfer(args) -> int:
    peers, gid = await _resolve_group(args)
    target = _target_peer_id(args, peers)
    async with _new_client(peers, gid) as client:
        reply = await client.admin().transfer_leadership(
            target, timeout_ms=args.timeout * 1000.0)
    print(f"leadership transfer to {target}: "
          f"{'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def cmd_election_step_down(args) -> int:
    peers, gid = await _resolve_group(args)
    async with _new_client(peers, gid) as client:
        reply = await client.admin().transfer_leadership(None)
    print(f"step down: {'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def _election_pause_resume(args, op: str) -> int:
    peers, gid = await _resolve_group(args)
    target = _target_peer_id(args, peers)
    async with _new_client(peers, gid) as client:
        api = client.leader_election_management()
        reply = await (api.pause(target) if op == "pause"
                       else api.resume(target))
    print(f"election {op} on {target}: "
          f"{'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def cmd_peer_add(args) -> int:
    from ratis_tpu.protocol.admin import SetConfigurationMode
    peers, gid = await _resolve_group(args)
    new_peer = RaftPeer(RaftPeerId.value_of(args.peerId),
                        address=args.address)
    async with _new_client(peers, gid) as client:
        info = await client.group_management().group_info(peers[0].id, gid)
        current = [p for p in info.group.peers if not p.is_listener()]
        if any(p.id == new_peer.id for p in current):
            print(f"peer {new_peer.id} already in the group")
            return 1
        reply = await client.admin().set_configuration(
            current + [new_peer], mode=SetConfigurationMode.SET_UNCONDITIONALLY)
    print(f"peer add {new_peer.id}: "
          f"{'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def cmd_peer_remove(args) -> int:
    from ratis_tpu.protocol.admin import SetConfigurationMode
    peers, gid = await _resolve_group(args)
    victim = _target_peer_id(args, peers)
    async with _new_client(peers, gid) as client:
        info = await client.group_management().group_info(peers[0].id, gid)
        current = [p for p in info.group.peers if not p.is_listener()]
        remaining = [p for p in current if p.id != victim]
        if len(remaining) == len(current):
            print(f"peer {victim} not in the group")
            return 1
        reply = await client.admin().set_configuration(
            remaining, mode=SetConfigurationMode.SET_UNCONDITIONALLY)
    print(f"peer remove {victim}: "
          f"{'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def cmd_peer_set_priority(args) -> int:
    from ratis_tpu.protocol.admin import SetConfigurationMode
    peers, gid = await _resolve_group(args)
    updates = {}
    for spec in args.addressPriority:
        address, _, prio = spec.rpartition("|")
        updates[address] = int(prio)
    async with _new_client(peers, gid) as client:
        info = await client.group_management().group_info(peers[0].id, gid)
        new_conf = []
        for p in info.group.peers:
            if p.is_listener():
                continue
            new_conf.append(p.with_priority(updates[p.address])
                            if p.address in updates else p)
        reply = await client.admin().set_configuration(new_conf)
    print(f"setPriority: {'SUCCESS' if reply.success else reply.exception}")
    return 0 if reply.success else 1


async def cmd_snapshot_create(args) -> int:
    peers, gid = await _resolve_group(args)
    target = (_target_peer_id(args, peers)
              if (args.peerId or args.address) else None)
    async with _new_client(peers, gid) as client:
        reply = await client.snapshot_management().create(
            creation_gap=args.creationGap, server_id=target)
    if reply.success:
        print(f"snapshot created at index {reply.log_index}")
        return 0
    print(f"snapshot create failed: {reply.exception}")
    return 1


async def cmd_health(args) -> int:
    """Cluster health from the observability plane: scrape one or more
    servers' introspection endpoints (``raft.tpu.metrics.http-port``) and
    pretty-print liveness, engine freshness, per-division state, active
    chaos-injected faults, and the stall watchdog's journal.  Exit 0 =
    every endpoint reachable and ok; 1 = any endpoint degraded,
    unreachable, with journaled (organic) events, with ACTIVE injected
    faults, or with an injected-fault event whose recovery pair never
    landed.  A recovered injected fault is printed as history and does
    NOT degrade the exit status — a finished chaos campaign leaves a
    healthy cluster healthy."""
    from ratis_tpu.metrics.aggregate import scrape_cluster
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if not endpoints:
        raise SystemExit("pass -endpoints host:port[,host:port...]")
    merged = await scrape_cluster(endpoints, timeout_s=args.timeout)
    rc = 0
    procs = merged.get("procs", {})
    print(f"cluster: {merged['healthy']}/{merged['servers']} server(s) "
          f"healthy, {merged['watchdog_events']} watchdog event(s)")
    for pid, proc in sorted(procs.items()):
        roles = ", ".join(f"{n} {r}" for r, n in
                          sorted(proc.get("roles", {}).items()))
        print(f"  {proc.get('peer')} pid={pid} @{proc.get('address')}: "
              f"{proc.get('status')} | {proc.get('divisions')} division(s)"
              f"{' (' + roles + ')' if roles else ''} | "
              f"engine ticks={proc.get('engineTicks')} "
              f"occupancy={proc.get('laneOccupancyGroups'):.3f} | "
              f"pending={proc.get('pendingRequests')} "
              f"lagMax={proc.get('followerLagMax')} "
              f"shed={proc.get('shedRequests', 0)}")
        if proc.get("status") != "ok":
            rc = 1
        if proc.get("chaosActiveFaults"):
            rc = 1
            inj = proc.get("chaosInjections") or []
            print(f"    ACTIVE INJECTED FAULTS: "
                  f"{proc['chaosActiveFaults']}"
                  f"{' (injections: ' + ', '.join(inj) + ')' if inj else ''}")
    for dead in merged.get("unreachable", []):
        print(f"  UNREACHABLE {dead['address']}: {dead['error']}")
        rc = 1
    if args.verbose:
        for address in endpoints:
            from ratis_tpu.metrics.aggregate import fetch_json
            try:
                divisions = await fetch_json(address, "/divisions",
                                             args.timeout)
            except Exception:
                continue
            print(f"  divisions @{address}:")
            for d in divisions:
                fol = " ".join(
                    f"{p}:lag={f['lag']}"
                    for p, f in sorted((d.get("followers") or {}).items()))
                print(f"    {d['group']} {d['role'].lower()} "
                      f"term={d['term']} commit={d['commitIndex']} "
                      f"applied={d['lastApplied']} "
                      f"shard={d['loopShard']}"
                      f"{' | ' + fol if fol else ''}")
    all_events: list = []
    for address in endpoints:
        from ratis_tpu.metrics.aggregate import fetch_json
        try:
            events = await fetch_json(address, "/events", args.timeout)
        except Exception:
            continue
        all_events.extend((address, e) for e in events.get("events", []))
    # injected-fault / fault-recovered pairing (ratis_tpu.chaos): a fault
    # whose recovery event landed — on ANY endpoint — is campaign history,
    # not a degradation; an unrecovered one fails health like an organic
    # event does
    recovered = {e.get("fault") for _a, e in all_events
                 if e.get("kind") in ("fault-recovered", "rebalance-done")
                 and e.get("fault")}
    shown = 0
    for address, e in all_events:
        kind = e.get("kind")
        if kind in ("fault-recovered", "rebalance-done"):
            continue  # shown through its opening pair below
        if shown == 0:
            print("watchdog events:")
        shown += 1
        group = f" [{e['group']}]" if e.get("group") else ""
        if kind == "injected-fault" and e.get("fault") in recovered:
            print(f"  {address} {kind}{group} (recovered): {e['detail']}")
            continue
        # placement actuations pair rebalance with rebalance-done the way
        # chaos pairs injected-fault with fault-recovered: a converged
        # actuation is history, a dangling one degrades health
        if kind == "rebalance" and e.get("fault") in recovered:
            print(f"  {address} {kind}{group} (converged): {e['detail']}")
            continue
        rc = 1
        tag = (" UNRECOVERED" if kind == "injected-fault"
               else " UNCONVERGED" if kind == "rebalance" else "")
        print(f"  {address} {kind}{group}{tag}: {e['detail']}")
    return rc


async def cmd_top(args) -> int:
    """Live cluster view over the continuous-telemetry plane: poll every
    server's ``GET /timeseries`` (incrementally, via ``?since=``) and
    render per-process rates computed from successive counter deltas,
    plus the merged hot-group leaderboard.  ``-iterations 0`` (default)
    refreshes until interrupted; a fixed count makes it scriptable."""
    import time as _time

    from ratis_tpu.metrics.aggregate import scrape_cluster_timeseries
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if not endpoints:
        raise SystemExit("pass -endpoints host:port[,host:port...]")
    since: dict = {}
    prev: dict = {}          # pid -> (monotonic, cumulative totals)
    i = 0
    while True:
        merged = await scrape_cluster_timeseries(
            endpoints, timeout_s=args.timeout,
            since=since if since else None)
        now = _time.monotonic()
        procs = merged.get("procs", {})
        print(f"-- top @ {_time.strftime('%H:%M:%S')} | "
              f"{len(procs)} process(es) | cluster "
              + " ".join(f"{k}={v:g}"
                         for k, v in sorted(
                             merged.get("rates", {}).items())))
        print(f"{'PEER':<10} {'PID':<8} {'C/S':>9} {'ACK/S':>9} "
              f"{'REW/S':>7} {'SHED/S':>7} {'OCC':>6} {'PEND':>6} "
              f"{'LAG':>6} {'DIV':>6} {'EVT':>5}")
        for pid, proc in sorted(procs.items()):
            addr = merged.get("addresses", {}).get(pid)
            if addr is not None and proc.get("seq", -1) >= 0:
                since[addr] = proc["seq"]
            last = proc.get("last") or {}
            totals = last.get("totals") or {}
            rates = dict(last.get("rates") or {})
            p = prev.get(pid)
            if p is not None and totals:
                # rates over OUR polling window from the cumulative
                # counters each sample carries — true /timeseries deltas,
                # independent of the server-side sampling cadence
                dt = max(1e-6, now - p[0])
                for k in ("commits", "acks", "rewinds", "shed"):
                    if k in totals and k in p[1]:
                        rates[f"{k}_per_s"] = round(
                            max(0, totals[k] - p[1][k]) / dt, 1)
            if totals:
                prev[pid] = (now, totals)
            print(f"{str(proc.get('peer') or '?'):<10} {pid:<8} "
                  f"{rates.get('commits_per_s', 0):>9g} "
                  f"{rates.get('acks_per_s', 0):>9g} "
                  f"{rates.get('rewinds_per_s', 0):>7g} "
                  f"{rates.get('shed_per_s', 0):>7g} "
                  f"{last.get('occupancy', 0):>6g} "
                  f"{last.get('pending', 0):>6g} "
                  f"{last.get('lag', 0):>6g} "
                  f"{last.get('divisions', 0):>6g} "
                  f"{totals.get('events', 0):>5g}")
        hot = (merged.get("hotgroups") or {}).get("groups", [])
        if hot:
            print("hot groups: " + "  ".join(
                f"{g['group']}={g['commits']}c/{g['pending']}p"
                f"({g['share']:.0%})" for g in hot[:5]))
        for dead in merged.get("unreachable", []):
            print(f"  UNREACHABLE {dead['address']}: {dead['error']}")
        i += 1
        if args.iterations and i >= args.iterations:
            return 0
        await asyncio.sleep(args.interval)


async def cmd_lag(args) -> int:
    """Cluster lag heatmap over the lag & health ledger: scrape every
    server's ``GET /lag`` and render the peers x leaders health-score
    matrix (each row is one server's leader-side view of every follower
    peer; 1.00 = every watched link inside the lag threshold), then each
    server's worst laggard groups with their shard placement."""
    import time as _time

    from ratis_tpu.metrics.aggregate import scrape_cluster_lag
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if not endpoints:
        raise SystemExit("pass -endpoints host:port[,host:port...]")
    out = await scrape_cluster_lag(endpoints, timeout_s=args.timeout)
    servers = out.get("servers", [])
    peer_cols = sorted({p["peer"] for s in servers for p in s["peers"]})
    thr = servers[0]["lagThreshold"] if servers else "?"
    print(f"-- lag @ {_time.strftime('%H:%M:%S')} | {len(servers)} "
          f"server(s) | score = healthy share of watched links "
          f"(threshold {thr} entries; '-' = no links)")
    print(f"{'LEADER':<10} {'LEADS':>6} {'GAP':>6} "
          + " ".join(f"{c:>10}" for c in peer_cols))
    worst_lines = []
    for s in servers:
        by = {p["peer"]: p for p in s["peers"]}
        cells = []
        for name in peer_cols:
            p = by.get(name)
            cells.append("-" if p is None else f"{p['score']:.2f}")
        print(f"{str(s.get('peer') or '?'):<10} {s['leading']:>6} "
              f"{s['gapTotal']:>6} "
              + " ".join(f"{c:>10}" for c in cells))
        if s.get("groups"):
            worst_lines.append(
                f"  {s['peer']} worst: " + "  ".join(
                    f"{g['group']}[shard{g['shard']}]={g['lag']}"
                    f" via {g['peer']}" for g in s["groups"]))
    if worst_lines:
        print("laggard groups (entries behind commit):")
        for line in worst_lines:
            print(line)
    rc = 0
    for dead in out.get("unreachable", []):
        rc = 1
        print(f"  UNREACHABLE {dead['address']}: {dead['error']}")
    return rc


async def cmd_rebalance(args) -> int:
    """Placement plan over the whole fleet: scrape every server's
    ``/lag`` ``/divisions?rollup=1`` ``/health`` ``/hotgroups`` into the
    same ClusterSnapshot the in-server policy loop builds locally, run
    the same PlacementPolicy, and print the plan with reasons.

    ``--dry-run`` only prints (exit 0 = balanced, nothing to do; 2 =
    the plan has actions — scriptable as "work exists").  Without it the
    transfers are executed through the admin client (exit 0 = every
    transfer succeeded, 1 = any failed); steering and repins are
    in-server/advisory actions and are printed, never executed here."""
    from ratis_tpu.metrics.aggregate import fetch_json
    from ratis_tpu.placement import (ClusterSnapshot, PlacementPolicy,
                                     view_from_payloads)
    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if not endpoints:
        raise SystemExit("pass -endpoints host:port[,host:port...]")
    views = []
    for address in endpoints:
        payloads = {}
        for name, path in (("lag", "/lag"),
                           ("rollup", "/divisions?rollup=1"),
                           ("health", "/health"),
                           ("hotgroups", "/hotgroups")):
            try:
                payloads[name] = await fetch_json(address, path,
                                                  args.timeout)
            except Exception:
                payloads[name] = None  # telemetry-off / degraded server
        if all(v is None for v in payloads.values()):
            print(f"  UNREACHABLE {address}", file=sys.stderr)
            return 1
        views.append(view_from_payloads(**payloads))
    policy = PlacementPolicy(hot_share=args.hot_share,
                             grey_score=args.grey_score,
                             hysteresis=args.hysteresis,
                             max_transfers_per_round=args.max_transfers)
    plan = policy.plan(ClusterSnapshot(views=tuple(views)))
    print(f"placement plan over {len(views)} server(s): "
          f"imbalance={plan.imbalance:g}, "
          f"{len(plan.transfers())} transfer(s), "
          f"{len(plan.steers())} steer(s), "
          f"{len(plan.repins())} advisory repin(s)")
    for line in plan.explain():
        print(f"  {line}")
    if not plan.transfers() and not plan.steers():
        print("balanced: nothing to do")
        return 0
    if args.dry_run:
        return 2
    if not args.peers:
        raise SystemExit("executing a plan needs -peers id=host:port,...")
    peers = parse_peers(args.peers)
    async with _new_client(peers, None) as probe:
        groups = await probe.group_management().group_list(peers[0].id)
    # plan groups carry display strings (str(RaftGroupId) is not
    # parseable back) — resolve them against the server's group list
    by_display = {str(g): g for g in groups}
    rc = 0
    for t in plan.transfers():
        gid = by_display.get(t.group)
        if gid is None:
            print(f"  {t.group}: not hosted by {peers[0].id}, skipped")
            rc = 1
            continue
        async with _new_client(peers, gid) as client:
            reply = await client.admin().transfer_leadership(
                RaftPeerId.value_of(t.to_peer),
                timeout_ms=args.timeout * 1000.0)
        print(f"  TRANSFER {t.group} -> {t.to_peer}: "
              f"{'SUCCESS' if reply.success else reply.exception}")
        if not reply.success:
            rc = 1
    return rc


def cmd_local_raft_meta_conf(args) -> int:
    """Offline rewrite of raft-meta.conf to a new peer list (reference
    `local raftMetaConf`, used to resurrect a group whose quorum is gone)."""
    import pathlib

    from ratis_tpu.protocol.logentry import LogEntry, make_config_entry
    from ratis_tpu.server.storage import RaftStorageDirectory
    peers = parse_peers(args.peers)
    path = pathlib.Path(args.path)
    conf_file = path / RaftStorageDirectory.CONF_FILE
    if not conf_file.exists():
        print(f"no {RaftStorageDirectory.CONF_FILE} under {path}",
              file=sys.stderr)
        return 1
    old = LogEntry.from_bytes(conf_file.read_bytes())
    new_entry = make_config_entry(old.term, old.index + 1, peers)
    backup = conf_file.with_suffix(".conf.bak")
    backup.write_bytes(conf_file.read_bytes())
    tmp = conf_file.with_suffix(".conf.tmp")
    tmp.write_bytes(new_entry.to_bytes())
    tmp.replace(conf_file)
    print(f"rewrote {conf_file} at index {new_entry.index} with "
          f"{len(peers)} peer(s); backup at {backup}")
    return 0


# -------------------------------------------------------------- parser

def _add_common(p: argparse.ArgumentParser, group_opt: bool = True) -> None:
    p.add_argument("-peers", required=True,
                   help="comma list of id=host:port")
    if group_opt:
        p.add_argument("-groupid", default=None, help="group UUID")


def _add_target(p: argparse.ArgumentParser) -> None:
    p.add_argument("-peerId", default=None)
    p.add_argument("-address", default=None)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="ratis sh", description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="command", required=True)

    g = sub.add_parser("group").add_subparsers(dest="sub", required=True)
    p = g.add_parser("list")
    _add_common(p, group_opt=False)
    _add_target(p)
    p.set_defaults(func=cmd_group_list)
    p = g.add_parser("info")
    _add_common(p)
    p.set_defaults(func=cmd_group_info)

    e = sub.add_parser("election").add_subparsers(dest="sub", required=True)
    p = e.add_parser("transfer")
    _add_common(p)
    _add_target(p)
    p.add_argument("-timeout", type=float, default=10.0, help="seconds")
    p.set_defaults(func=cmd_election_transfer)
    p = e.add_parser("stepDown")
    _add_common(p)
    p.set_defaults(func=cmd_election_step_down)
    p = e.add_parser("pause")
    _add_common(p)
    _add_target(p)
    p.set_defaults(func=lambda a: _election_pause_resume(a, "pause"))
    p = e.add_parser("resume")
    _add_common(p)
    _add_target(p)
    p.set_defaults(func=lambda a: _election_pause_resume(a, "resume"))

    pe = sub.add_parser("peer").add_subparsers(dest="sub", required=True)
    p = pe.add_parser("add")
    _add_common(p)
    p.add_argument("-peerId", required=True)
    p.add_argument("-address", required=True)
    p.set_defaults(func=cmd_peer_add)
    p = pe.add_parser("remove")
    _add_common(p)
    _add_target(p)
    p.set_defaults(func=cmd_peer_remove)
    p = pe.add_parser("setPriority")
    _add_common(p)
    p.add_argument("-addressPriority", nargs="+", required=True,
                   metavar="host:port|priority")
    p.set_defaults(func=cmd_peer_set_priority)

    s = sub.add_parser("snapshot").add_subparsers(dest="sub", required=True)
    p = s.add_parser("create")
    _add_common(p)
    _add_target(p)
    p.add_argument("-creationGap", type=int, default=0)
    p.set_defaults(func=cmd_snapshot_create)

    p = sub.add_parser(
        "health",
        help="scrape servers' observability endpoints "
             "(raft.tpu.metrics.http-port) and print cluster health")
    p.add_argument("-endpoints", required=True,
                   help="comma list of host:port metrics endpoints")
    p.add_argument("-timeout", type=float, default=10.0, help="seconds")
    p.add_argument("-verbose", action="store_true",
                   help="also print every division's state")
    p.set_defaults(func=cmd_health)

    p = sub.add_parser(
        "top",
        help="live per-process rate view over the telemetry plane "
             "(raft.tpu.telemetry.enabled servers' GET /timeseries)")
    p.add_argument("-endpoints", required=True,
                   help="comma list of host:port metrics endpoints")
    p.add_argument("-interval", type=float, default=2.0,
                   help="refresh seconds")
    p.add_argument("-iterations", type=int, default=0,
                   help="refresh count (0 = until interrupted)")
    p.add_argument("-timeout", type=float, default=10.0, help="seconds")
    p.set_defaults(func=cmd_top)

    p = sub.add_parser(
        "lag",
        help="cluster lag heatmap over the lag & health ledger "
             "(every server's GET /lag: per-peer health scores + "
             "worst laggard groups)")
    p.add_argument("-endpoints", required=True,
                   help="comma list of host:port metrics endpoints")
    p.add_argument("-timeout", type=float, default=10.0, help="seconds")
    p.set_defaults(func=cmd_lag)

    p = sub.add_parser(
        "rebalance",
        help="compute (and optionally execute) the placement plan the "
             "in-server policy loop runs, from scraped endpoints")
    p.add_argument("-endpoints", required=True,
                   help="comma list of host:port metrics endpoints")
    p.add_argument("-peers", default=None,
                   help="comma list of id=host:port (needed to execute)")
    p.add_argument("-dry-run", "--dry-run", action="store_true",
                   dest="dry_run",
                   help="print the plan only; exit 2 when actions exist")
    p.add_argument("-hot-share", type=float, default=0.2, dest="hot_share",
                   help="share_min floor marking a group hot")
    p.add_argument("-grey-score", type=float, default=0.5,
                   dest="grey_score",
                   help="health score under which a peer is steered")
    p.add_argument("-hysteresis", type=float, default=1.0,
                   help="extra hot groups over fair share tolerated")
    p.add_argument("-max-transfers", type=int, default=2,
                   dest="max_transfers", help="transfer cap per round")
    p.add_argument("-timeout", type=float, default=10.0, help="seconds")
    p.set_defaults(func=cmd_rebalance)

    lo = sub.add_parser("local").add_subparsers(dest="sub", required=True)
    p = lo.add_parser("raftMetaConf")
    p.add_argument("-path", required=True,
                   help="the group's `current/` storage dir")
    p.add_argument("-peers", required=True)
    p.set_defaults(func=cmd_local_raft_meta_conf, sync=True)

    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    func = args.func
    if getattr(args, "sync", False):
        return func(args)
    try:
        return asyncio.run(func(args))
    except SystemExit:
        raise
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
