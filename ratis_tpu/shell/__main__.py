"""``python -m ratis_tpu.shell`` — the admin CLI entry point
(reference ratis-shell/src/main/bin + RatisShell.main:60)."""

import sys

from ratis_tpu.shell.cli import main

sys.exit(main())
