"""Send-side write coalescing for the real-socket transports.

Round-5 tracing (BENCH_r05 host_path_decomposition + docs/perf.md) put the
north-star residual in the host wire path, not in consensus: the same host
does 3205.8 commits/s over the sim transport but 1025 over TCP at 5-peer x
10240 groups.  A dominant share of that gap is the per-frame
``write() + await drain()`` pattern — every frame pays a drain await (a
task switch + flow-control check) and, under a send lock, serializes every
concurrent caller on the connection through it.

:class:`WriteCoalescer` replaces the pattern with a per-connection send
queue: frames accumulate while one buffered flush is pending, and the whole
batch goes to the transport as a single writev-style write + ONE drain.
Policy (``raft.tpu.tcp.*`` / ``raft.tpu.grpc.*`` keys, conf/keys.py):

- ``flush_bytes`` > 0: flush as soon as that many bytes are pending.
- ``flush_micros`` > 0: wait at most that long for more frames before
  flushing; 0 flushes at the *next event-loop pass*, which batches every
  frame enqueued in the current pass at zero added latency.
- both 0 (the default): coalescing OFF — each ``send`` performs the exact
  write+drain of the per-frame path, serialized, byte-identical on the
  wire (asserted in tests/test_wire_fastpath.py).

Failure contract: a flush error fails every send awaiting that batch and
POISONS the coalescer — some frames of the batch may be half-written, so
the connection is unusable and later sends fail fast; the error never
escapes into the flusher task or the event loop (a partial-batch failure
poisons the connection, not the loop).
"""

from __future__ import annotations

import asyncio
from typing import Optional

__all__ = ["WriteCoalescer"]


class WriteCoalescer:
    """Batches outbound frames into single transport flushes.

    Generic over the flush primitive: subclasses implement
    :meth:`_flush_batch` (the TCP transport joins frame bytes and performs
    one ``write+drain``; the gRPC transport packs chunks into one stream
    message).  ``max_frames`` additionally caps frames per flush (0 =
    unbounded) — the gRPC framing uses it so one stream message never
    carries an unbounded chunk list.
    """

    def __init__(self, flush_bytes: int = 0, flush_micros: int = 0,
                 max_frames: int = 0):
        self.flush_bytes = int(flush_bytes)
        self.flush_micros = int(flush_micros)
        self.max_frames = int(max_frames)
        self._pending: list = []
        self._pending_bytes = 0
        self._waiters: list[asyncio.Future] = []
        self._flusher: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._dead: Optional[Exception] = None
        self.metrics = {"flushes": 0, "frames": 0, "coalesced_frames": 0}

    @property
    def coalescing(self) -> bool:
        return self.flush_bytes > 0 or self.flush_micros > 0

    @property
    def poisoned(self) -> bool:
        return self._dead is not None

    async def _flush_batch(self, frames: list) -> None:
        raise NotImplementedError

    async def send(self, frame, nbytes: int) -> None:
        """Queue ``frame`` and return once the flush carrying it drained
        (backpressure: callers wait out the transport's flow control
        exactly as the per-frame path did)."""
        if self._dead is not None:
            raise self._dead
        if not self.coalescing:
            # the exact legacy path: one write+drain per frame, serialized
            async with self._lock:
                if self._dead is not None:
                    raise self._dead
                await self._flush_batch([frame])
                self.metrics["flushes"] += 1
                self.metrics["frames"] += 1
            return
        fut = asyncio.get_running_loop().create_future()
        self._pending.append(frame)
        self._pending_bytes += nbytes
        self._waiters.append(fut)
        if (0 < self.flush_bytes <= self._pending_bytes
                or (self.max_frames
                    and len(self._pending) >= self.max_frames)):
            await self._flush_now()
        elif self._flusher is None:
            self._flusher = asyncio.create_task(self._flush_after_delay())
        await fut

    def send_nowait(self, frame, nbytes: int) -> None:
        """Fire-and-forget enqueue: the frame joins the pending batch and
        the flusher (armed at most once per batch) writes it out on the
        next pass — REGARDLESS of the flush thresholds, so deferred-reply
        fan-out batches coalesce even on a connection configured for the
        per-frame path.  No backpressure: callers are reply producers
        whose volume is bounded by the connection's in-flight requests; a
        dead coalescer drops the frame (the connection is gone and its
        client will retry/timeout exactly as with a torn socket)."""
        if self._dead is not None:
            return
        self._pending.append(frame)
        self._pending_bytes += nbytes
        if self._flusher is None:
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_after_delay())

    async def _flush_after_delay(self) -> None:
        try:
            while self._pending and self._dead is None:
                if self.flush_micros:
                    await asyncio.sleep(self.flush_micros / 1e6)
                else:
                    await asyncio.sleep(0)  # batch the current loop pass
                await self._flush_now()
        finally:
            self._flusher = None

    async def _flush_now(self) -> None:
        async with self._lock:
            if not self._pending or self._dead is not None:
                return
            frames = self._pending
            waiters = self._waiters
            self._pending, self._waiters = [], []
            self._pending_bytes = 0
            try:
                await self._flush_batch(frames)
            except asyncio.CancelledError:
                self._poison(ConnectionError("flush cancelled mid-batch"),
                             waiters)
                raise
            except Exception as e:
                self._poison(e, waiters)
                return
            self.metrics["flushes"] += 1
            self.metrics["frames"] += len(frames)
            if len(frames) > 1:
                self.metrics["coalesced_frames"] += len(frames)
            for f in waiters:
                if not f.done():
                    f.set_result(None)

    def _poison(self, exc: Exception, waiters=()) -> None:
        if self._dead is None:
            self._dead = exc
        # abandoned waiters (caller's await was cancelled) are already done
        for f in (*waiters, *self._waiters):
            if not f.done():
                f.set_exception(exc)
        self._waiters.clear()
        self._pending.clear()
        self._pending_bytes = 0

    async def aclose(self) -> None:
        """Flush anything still pending (flush-on-close), then retire the
        flusher.  Safe on a poisoned coalescer (no-op flush)."""
        try:
            await self._flush_now()
        finally:
            t = self._flusher
            if t is not None and t is not asyncio.current_task():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
