"""TCP transport: raw-socket envelope RPC (the Netty-analog backend).

Capability parity with the reference Netty transport
(ratis-netty/src/main/java/org/apache/ratis/netty/server/NettyRpcService.java
+ NettyRpcProxy + Netty.proto:31-48): a single length-prefixed
request/reply envelope union over all RPCs — server-to-server consensus
traffic and client requests share one listening port, exactly like the
reference's RaftNettyServerRequestProto union.  asyncio streams take the
place of Netty's event loop; connections are cached per destination and
multiplex concurrent calls by a request sequence number.

Frame: u32 length | u64 call_seq | u8 kind | msgpack body.
kind: 1=server-rpc 2=client-request 3=reply 4=error-reply.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Callable, Dict, Optional

from ratis_tpu.metrics.hops import hop
from ratis_tpu.protocol.exceptions import (RaftException, TimeoutIOException,
                                           exception_from_wire,
                                           exception_to_wire)
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.raftrpc import decode_rpc, encode_rpc
from ratis_tpu.protocol.requests import (DEFERRED_REPLY, RaftClientReply,
                                         RaftClientRequest,
                                         attach_reply_sink)
from ratis_tpu.trace.tracer import (INGRESS_NS, STAGE_DECODE, STAGE_ENCODE,
                                    STAGE_RESPOND, STAGE_WIRE, TRACER)
from ratis_tpu.transport.base import (ClientRequestHandler, ClientTransport,
                                      ServerRpcHandler, ServerTransport,
                                      TransportFactory)
from ratis_tpu.transport.coalesce import WriteCoalescer

LOG = logging.getLogger(__name__)

KIND_SERVER_RPC = 1
KIND_CLIENT_REQUEST = 2
KIND_REPLY = 3
KIND_ERROR = 4

_FRAME = struct.Struct(">IQB")
MAX_FRAME = 256 << 20


def _encode_frame(call_seq: int, kind: int, body: bytes) -> bytes:
    return _FRAME.pack(9 + len(body), call_seq, kind) + body


class _StreamFrameCoalescer(WriteCoalescer):
    """WriteCoalescer over an asyncio StreamWriter: the batch goes out as
    ONE buffered write (frames are already length-prefixed, so joining is
    byte-identical to writing them one by one) followed by ONE drain."""

    def __init__(self, writer: asyncio.StreamWriter,
                 flush_bytes: int = 0, flush_micros: int = 0):
        super().__init__(flush_bytes=flush_bytes, flush_micros=flush_micros)
        self._writer = writer

    async def _flush_batch(self, frames: list) -> None:
        w = self._writer
        w.write(frames[0] if len(frames) == 1 else b"".join(frames))
        await w.drain()


def _flush_conf(properties) -> tuple[int, int]:
    """(flush_bytes, flush_micros) for the TCP transport; (0, 0) — the
    per-frame path — when unconfigured."""
    if properties is None:
        return 0, 0
    from ratis_tpu.conf.keys import WireConfigKeys
    return (WireConfigKeys.Tcp.flush_bytes(properties),
            WireConfigKeys.Tcp.flush_micros(properties))


def _defer_conf(properties) -> bool:
    """Whether client requests get a deferred-reply sink attached (the
    commit fan-out collapse, raft.tpu.replication.sweep/reply-fanout)."""
    if properties is None:
        return False
    from ratis_tpu.conf.keys import RaftServerConfigKeys
    K = RaftServerConfigKeys.Replication
    return K.sweep(properties) and K.reply_fanout(properties)


class _DeferredReplyFanout:
    """Per-connection deferred-reply batcher: the division's waterline
    fan-out calls :meth:`submit` synchronously (possibly from a shard
    loop); replies queue here and ONE armed callback per burst drains them
    into the connection's write coalescer — one scheduled hop per batch
    per connection, replacing the per-request handler-resume + send-wait
    chain the traced decomposition measured as ``server.reply`` /
    ``server.respond``."""

    __slots__ = ("_conn_out", "_loop", "_q", "_lock", "_armed")

    def __init__(self, conn_out: "_StreamFrameCoalescer",
                 loop: asyncio.AbstractEventLoop) -> None:
        import collections
        import threading
        self._conn_out = conn_out
        self._loop = loop
        self._q = collections.deque()
        self._lock = threading.Lock()
        self._armed = False

    def sink_for(self, call_seq: int, trace_id: int = 0):
        def sink(reply: RaftClientReply) -> None:
            self.submit(call_seq, reply, trace_id)
        return sink

    def submit(self, call_seq: int, reply: RaftClientReply,
               trace_id: int = 0) -> None:
        tid = trace_id if TRACER.enabled else 0
        t0 = TRACER.now() if tid else 0
        # encode on the CALLING (division) loop: serialization stays off
        # the connection's loop, which only performs the buffered write
        body = reply.to_bytes()
        frame = _encode_frame(call_seq, KIND_REPLY, body)
        with self._lock:
            self._q.append((frame, tid, t0, len(body)))
            if self._armed:
                return
            self._armed = True
        hop("reply_flush")
        try:
            self._loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            pass  # connection loop closed: the client sees a torn socket

    def _drain(self) -> None:
        with self._lock:
            items = list(self._q)
            self._q.clear()
            self._armed = False
        now = TRACER.now() if TRACER.enabled else 0
        for frame, tid, t0, nbody in items:
            try:
                self._conn_out.send_nowait(frame, len(frame))
            except Exception:
                return  # connection dead; remaining frames undeliverable
            if tid and t0:
                # respond span (deferred shape): reply ready at the
                # division -> handed to this connection's batched write
                # path (the flush itself is the coalescer's single
                # write+drain per batch)
                TRACER.record(tid, STAGE_RESPOND, t0, now, tag=nbody)


async def _read_frame(reader: asyncio.StreamReader):
    """(call_seq, kind, body) or None on clean EOF."""
    try:
        prefix = await reader.readexactly(4)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise ConnectionError("truncated frame") from None
    (length,) = struct.unpack(">I", prefix)
    if length < 9 or length > MAX_FRAME:
        raise ConnectionError(f"bad frame length {length}")
    body = await reader.readexactly(length)
    _, call_seq, kind = _FRAME.unpack(prefix + body[:9])
    return call_seq, kind, body[9:]


class TcpTlsConfig:
    """TLS for the raw-TCP transport (NettyConfigKeys.Tls): same parameter
    surface as the gRPC GrpcTlsConfig — cert chain + key server-side,
    optional trust root, optional mutual auth — applied as ssl contexts on
    asyncio start_server / open_connection."""

    def __init__(self, cert_chain_path=None, private_key_path=None,
                 trust_root_path=None, mutual_auth=False):
        self.cert_chain_path = cert_chain_path
        self.private_key_path = private_key_path
        self.trust_root_path = trust_root_path
        self.mutual_auth = mutual_auth

    @staticmethod
    def from_properties(p) -> "TcpTlsConfig | None":
        from ratis_tpu.conf.keys import NettyConfigKeys
        if p is None or not NettyConfigKeys.Tls.enabled(p):
            return None
        cfg = TcpTlsConfig(
            cert_chain_path=NettyConfigKeys.Tls.cert_chain(p),
            private_key_path=NettyConfigKeys.Tls.private_key(p),
            trust_root_path=NettyConfigKeys.Tls.trust_root(p),
            mutual_auth=NettyConfigKeys.Tls.mutual_auth(p))
        if not cfg.trust_root_path:
            # Once per configuration, not per connection: encryption without
            # server authentication is a silent downgrade (MITM-able); the
            # gRPC path refuses to run without explicit cert material.
            LOG.warning(
                "TLS enabled WITHOUT a trust root (*.tls.trust.root.path "
                "unset): connections are encrypted but the server is NOT "
                "authenticated — configure a trust root for production")
        return cfg

    def server_context(self):
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_chain_path, self.private_key_path)
        if self.trust_root_path:
            ctx.load_verify_locations(self.trust_root_path)
        if self.mutual_auth:
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def client_context(self):
        import ssl
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        # cluster-internal trust root, not the system store; hostname
        # checks are disabled because peers dial each other by raw IP
        ctx.check_hostname = False
        if self.trust_root_path:
            ctx.load_verify_locations(self.trust_root_path)
            ctx.verify_mode = ssl.CERT_REQUIRED
        else:
            # no trust root: encrypted but unauthenticated — warned once at
            # from_properties time
            ctx.verify_mode = ssl.CERT_NONE
        if self.mutual_auth and self.cert_chain_path:
            ctx.load_cert_chain(self.cert_chain_path, self.private_key_path)
        return ctx


class _Connection:
    """One outbound connection multiplexing calls by sequence number
    (reference NettyRpcProxy channel)."""

    def __init__(self, address: str, tls=None,
                 flush_bytes: int = 0, flush_micros: int = 0) -> None:
        self.address = address
        self._tls = tls
        self._flush_bytes = flush_bytes
        self._flush_micros = flush_micros
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._out: Optional[_StreamFrameCoalescer] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._dead: Optional[Exception] = None
        self.loop: Optional[asyncio.AbstractEventLoop] = None  # at connect

    async def connect(self) -> None:
        self.loop = asyncio.get_running_loop()
        host, port = self.address.rsplit(":", 1)
        ssl_ctx = self._tls.client_context() if self._tls is not None else None
        self._reader, self._writer = await asyncio.open_connection(
            host, int(port), ssl=ssl_ctx)
        self._out = _StreamFrameCoalescer(self._writer, self._flush_bytes,
                                          self._flush_micros)
        self._recv_task = asyncio.create_task(
            self._recv_loop(), name=f"tcp-rpc-recv-{self.address}")

    async def _recv_loop(self) -> None:
        cause: Exception = ConnectionError(f"{self.address} closed")
        try:
            while True:
                frame = await _read_frame(self._reader)
                if frame is None:
                    break
                call_seq, kind, body = frame
                fut = self._pending.pop(call_seq, None)
                if fut is not None and not fut.done():
                    fut.set_result((kind, body))
        except (ConnectionError, OSError, asyncio.CancelledError) as e:
            cause = ConnectionError(f"{self.address} lost: {e}")
        finally:
            self._dead = cause
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(cause)
            self._pending.clear()

    @property
    def alive(self) -> bool:
        return (self._writer is not None and self._dead is None
                and not self._out.poisoned)

    async def call(self, kind: int, body: bytes,
                   timeout_s: float) -> tuple[int, bytes]:
        if self._dead is not None:
            raise self._dead
        seq = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        frame = _encode_frame(seq, kind, body)
        try:
            await self._out.send(frame, len(frame))
        except BaseException:
            self._pending.pop(seq, None)
            raise
        try:
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            self._pending.pop(seq, None)
            raise TimeoutIOException(
                f"rpc to {self.address} timed out after {timeout_s}s") \
                from None

    async def close(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except asyncio.CancelledError:
                pass
        if self._out is not None:
            # flush-on-close: frames already queued must reach the wire
            await self._out.aclose()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class _ConnectionPool:
    """(calling loop, address) -> cached connection; reconnects dead ones
    on demand.

    Keyed per loop on purpose: with loop sharding
    (raft.tpu.server.loop-shards) divisions pinned to worker loops send
    through this pool from their own threads, and an asyncio connection
    (StreamWriter, drain waiters, recv task) is loop-affine — so each
    shard dials its own connection per destination, which also gives each
    shard an independent send pipe instead of one shared serialized
    writer.  Single-loop runtimes see exactly the old one-connection-per-
    address behavior."""

    def __init__(self, tls=None, flush_bytes: int = 0,
                 flush_micros: int = 0) -> None:
        self._conns: Dict[tuple[int, str], _Connection] = {}
        self._locks: Dict[tuple[int, str], asyncio.Lock] = {}
        self._tls = tls
        self._flush_bytes = flush_bytes
        self._flush_micros = flush_micros

    async def get(self, address: str) -> _Connection:
        key = (id(asyncio.get_running_loop()), address)
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            conn = self._conns.get(key)
            if conn is not None and conn.alive:
                return conn
            if conn is not None:
                await conn.close()
            conn = _Connection(address, tls=self._tls,
                               flush_bytes=self._flush_bytes,
                               flush_micros=self._flush_micros)
            await conn.connect()
            self._conns[key] = conn
            return conn

    async def close(self) -> None:
        conns = list(self._conns.values())
        self._conns.clear()
        self._locks.clear()
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        for conn in conns:
            if conn.loop is None or conn.loop is current:
                await conn.close()
            elif conn.loop.is_running():
                # shard-owned connection: its recv task and writer must be
                # unwound on the loop they live on
                try:
                    await asyncio.wrap_future(
                        asyncio.run_coroutine_threadsafe(conn.close(),
                                                         conn.loop))
                except Exception:
                    pass  # connection already broken; socket dies with it
            else:
                # owner loop gone (test teardown): close the raw transport
                # so the fd is released; tasks on the dead loop never run
                if conn._writer is not None:
                    conn._writer.close()


class TcpServerTransport(ServerTransport):
    """Single listening port serving both the consensus union and client
    requests (reference NettyRpcService envelope dispatch)."""

    def __init__(self, peer_id: RaftPeerId, address: str,
                 server_handler: ServerRpcHandler,
                 client_handler: ClientRequestHandler,
                 peer_resolver: Optional[Callable[[RaftPeerId],
                                                  Optional[str]]] = None,
                 request_timeout_s: float = 3.0,
                 tls: "TcpTlsConfig | None" = None,
                 flush_bytes: int = 0, flush_micros: int = 0,
                 defer_replies: bool = False, chaos: bool = False):
        self.peer_id = peer_id
        self._address = address
        self._bound_port: Optional[int] = None
        self.server_handler = server_handler
        self.client_handler = client_handler
        self.peer_resolver = peer_resolver
        self.request_timeout_s = request_timeout_s
        # chaos link-fault gate (raft.tpu.chaos.enabled): when armed,
        # server RPC sends consult the process-wide link-fault table
        # (ratis_tpu.chaos.link) — partitions/latency/drop on real sockets
        self.chaos = chaos
        self.tls = tls
        self.flush_bytes = flush_bytes
        self.flush_micros = flush_micros
        # commit fan-out collapse: attach a per-connection deferred-reply
        # sink to client requests (the division decides per request
        # whether to engage it; see _DeferredReplyFanout)
        self.defer_replies = defer_replies
        self._server: Optional[asyncio.AbstractServer] = None
        self._pool = _ConnectionPool(tls=tls, flush_bytes=flush_bytes,
                                     flush_micros=flush_micros)
        self._accepted: set[asyncio.StreamWriter] = set()

    async def start(self) -> None:
        host, port = self._address.rsplit(":", 1)
        ssl_ctx = self.tls.server_context() if self.tls is not None else None
        self._server = await asyncio.start_server(self._on_connect, host,
                                                  int(port), ssl=ssl_ctx)
        self._bound_port = self._server.sockets[0].getsockname()[1]

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        self._accepted.add(writer)
        # per-connection reply coalescer: concurrent _serve_one replies
        # fold into one buffered flush + one drain per batch
        conn_out = _StreamFrameCoalescer(writer, self.flush_bytes,
                                         self.flush_micros)
        fanout = (_DeferredReplyFanout(conn_out, asyncio.get_running_loop())
                  if self.defer_replies else None)
        tasks: set[asyncio.Task] = set()
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                # handle concurrently: one slow consensus RPC must not
                # head-of-line-block the connection (gRPC gives this for
                # free; here we spawn per-call tasks)
                t = asyncio.create_task(
                    self._serve_one(frame, conn_out, fanout))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        except (ConnectionError, OSError):
            pass
        finally:
            for t in tasks:
                t.cancel()
            try:
                await conn_out.aclose()  # flush-on-close: queued replies
            except (ConnectionError, OSError):
                pass
            self._accepted.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _serve_one(self, frame, conn_out: _StreamFrameCoalescer,
                         fanout: "Optional[_DeferredReplyFanout]" = None
                         ) -> None:
        call_seq, kind, body = frame
        trace_tid = trace_egress = 0
        client_reply = False
        try:
            if kind == KIND_SERVER_RPC:
                reply = await self.server_handler(decode_rpc(body))
                out_kind, out = KIND_REPLY, encode_rpc(reply)
            elif kind == KIND_CLIENT_REQUEST:
                t0 = TRACER.now() if TRACER.enabled else 0
                request = RaftClientRequest.from_bytes(body)
                if t0 and request.trace_id:
                    now = TRACER.now()
                    TRACER.record(request.trace_id, STAGE_DECODE, t0,
                                  now, tag=len(body))
                    INGRESS_NS.set(now)  # route span starts post-decode
                if fanout is not None:
                    attach_reply_sink(
                        request, fanout.sink_for(call_seq,
                                                 request.trace_id))
                reply = await self.client_handler(request)
                if reply is DEFERRED_REPLY:
                    # reply rides the per-connection fan-out batcher at
                    # commit; this task is done at append time
                    return
                trace_tid = request.trace_id
                trace_egress = TRACER.pop_egress(trace_tid)
                client_reply = True
                out_kind, out = KIND_REPLY, reply.to_bytes()
            else:
                raise RaftException(f"unexpected frame kind {kind}")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            LOG.warning("%s tcp rpc failed: %s", self.peer_id, e)
            exc = e if isinstance(e, RaftException) else RaftException(str(e))
            import msgpack
            out_kind, out = KIND_ERROR, msgpack.packb(
                exception_to_wire(exc), use_bin_type=True)
        try:
            if client_reply:
                # per-request commit->reply hop #3 (legacy path): this
                # task suspends for the send/drain — the deferred-reply
                # fan-out replaces it with one drain arm per connection
                # per burst (metrics/hops.py reply_send vs reply_flush)
                hop("reply_send")
            reply_frame = _encode_frame(call_seq, out_kind, out)
            await conn_out.send(reply_frame, len(reply_frame))
            if trace_egress:
                # handler done -> reply serialized, framed, and drained to
                # the socket (possibly as part of a coalesced batch): the
                # real "reply write" cost on this transport — the respond
                # span stays attributed across the coalesced flush
                TRACER.record(trace_tid, STAGE_RESPOND, trace_egress,
                              TRACER.now(), tag=len(out))
        except (ConnectionError, OSError):
            pass

    async def send_server_rpc(self, to: RaftPeerId, msg) -> object:
        address = self.peer_resolver(to) if self.peer_resolver else None
        if address is None:
            raise RaftException(f"unknown peer {to}")
        faults = None
        if self.chaos:
            from ratis_tpu.chaos.link import link_faults
            faults = link_faults()
            if faults:
                await faults.gate(self.peer_id, to)
        try:
            conn = await self._pool.get(address)
            kind, body = await conn.call(KIND_SERVER_RPC, encode_rpc(msg),
                                         self.request_timeout_s)
        except (ConnectionError, OSError) as e:
            raise TimeoutIOException(f"{self.peer_id}->{to}: {e}") from None
        if faults:
            # the reply hop can be degraded independently (asymmetric
            # partitions): the peer processed the RPC but we never hear it
            await faults.gate(to, self.peer_id)
        if kind == KIND_ERROR:
            raise _decode_error(body)
        return decode_rpc(body)

    @property
    def address(self) -> str:
        if self._bound_port and self._address.endswith(":0"):
            host = self._address.rsplit(":", 1)[0]
            return f"{host}:{self._bound_port}"
        return self._address

    async def close(self) -> None:
        await self._pool.close()
        for writer in list(self._accepted):
            writer.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


def _decode_error(body: bytes) -> RaftException:
    import msgpack
    try:
        return exception_from_wire(msgpack.unpackb(body, raw=False))
    except Exception:
        return RaftException(f"undecodable remote error ({len(body)}B)")


class TcpClientTransport(ClientTransport):
    def __init__(self, request_timeout_s: float = 30.0,
                 tls: "TcpTlsConfig | None" = None,
                 flush_bytes: int = 0, flush_micros: int = 0):
        self._pool = _ConnectionPool(tls=tls, flush_bytes=flush_bytes,
                                     flush_micros=flush_micros)
        self.request_timeout_s = request_timeout_s

    async def send_request(self, peer_address: str,
                           request: RaftClientRequest) -> RaftClientReply:
        timeout = (request.timeout_ms / 1000.0 if request.timeout_ms > 0
                   else self.request_timeout_s)
        tid = request.trace_id if TRACER.enabled else 0
        try:
            conn = await self._pool.get(peer_address)
            t0 = TRACER.now() if tid else 0
            payload = request.to_bytes()
            if tid:
                TRACER.record(tid, STAGE_ENCODE, t0, TRACER.now(),
                              tag=len(payload))
                t0 = TRACER.now()
            kind, body = await conn.call(KIND_CLIENT_REQUEST, payload,
                                         timeout)
            if tid:
                # socket write + server + reply read: overlaps the server
                # stages — the wire share is this minus the server tiling
                TRACER.record(tid, STAGE_WIRE, t0, TRACER.now(),
                              tag=len(body))
        except (ConnectionError, OSError) as e:
            raise TimeoutIOException(f"client->{peer_address}: {e}") from None
        if kind == KIND_ERROR:
            raise _decode_error(body)
        return RaftClientReply.from_bytes(body)

    async def close(self) -> None:
        await self._pool.close()


class TcpTransportFactory(TransportFactory):
    def new_server_transport(self, peer_id: RaftPeerId, address: str,
                             server_handler, client_handler, properties=None,
                             peer_resolver=None) -> ServerTransport:
        from ratis_tpu.conf.keys import RaftServerConfigKeys
        timeout_s = 3.0
        if properties is not None:
            timeout_s = RaftServerConfigKeys.Rpc.request_timeout(
                properties).seconds
        fb, fm = _flush_conf(properties)
        chaos = (properties is not None
                 and RaftServerConfigKeys.Chaos.enabled(properties))
        return TcpServerTransport(peer_id, address, server_handler,
                                  client_handler, peer_resolver=peer_resolver,
                                  request_timeout_s=timeout_s,
                                  tls=TcpTlsConfig.from_properties(properties),
                                  flush_bytes=fb, flush_micros=fm,
                                  defer_replies=_defer_conf(properties),
                                  chaos=chaos)

    def new_client_transport(self, properties=None) -> ClientTransport:
        fb, fm = _flush_conf(properties)
        return TcpClientTransport(tls=TcpTlsConfig.from_properties(properties),
                                  flush_bytes=fb, flush_micros=fm)


TransportFactory.register("NETTY", TcpTransportFactory())
TransportFactory.register("TCP", TcpTransportFactory())
