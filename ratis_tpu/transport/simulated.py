"""Deterministic in-memory transport with fault injection.

Capability parity with the reference's simulated RPC used by every abstract
test suite (ratis-server/src/test/.../simulation/SimulatedRequestReply.java:38-100,
SimulatedServerRpc.java): in-process request/reply queues with injectable
latency, per-direction blocking, and peer kill — how multi-node behavior is
tested without sockets.

All servers in one process share a :class:`SimulatedNetwork` hub.  Messages
are delivered by awaiting the target's handler; an optional per-hop delay and
block/partition matrix sits in front.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ratis_tpu.protocol.exceptions import TimeoutIOException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.requests import (DEFERRED_REPLY, RaftClientReply,
                                         RaftClientRequest,
                                         attach_reply_sink)
from ratis_tpu.trace.tracer import (INGRESS_NS, STAGE_RESPOND, STAGE_WIRE,
                                    TRACER)
from ratis_tpu.transport.base import (ClientRequestHandler, ClientTransport,
                                      ServerRpcHandler, ServerTransport,
                                      TransportFactory)


class SimulatedNetwork:
    """The shared hub: routes messages between registered endpoints."""

    def __init__(self, base_delay_ms: float = 0.0, jitter_ms: float = 0.0,
                 seed: int = 0):
        self._endpoints: dict[str, "SimulatedServerTransport"] = {}
        self._by_id: dict[RaftPeerId, "SimulatedServerTransport"] = {}
        self.base_delay_ms = base_delay_ms
        self.jitter_ms = jitter_ms
        self._rng = random.Random(seed)
        # Per-link FIFO clock: the real transports run over TCP connections,
        # which deliver in send order even when latency varies — pipelined
        # appenders rely on that.  Each (src, dst) link remembers its last
        # scheduled delivery instant; a later send is never delivered before
        # an earlier one on the same link.
        self._link_clock: dict[tuple[object, object], float] = {}
        # (src, dst) peer-id pairs currently blackholed
        self._blocked: set[tuple[Optional[RaftPeerId], Optional[RaftPeerId]]] = set()
        self.request_timeout_s = 3.0
        # Client requests may legitimately block server-side far longer than
        # a server-to-server RPC (watch waits for replication, linearizable
        # reads wait for apply) — the server-side timeout governs those.
        self.client_request_timeout_s = 30.0

    # -- fault injection (cf. MiniRaftCluster.RpcBase.setBlockRequestsFrom) --

    def block(self, src: Optional[RaftPeerId] = None,
              dst: Optional[RaftPeerId] = None) -> None:
        """Blackhole src->dst traffic.  None acts as a wildcard."""
        self._blocked.add((src, dst))

    def unblock(self, src: Optional[RaftPeerId] = None,
                dst: Optional[RaftPeerId] = None) -> None:
        self._blocked.discard((src, dst))

    def unblock_all(self) -> None:
        self._blocked.clear()

    def partition(self, side_a: list[RaftPeerId], side_b: list[RaftPeerId]) -> None:
        for a in side_a:
            for b in side_b:
                self.block(a, b)
                self.block(b, a)

    def is_blocked(self, src: Optional[RaftPeerId], dst: Optional[RaftPeerId]) -> bool:
        b = self._blocked
        return ((src, dst) in b or (src, None) in b or (None, dst) in b
                or (None, None) in b)

    # -- registry ------------------------------------------------------------

    def register(self, t: "SimulatedServerTransport") -> None:
        self._endpoints[t.address] = t
        self._by_id[t.peer_id] = t

    def deregister(self, t: "SimulatedServerTransport") -> None:
        self._endpoints.pop(t.address, None)
        if self._by_id.get(t.peer_id) is t:
            self._by_id.pop(t.peer_id, None)

    def lookup_id(self, peer_id: RaftPeerId) -> Optional["SimulatedServerTransport"]:
        return self._by_id.get(peer_id)

    def lookup_addr(self, address: str) -> Optional["SimulatedServerTransport"]:
        return self._endpoints.get(address)

    async def _hop_delay(self, link: Optional[tuple] = None) -> None:
        d = self.base_delay_ms
        if self.jitter_ms:
            d += self._rng.uniform(0, self.jitter_ms)
        if d <= 0:
            return
        if link is None:
            await asyncio.sleep(d / 1e3)
            return
        loop = asyncio.get_running_loop()
        now = loop.time()
        at = max(now + d / 1e3, self._link_clock.get(link, 0.0) + 1e-6)
        self._link_clock[link] = at
        await asyncio.sleep(at - now)

    # -- delivery ------------------------------------------------------------

    async def deliver_server_rpc(self, src: RaftPeerId, dst: RaftPeerId, msg):
        if self.is_blocked(src, dst):
            raise TimeoutIOException(f"simulated: {src}->{dst} blocked")
        target = self.lookup_id(dst)
        if target is None or not target.running:
            raise TimeoutIOException(f"simulated: {dst} unreachable")
        await self._hop_delay((src, dst))
        reply = await asyncio.wait_for(target.server_handler(msg),
                                       self.request_timeout_s)
        if self.is_blocked(dst, src):  # reply path can be blocked too
            raise TimeoutIOException(f"simulated: {dst}->{src} blocked")
        await self._hop_delay((dst, src))
        return reply

    async def deliver_client_request(self, address: str,
                                     request: RaftClientRequest) -> RaftClientReply:
        target = self.lookup_addr(address)
        if target is None or not target.running:
            raise TimeoutIOException(f"simulated: {address} unreachable")
        if self.is_blocked(None, target.peer_id):
            raise TimeoutIOException(f"simulated: client->{target.peer_id} blocked")
        await self._hop_delay()
        # Deferred-reply sink (commit fan-out collapse): the division's
        # waterline fan-out resolves this future directly — the handler
        # coroutine chain finishes at append time, so the commit->reply
        # path is one future resolution instead of the resume chain.  The
        # division engages it only when its server runs with
        # raft.tpu.replication.reply-fanout; otherwise the sink is unused.
        loop = asyncio.get_running_loop()
        sink_fut: asyncio.Future = loop.create_future()
        sink_ns = [0]

        def _set(reply: RaftClientReply) -> None:
            if not sink_fut.done():
                sink_fut.set_result(reply)

        def _sink(reply: RaftClientReply) -> None:
            sink_ns[0] = TRACER.now() if TRACER.enabled else 0
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is loop:
                _set(reply)
            else:
                try:
                    loop.call_soon_threadsafe(_set, reply)
                except RuntimeError:
                    pass  # client loop gone (teardown)

        attach_reply_sink(request, _sink)
        timeout_s = self.client_request_timeout_s
        tid = request.trace_id if TRACER.enabled else 0
        if not tid:
            reply = await asyncio.wait_for(target.client_handler(request),
                                           timeout_s)
            if reply is DEFERRED_REPLY:
                reply = await asyncio.wait_for(sink_fut, timeout_s)
            return reply
        # wire span over a direct function call: ~the server wall — the
        # same overlap shape the socket transports record, so a trace read
        # in Perfetto has the hop lane on every transport
        t0 = TRACER.now()
        INGRESS_NS.set(t0)  # wait_for's task copies this context: the
        # handler's route span starts at ingress, not at task start
        try:
            reply = await asyncio.wait_for(target.client_handler(request),
                                           timeout_s)
            if reply is DEFERRED_REPLY:
                reply = await asyncio.wait_for(sink_fut, timeout_s)
            return reply
        finally:
            now = TRACER.now()
            egress = TRACER.pop_egress(tid) or sink_ns[0]
            if egress:
                # handler done (or fan-out delivery) -> this coroutine
                # resumed: the hand-back task-switch hop (the sim's whole
                # "reply write" cost)
                TRACER.record(tid, STAGE_RESPOND, egress, now)
            TRACER.record(tid, STAGE_WIRE, t0, now)


class SimulatedServerTransport(ServerTransport):
    def __init__(self, network: SimulatedNetwork, peer_id: RaftPeerId,
                 address: str, server_handler: ServerRpcHandler,
                 client_handler: ClientRequestHandler,
                 chaos: bool = False):
        self.network = network
        self.peer_id = peer_id
        self._address = address
        self.server_handler = server_handler
        self.client_handler = client_handler
        # chaos link-fault gate (raft.tpu.chaos.enabled): the scenario
        # engine's fault plane, layered on top of the hub's own
        # block/partition matrix so all three transports share one fault
        # vocabulary (ratis_tpu.chaos.link)
        self.chaos = chaos
        self.running = False

    async def start(self) -> None:
        self.network.register(self)
        self.running = True

    async def close(self) -> None:
        self.running = False
        self.network.deregister(self)

    async def send_server_rpc(self, to: RaftPeerId, msg):
        faults = None
        if self.chaos:
            from ratis_tpu.chaos.link import link_faults
            faults = link_faults()
            if faults:
                await faults.gate(self.peer_id, to)
        reply = await self.network.deliver_server_rpc(self.peer_id, to, msg)
        if faults:
            # independent reply-hop fault (asymmetric partitions): the
            # peer processed the RPC but this sender never hears back
            await faults.gate(to, self.peer_id)
        return reply

    @property
    def address(self) -> str:
        return self._address


class SimulatedClientTransport(ClientTransport):
    def __init__(self, network: SimulatedNetwork):
        self.network = network

    async def send_request(self, peer_address: str,
                           request: RaftClientRequest) -> RaftClientReply:
        return await self.network.deliver_client_request(peer_address, request)


class SimulatedTransportFactory(TransportFactory):
    """Factory bound to one hub instance (pass via properties Parameters or
    construct directly in tests)."""

    def __init__(self, network: Optional[SimulatedNetwork] = None):
        self.network = network or SimulatedNetwork()

    def new_server_transport(self, peer_id, address, server_handler,
                             client_handler, properties=None,
                             peer_resolver=None) -> ServerTransport:
        chaos = False
        if properties is not None:
            from ratis_tpu.conf.keys import RaftServerConfigKeys
            chaos = RaftServerConfigKeys.Chaos.enabled(properties)
        return SimulatedServerTransport(self.network, peer_id, address,
                                        server_handler, client_handler,
                                        chaos=chaos)

    def new_client_transport(self, properties=None) -> ClientTransport:
        return SimulatedClientTransport(self.network)
