"""Pluggable transport SPI.

Capability parity with the reference RpcType / ServerFactory / ClientFactory
SPI (ratis-common/.../rpc/SupportedRpcType.java:24-48, RpcFactory): a server
binds one endpoint serving all its groups; clients and peer servers reach it
by peer address.  Implementations: SIMULATED (in-memory, deterministic,
fault-injectable — the test transport, cf. the reference's
SimulatedRequestReply) and GRPC (real network).
"""

from __future__ import annotations

import abc
from typing import Awaitable, Callable, Optional

from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest

# A server exposes these two handlers to its transport:
ServerRpcHandler = Callable[[object], Awaitable[object]]          # raftrpc msg -> reply
ClientRequestHandler = Callable[[RaftClientRequest], Awaitable[RaftClientReply]]


class ServerTransport(abc.ABC):
    """One server's endpoint: receives server RPCs + client requests, and
    sends server RPCs to peers."""

    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...

    @abc.abstractmethod
    async def send_server_rpc(self, to: RaftPeerId, msg) -> object:
        """Request/response to a peer server (vote/append/snapshot/...)."""

    @property
    @abc.abstractmethod
    def address(self) -> str: ...


class ClientTransport(abc.ABC):
    """Client side: send a RaftClientRequest to a given peer."""

    @abc.abstractmethod
    async def send_request(self, peer_address: str,
                           request: RaftClientRequest) -> RaftClientReply: ...

    async def close(self) -> None:
        pass


class TransportFactory:
    """Registry keyed by rpc type string (SIMULATED / GRPC)."""

    _factories: dict[str, "TransportFactory"] = {}

    @classmethod
    def register(cls, rpc_type: str, factory: "TransportFactory") -> None:
        cls._factories[rpc_type.upper()] = factory

    @classmethod
    def get(cls, rpc_type: str) -> "TransportFactory":
        try:
            return cls._factories[rpc_type.upper()]
        except KeyError:
            raise ValueError(f"unsupported rpc type {rpc_type!r}; "
                             f"known: {sorted(cls._factories)}") from None

    def new_server_transport(self, peer_id: RaftPeerId, address: str,
                             server_handler: ServerRpcHandler,
                             client_handler: ClientRequestHandler,
                             properties=None,
                             peer_resolver=None) -> ServerTransport:
        """peer_resolver: RaftPeerId -> address | None, for transports that
        dial peers by network address (the simulated hub routes by id)."""
        raise NotImplementedError

    def new_client_transport(self, properties=None) -> ClientTransport:
        raise NotImplementedError
