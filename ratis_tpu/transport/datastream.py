"""DataStream transport: bulk byte streaming over asyncio TCP.

Capability parity with the reference Netty DataStream path
(ratis-netty/src/main/java/org/apache/ratis/netty/NettyDataStreamUtils.java
framing + NettyServerStreamRpc / NettyClientStreamRpc): a client opens one
TCP connection to the *primary* peer and sends framed packets — a HEADER
carrying the serialized RaftClientRequest (with routing table), then DATA
packets, finally a packet flagged CLOSE; each packet is acked, and the
CLOSE ack carries the final RaftClientReply of the raft write the primary
submitted.  Peers forward packets to successors over the same framing.

Frame layout (all big-endian):
    u32 total_len | u8 kind | u64 stream_id | u64 offset | u8 flags | bytes
kind: 1=HEADER 2=DATA 3=REPLY; flags bit0=SYNC bit1=CLOSE bit2=SUCCESS.
TPU-first note: this is pure host-side I/O — bulk bytes ride DCN between
failure domains and never enter an XLA program (SURVEY.md §2.6).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import struct
from typing import Awaitable, Callable, Optional

LOG = logging.getLogger(__name__)

KIND_HEADER = 1
KIND_DATA = 2
KIND_REPLY = 3

FLAG_SYNC = 1
FLAG_CLOSE = 2
FLAG_SUCCESS = 4
FLAG_PRIMARY = 8  # set by the client on the header it sends the primary

_HDR = struct.Struct(">IBQQB")  # total_len, kind, stream_id, offset, flags
MAX_FRAME = 64 << 20


def encode_header(request, routing) -> bytes:
    """HEADER payload: the serialized RaftClientRequest + RoutingTable
    (reference DataStreamRequestHeader + RoutingTableProto)."""
    import msgpack
    return msgpack.packb({"req": request.to_bytes(), "rt": routing.to_dict()},
                         use_bin_type=True)


def decode_header(data: bytes):
    import msgpack

    from ratis_tpu.protocol.requests import RaftClientRequest
    from ratis_tpu.protocol.routing import RoutingTable
    d = msgpack.unpackb(data, raw=False)
    return (RaftClientRequest.from_bytes(d["req"]),
            RoutingTable.from_dict(d.get("rt")))


@dataclasses.dataclass(frozen=True)
class Packet:
    kind: int
    stream_id: int
    offset: int
    flags: int
    data: bytes

    @property
    def is_close(self) -> bool:
        return bool(self.flags & FLAG_CLOSE)

    @property
    def is_sync(self) -> bool:
        return bool(self.flags & FLAG_SYNC)

    @property
    def success(self) -> bool:
        return bool(self.flags & FLAG_SUCCESS)


def encode_packet(p: Packet) -> bytes:
    body_len = _HDR.size - 4 + len(p.data)
    return _HDR.pack(body_len, p.kind, p.stream_id, p.offset,
                     p.flags) + p.data


async def read_packet(reader: asyncio.StreamReader) -> Optional[Packet]:
    """Read one frame; None on clean EOF; raises on truncation/oversize."""
    try:
        prefix = await reader.readexactly(4)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise ConnectionError("truncated frame prefix") from None
    (body_len,) = struct.unpack(">I", prefix)
    if body_len < _HDR.size - 4 or body_len > MAX_FRAME:
        raise ConnectionError(f"bad frame length {body_len}")
    body = await reader.readexactly(body_len)
    _, kind, stream_id, offset, flags = _HDR.unpack(prefix + body[:_HDR.size - 4])
    return Packet(kind, stream_id, offset, flags, body[_HDR.size - 4:])


PacketHandler = Callable[[Packet, "PeerConnection"], Awaitable[None]]


class PeerConnection:
    """One accepted connection; the handler replies via :meth:`send`.

    Loop-aware: with the DataStream plane pinned to division loop shards
    (raft.tpu.replication.stream-shards) the packet handlers — and their
    reply sends — run on shard loops while the accepted socket lives on
    the accept loop; a cross-loop send hops back to the owner (StreamWriter
    is loop-affine).  Single-loop servers take the direct path."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self.reader = reader
        self.writer = writer
        self._send_lock = asyncio.Lock()
        self._loop = asyncio.get_running_loop()

    async def send(self, packet: Packet) -> None:
        if asyncio.get_running_loop() is not self._loop:
            await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self._send_owned(packet), self._loop))
            return
        await self._send_owned(packet)

    async def _send_owned(self, packet: Packet) -> None:
        async with self._send_lock:
            self.writer.write(encode_packet(packet))
            await self.writer.drain()

    async def close(self) -> None:
        self.writer.close()
        try:
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass


class DataStreamServer:
    """Accept loop dispatching packets to a handler (NettyServerStreamRpc)."""

    def __init__(self, address: str, handler: PacketHandler,
                 tls=None) -> None:
        self.address = address
        self.handler = handler
        self.tls = tls  # transport.tcp.TcpTlsConfig (same surface)
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[PeerConnection] = set()

    async def start(self) -> None:
        host, port = self.address.rsplit(":", 1)
        ssl_ctx = self.tls.server_context() if self.tls is not None else None
        self._server = await asyncio.start_server(self._on_connect, host,
                                                  int(port), ssl=ssl_ctx)

    @property
    def bound_port(self) -> Optional[int]:
        if self._server and self._server.sockets:
            return self._server.sockets[0].getsockname()[1]
        return None

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        conn = PeerConnection(reader, writer)
        self._conns.add(conn)
        try:
            while True:
                packet = await read_packet(reader)
                if packet is None:
                    break
                try:
                    await self.handler(packet, conn)
                except Exception:
                    LOG.exception("datastream handler failed")
                    await conn.send(Packet(KIND_REPLY, packet.stream_id,
                                           packet.offset, packet.flags & ~FLAG_SUCCESS,
                                           b""))
        except (ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(conn)
            await conn.close()

    async def close(self) -> None:
        # connections first: wait_closed() (3.12+) waits for every handler,
        # and handlers block in read_packet until their connection dies
        for conn in list(self._conns):
            await conn.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class DataStreamConnection:
    """Client/forwarder side: one connection with per-packet ack futures
    keyed by (stream_id, offset, close-flag) — the sliding-window analog of
    OrderedStreamAsync."""

    def __init__(self, address: str, tls=None) -> None:
        self.address = address
        self.tls = tls
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: dict[tuple, asyncio.Future] = {}
        self._recv_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self._dead: Optional[Exception] = None

    async def connect(self) -> None:
        host, port = self.address.rsplit(":", 1)
        ssl_ctx = self.tls.client_context() if self.tls is not None else None
        self._reader, self._writer = await asyncio.open_connection(
            host, int(port), ssl=ssl_ctx)
        self._recv_task = asyncio.create_task(
            self._recv_loop(), name=f"datastream-recv-{self.address}")

    async def _recv_loop(self) -> None:
        cause: Exception = ConnectionError(
            f"datastream connection to {self.address} closed")
        try:
            while True:
                packet = await read_packet(self._reader)
                if packet is None:
                    break  # clean EOF still fails whatever is outstanding
                key = (packet.stream_id, packet.offset, packet.is_close)
                fut = self._pending.pop(key, None)
                if fut is not None and not fut.done():
                    fut.set_result(packet)
        except (ConnectionError, OSError, asyncio.CancelledError) as e:
            cause = ConnectionError(f"datastream connection lost: {e}")
        finally:
            self._dead = cause
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(cause)
            self._pending.clear()

    async def send(self, packet: Packet) -> "asyncio.Future[Packet]":
        """Send one packet; returns the future of its REPLY packet."""
        if self._dead is not None:
            raise self._dead
        key = (packet.stream_id, packet.offset, packet.is_close)
        if key in self._pending:
            raise ConnectionError(
                f"duplicate in-flight packet key {key} (zero-length data?)")
        fut = asyncio.get_running_loop().create_future()
        self._pending[key] = fut
        async with self._send_lock:
            self._writer.write(encode_packet(packet))
            await self._writer.drain()
        return fut

    async def close(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except asyncio.CancelledError:
                pass
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
