"""gRPC transport: the real-network RPC backend (asyncio, grpc.aio).

Capability parity with the reference gRPC transport (ratis-grpc/
GrpcFactory.java, server/GrpcServicesImpl.java:56, GrpcServerProtocolService
:46, client/GrpcClientRpc): one server endpoint per RaftServer carrying all
groups' traffic, with

- a server-to-server service (requestVote / appendEntries / installSnapshot
  / readIndex / startLeaderElection),
- a client service (all RaftClientRequest types incl. admin).

Transport-format difference by design: instead of compiled protobuf stubs
the services are grpc *generic* handlers over the framework's tagged msgpack
envelope (protocol.raftrpc.encode_rpc — the same union shape as the
reference's Netty.proto:31-48), so every transport shares one codec and the
wire layer needs no generated code.  Peer channels are cached per address
(reference PeerProxyMap / GrpcServerProtocolClient).
"""

from __future__ import annotations

import asyncio
import logging
import pathlib
import time
from typing import Callable, Optional

import grpc
import grpc.aio
import msgpack

from ratis_tpu.metrics.hops import hop
from ratis_tpu.protocol.exceptions import RaftException, TimeoutIOException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.raftrpc import (AppendEntriesRequest, AppendEnvelope,
                                        decode_rpc, encode_rpc)
from ratis_tpu.protocol.requests import (DEFERRED_REPLY, RaftClientReply,
                                         RaftClientRequest,
                                         attach_reply_sink)
from ratis_tpu.trace.tracer import (INGRESS_NS, STAGE_DECODE, STAGE_ENCODE,
                                    STAGE_RESPOND, STAGE_WIRE, TRACER)
from ratis_tpu.transport.base import (ClientRequestHandler, ClientTransport,
                                      ServerRpcHandler, ServerTransport,
                                      TransportFactory)
from ratis_tpu.transport.coalesce import WriteCoalescer

LOG = logging.getLogger(__name__)

SERVER_SERVICE = "ratis_tpu.RaftServerProtocol"
CLIENT_SERVICE = "ratis_tpu.RaftClientProtocol"
_RPC_METHOD = f"/{SERVER_SERVICE}/rpc"
_APPEND_STREAM_METHOD = f"/{SERVER_SERVICE}/appendStream"
_REQUEST_METHOD = f"/{CLIENT_SERVICE}/request"
_REQUEST_STREAM_METHOD = f"/{CLIENT_SERVICE}/requestStream"

# append-stream envelope status codes
_ST_OK = 0
_ST_RAFT_ERROR = 1
_ST_INTERNAL = 2


class GrpcTlsConfig:
    """TLS parameters (reference GrpcTlsConfig, ratis-grpc/.../GrpcTlsConfig):
    cert chain + private key for the server side, an optional trust root for
    verifying peers/servers, optional mutual auth."""

    def __init__(self, cert_chain_path: Optional[str] = None,
                 private_key_path: Optional[str] = None,
                 trust_root_path: Optional[str] = None,
                 mutual_auth: bool = False,
                 target_name_override: Optional[str] = None):
        self.cert_chain_path = cert_chain_path
        self.private_key_path = private_key_path
        self.trust_root_path = trust_root_path
        self.mutual_auth = mutual_auth
        # test/dev certs are rarely issued for raw IPs; this maps to
        # grpc.ssl_target_name_override
        self.target_name_override = target_name_override

    @staticmethod
    def from_properties(p) -> Optional["GrpcTlsConfig"]:
        from ratis_tpu.conf.keys import GrpcConfigKeys
        if p is None or not GrpcConfigKeys.Tls.enabled(p):
            return None
        return GrpcTlsConfig(
            cert_chain_path=GrpcConfigKeys.Tls.cert_chain(p),
            private_key_path=GrpcConfigKeys.Tls.private_key(p),
            trust_root_path=GrpcConfigKeys.Tls.trust_root(p),
            mutual_auth=GrpcConfigKeys.Tls.mutual_auth(p),
            target_name_override=GrpcConfigKeys.Tls.name_override(p))

    @staticmethod
    def admin_from_properties(p) -> Optional["GrpcTlsConfig"]:
        """The admin endpoint's own TLS block (reference admin
        GrpcTlsConfig, GrpcServicesImpl.java:56,219-224); falls back to the
        main Tls block when not separately enabled."""
        from ratis_tpu.conf.keys import GrpcConfigKeys
        if p is None or not GrpcConfigKeys.AdminTls.enabled(p):
            return GrpcTlsConfig.from_properties(p)
        return GrpcTlsConfig(
            cert_chain_path=GrpcConfigKeys.AdminTls.cert_chain(p),
            private_key_path=GrpcConfigKeys.AdminTls.private_key(p),
            trust_root_path=GrpcConfigKeys.AdminTls.trust_root(p),
            mutual_auth=GrpcConfigKeys.AdminTls.mutual_auth(p),
            target_name_override=GrpcConfigKeys.Tls.name_override(p))

    def _read(self, path: Optional[str]) -> Optional[bytes]:
        return pathlib.Path(path).read_bytes() if path else None

    def server_credentials(self) -> grpc.ServerCredentials:
        return grpc.ssl_server_credentials(
            [(self._read(self.private_key_path),
              self._read(self.cert_chain_path))],
            root_certificates=self._read(self.trust_root_path),
            require_client_auth=self.mutual_auth)

    def channel_credentials(self) -> grpc.ChannelCredentials:
        return grpc.ssl_channel_credentials(
            root_certificates=self._read(self.trust_root_path),
            private_key=(self._read(self.private_key_path)
                         if self.mutual_auth else None),
            certificate_chain=(self._read(self.cert_chain_path)
                               if self.mutual_auth else None))

    def channel_options(self) -> list:
        if self.target_name_override:
            return [("grpc.ssl_target_name_override",
                     self.target_name_override)]
        return []

# Generous bounds: appenders batch up to the configured buffer byte limit,
# snapshot chunks up to snapshot.chunk.size.max (16MB default).
_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    # A multi-raft host's event loop legitimately stalls for seconds
    # (deliberate GC seal, cold jit compile); default HTTP/2 ping/settings
    # deadlines then GOAWAY every connection at once, and the mass
    # reconnect allocates so much that the NEXT collector pass is even
    # longer — a measured death spiral at 1024 co-hosted groups.  Be
    # generous: consensus liveness has its own (election) timers.
    ("grpc.keepalive_timeout_ms", 60_000),
    ("grpc.http2.ping_timeout_ms", 60_000),
    ("grpc.http2.settings_timeout", 60_000),
]

_identity = lambda b: b  # noqa: E731  (bytes in/out; codecs are ours)

# Status codes that mean "transient — retry/failover"; everything else is a
# deterministic failure surfaced to the caller.
_TRANSIENT_CODES = frozenset((grpc.StatusCode.UNAVAILABLE,
                              grpc.StatusCode.DEADLINE_EXCEEDED,
                              grpc.StatusCode.CANCELLED))


class _ChannelPool:
    """address -> aio channel cache with cached multicallables
    (reference PeerProxyMap; building a fresh multicallable per call was
    measurable overhead on the append hot path)."""

    def __init__(self, tls: Optional[GrpcTlsConfig] = None):
        self._channels: dict[str, grpc.aio.Channel] = {}
        self._unary: dict[tuple[str, str], object] = {}
        self._stream: dict[tuple[str, str], object] = {}
        self._tls = tls

    def get(self, address: str) -> grpc.aio.Channel:
        ch = self._channels.get(address)
        if ch is None:
            if self._tls is not None:
                ch = grpc.aio.secure_channel(
                    address, self._tls.channel_credentials(),
                    options=_CHANNEL_OPTIONS + self._tls.channel_options())
            else:
                ch = grpc.aio.insecure_channel(address,
                                               options=_CHANNEL_OPTIONS)
            self._channels[address] = ch
        return ch

    def unary(self, address: str, method: str):
        key = (address, method)
        call = self._unary.get(key)
        if call is None:
            call = self.get(address).unary_unary(
                method, request_serializer=_identity,
                response_deserializer=_identity)
            self._unary[key] = call
        return call

    def stream(self, address: str, method: str):
        key = (address, method)
        call = self._stream.get(key)
        if call is None:
            call = self.get(address).stream_stream(
                method, request_serializer=_identity,
                response_deserializer=_identity)
            self._stream[key] = call
        return call

    async def close(self) -> None:
        self._unary.clear()
        self._stream.clear()
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()


class _StreamDialGate:
    """Per-address re-dial pacing for the shared bidi streams.  Without
    it, every pending send re-dials the instant a stream dies, and a
    transient stall (loop pause, peer GOAWAY) becomes a dial storm:
    thousands of grpc calls created per second, each leaving C-core
    operation objects behind — measured as multi-GB RSS growth and a
    drowned event loop.  One dial attempt per address per window; other
    senders fail fast as transient and retry through their normal paths."""

    WINDOW_S = 0.25

    def __init__(self):
        self._last: dict[str, float] = {}

    def may_dial(self, address: str) -> bool:
        now = time.monotonic()
        if now - self._last.get(address, 0.0) < self.WINDOW_S:
            return False
        self._last[address] = now
        return True


class _StreamChunkCoalescer(WriteCoalescer):
    """Stream-framing coalescing (VERDICT r5 item 6): one bidi stream
    message carries a BATCH of ``[call_id, payload]`` chunks, so grpc.aio's
    per-message Python+C-core cost is paid once per batch instead of once
    per append.  A single-chunk flush keeps the legacy wire shape (a bare
    pair), so with thresholds at 0 the stream framing is unchanged."""

    def __init__(self, call, flush_micros: int = 0, max_frames: int = 64):
        super().__init__(flush_micros=flush_micros, max_frames=max_frames)
        self._call = call

    async def _flush_batch(self, frames: list) -> None:
        # the coalescer's internal lock serializes flushes, which is the
        # overlapping-write serialization grpc core requires
        # (GRPC_CALL_ERROR_TOO_MANY_OPERATIONS)
        await self._call.write(msgpack.packb(
            frames[0] if len(frames) == 1 else frames))


class _DeferredStreamFanout:
    """Per-stream deferred-reply batcher (commit fan-out collapse on the
    gRPC bidi client stream — the transport analog of the TCP
    ``_DeferredReplyFanout``): the division's waterline fan-out calls
    :meth:`submit` synchronously (possibly from a shard loop); replies
    queue here and ONE armed callback per burst drains them into the
    stream's reply queue, where the generator's batch-what's-ready fold
    ships them — one scheduled hop per burst per stream instead of one
    handler-resume + reply-write chain per request."""

    __slots__ = ("_loop", "_replies", "_q", "_lock", "_armed")

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 replies: asyncio.Queue) -> None:
        import collections
        import threading
        self._loop = loop
        self._replies = replies
        self._q = collections.deque()
        self._lock = threading.Lock()
        self._armed = False

    def sink_for(self, call_id: int, trace_id: int = 0):
        def sink(reply: RaftClientReply) -> None:
            self.submit(call_id, reply, trace_id)
        return sink

    def submit(self, call_id: int, reply: RaftClientReply,
               trace_id: int = 0) -> None:
        tid = trace_id if TRACER.enabled else 0
        t0 = TRACER.now() if tid else 0
        # encode on the CALLING (division) loop: serialization stays off
        # the stream's loop, which only forwards the finished chunks
        body = reply.to_bytes()
        with self._lock:
            self._q.append(([call_id, _ST_OK, body], tid, t0))
            if self._armed:
                return
            self._armed = True
        hop("reply_flush")
        try:
            self._loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            pass  # stream loop closed: the client sees a dead stream

    def _drain(self) -> None:
        with self._lock:
            items = list(self._q)
            self._q.clear()
            self._armed = False
        now = TRACER.now() if TRACER.enabled else 0
        backlog: list = []
        for out, tid, t0 in items:
            if backlog:
                backlog.append(out)
            else:
                try:
                    self._replies.put_nowait(out)
                except asyncio.QueueFull:
                    # reply order across call ids is irrelevant (replies
                    # are id-matched); overflow rides one catch-up task
                    backlog.append(out)
            if tid and t0:
                # respond span (deferred shape): reply ready at the
                # division -> handed to this stream's reply fold
                TRACER.record(tid, STAGE_RESPOND, t0, now, tag=len(out[2]))
        if backlog:
            self._loop.create_task(self._put_backlog(backlog))

    async def _put_backlog(self, outs: list) -> None:
        for out in outs:
            await self._replies.put(out)


class _AppendStreamClient:
    """One ordered bidi stream to a peer carrying entry-bearing
    AppendEntries (reference GrpcLogAppender's appendEntries stream,
    GrpcLogAppender.java:343: requests flow in order on one HTTP/2 stream,
    replies are matched back by a stream-local id).  Heartbeats keep using
    the unary path — the reference's separate heartbeat channel — so they
    never queue behind a full window of batches."""

    def __init__(self, multicallable, flush_micros: int = 0,
                 flush_chunks: int = 64):
        self._call = multicallable()
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self.closed = False
        # serializes writes (grpc core rejects overlapping write() ops on
        # one call) and, when flush_micros > 0, batches chunks into one
        # stream message per flush
        self._out = _StreamChunkCoalescer(self._call,
                                          flush_micros=flush_micros,
                                          max_frames=flush_chunks)
        self._reader = asyncio.create_task(self._read_loop())

    async def send(self, payload: bytes, timeout_s: float) -> bytes:
        if self.closed:
            raise TimeoutIOException("append stream closed")
        call_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[call_id] = fut
        wrote = False

        async def _write_then_wait() -> bytes:
            nonlocal wrote
            await self._out.send([call_id, payload], len(payload) + 16)
            wrote = True
            return await fut

        try:
            # one deadline over write + reply: a flow-control-blocked write
            # (frozen peer, full HTTP/2 window) must also time out so the
            # appender's send slot frees and its window resets
            return await asyncio.wait_for(_write_then_wait(), timeout_s)
        except asyncio.TimeoutError:
            if not wrote and not self._out.coalescing:
                # the deadline cancelled the writer MID self._call.write():
                # the call may hold an abandoned core write op, and reusing
                # it breaks the overlapping-write serialization — this
                # stream is done (callers see .closed and re-dial); only
                # the reply-is-late case is safe to ride out.  With
                # coalescing on, the chunk was merely QUEUED and the
                # flusher task owns the core write — the stream stays
                # healthy and the late reply is dropped by the reader.
                self._fail(TimeoutIOException(
                    "append stream write timed out (flow-blocked peer)"))
            raise
        finally:
            self._pending.pop(call_id, None)

    def _dispatch_reply(self, call_id: int, status: int, payload) -> None:
        fut = self._pending.pop(call_id, None)
        if fut is None or fut.done():
            return
        if status == _ST_OK:
            fut.set_result(payload)
        elif status == _ST_RAFT_ERROR:
            fut.set_exception(RaftException(payload.decode()))
        else:
            fut.set_exception(TimeoutIOException(payload.decode()))

    async def _read_loop(self) -> None:
        try:
            async for chunk in self._call:
                decoded = msgpack.unpackb(chunk)
                if decoded and isinstance(decoded[0], (list, tuple)):
                    # coalesced reply batch: several [id, status, payload]
                    # triples in one stream message
                    for call_id, status, payload in decoded:
                        self._dispatch_reply(call_id, status, payload)
                else:
                    call_id, status, payload = decoded
                    self._dispatch_reply(call_id, status, payload)
        except asyncio.CancelledError:
            self._fail(ConnectionError("append stream closed"))
            raise
        except Exception as e:
            self._fail(e)
        else:
            self._fail(ConnectionError("append stream closed by peer"))

    def _fail(self, exc: Exception) -> None:
        self.closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    TimeoutIOException(f"append stream error: {exc}"))
        self._pending.clear()

    async def close(self) -> None:
        # fail in-flight sends NOW: they must not sit out their full
        # timeout on a stream we already know is dead
        self._fail(ConnectionError("append stream closed"))
        try:
            await self._out.aclose()
        except Exception:
            pass
        self._reader.cancel()
        try:
            await self._reader
        except (asyncio.CancelledError, Exception):
            pass
        try:
            # release the C-core call deterministically: a merely-abandoned
            # call keeps its operation objects (SendInitialMetadata /
            # ReceiveStatus / CallbackWrapper) alive until a GC pass, and a
            # re-dial storm accumulated tens of thousands of them (multi-GB
            # RSS measured)
            self._call.cancel()
        except Exception:
            pass


class GrpcServerTransport(ServerTransport):
    def __init__(self, peer_id: RaftPeerId, address: str,
                 server_handler: ServerRpcHandler,
                 client_handler: ClientRequestHandler,
                 peer_resolver: Optional[Callable[[RaftPeerId], Optional[str]]]
                 = None,
                 request_timeout_s: float = 3.0,
                 tls: Optional[GrpcTlsConfig] = None,
                 client_port: Optional[int] = None,
                 admin_port: Optional[int] = None,
                 admin_tls: Optional[GrpcTlsConfig] = None,
                 flush_micros: int = 0, flush_chunks: int = 64,
                 defer_replies: bool = False, chaos: bool = False):
        self.peer_id = peer_id
        # chaos link-fault gate (raft.tpu.chaos.enabled): armed server RPC
        # sends consult the process-wide link-fault table
        # (ratis_tpu.chaos.link) — partitions/latency/drop over gRPC
        self.chaos = chaos
        # stream-framing coalescing (raft.tpu.grpc.*): 0µs = one chunk per
        # stream message, the pre-round-6 wire shape
        self.flush_micros = flush_micros
        self.flush_chunks = max(1, flush_chunks)
        # commit fan-out collapse (raft.tpu.replication.reply-fanout):
        # attach a per-stream deferred-reply sink to client requests so
        # replies ride the waterline fan-out instead of per-request
        # handler resumes (the TCP transport's defer_replies analog)
        self.defer_replies = defer_replies
        # observability for the keyed-FIFO dispatch + framing coalescing
        # (ADVICE r5: make reorder churn and batching measurable)
        self.dispatch_metrics = {"stream_chunks": 0, "keyed_chunks": 0,
                                 "ordered_waits": 0, "batched_messages": 0,
                                 "reply_batches": 0}
        self._address = address
        self._bound_port: Optional[int] = None
        # optional dedicated client/admin endpoint (GrpcServicesImpl's
        # separate client/admin ports); None = client service shares the
        # server-to-server port
        self.client_port = client_port
        self._client_server: Optional[grpc.aio.Server] = None
        self.bound_client_port: Optional[int] = None
        # optional THIRD endpoint serving ONLY admin request types, with its
        # own TLS config (GrpcServicesImpl.java:56,197-224)
        self.admin_port = admin_port
        self.admin_tls = admin_tls
        self._admin_server: Optional[grpc.aio.Server] = None
        self.bound_admin_port: Optional[int] = None
        self.server_handler = server_handler
        self.client_handler = client_handler
        self.peer_resolver = peer_resolver
        self.request_timeout_s = request_timeout_s
        self.tls = tls
        self._server: Optional[grpc.aio.Server] = None
        self._pool = _ChannelPool(tls)
        self._append_streams: dict[str, _AppendStreamClient] = {}
        self._dial_gate = _StreamDialGate()

    # ---------------------------------------------------------- service side

    async def _handle_rpc(self, request_bytes: bytes, context) -> bytes:
        try:
            msg = decode_rpc(request_bytes)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"undecodable rpc: {e}")
        try:
            reply = await self.server_handler(msg)
        except RaftException as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except Exception as e:
            LOG.exception("%s: server rpc failed", self.peer_id)
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return encode_rpc(reply)

    async def _handle_client(self, request_bytes: bytes, context) -> bytes:
        try:
            request = RaftClientRequest.from_bytes(request_bytes)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"undecodable client request: {e}")
        reply = await self.client_handler(request)
        return reply.to_bytes()

    # bound on concurrently-processing chunks per inbound stream: enough to
    # keep every co-hosted group's append pipeline full, finite so a peer
    # cannot balloon the task set (HTTP/2 flow control bounds bytes, not
    # handler tasks)
    _STREAM_CONCURRENCY = 256

    async def _serve_stream(self, request_iterator, dispatch, classify=None,
                            defer: bool = False):
        """Shared server scaffold for the multiplexed bidi streams (append
        plane and client plane): chunks are handled CONCURRENTLY (a slow
        division flush must not head-of-line-block every co-hosted group
        riding the same stream — the same policy as the TCP transport's
        per-frame tasks) and replies carry the chunk's stream-local id, so
        they may complete out of order.

        ``classify(payload) -> (work, key)`` decodes/keys a chunk in the
        pump (arrival order); chunks sharing a non-None key dispatch in
        STRICT arrival order via a per-key completion chain — the keyed
        FIFO queue that closes ADVICE r5's reorder finding (same-group
        append chunks suspending at different await points could process
        out of arrival order and cause spurious INCONSISTENCY/rewind
        churn).  Distinct keys (and key None) stay fully concurrent.

        One inbound stream message may carry a coalesced BATCH of chunks
        (``raft.tpu.grpc.*``); replies batch the same way — everything
        ready in the reply queue folds into one stream message, zero added
        latency.  ``dispatch(work) -> reply bytes``; a RaftException maps
        to _ST_RAFT_ERROR, anything else to _ST_INTERNAL."""
        # BOUNDED reply queue: run_one blocks on put when the consumer (the
        # HTTP/2 send side) stalls, which keeps the gate held, which stops
        # the pump from accepting more chunks — end-to-end backpressure.
        # With an unbounded queue + release-on-enqueue, a peer that kept
        # writing while its read side lagged ballooned this server's heap
        # by the full reply backlog (measured: multi-GB RSS growth).
        replies: asyncio.Queue = asyncio.Queue(
            maxsize=self._STREAM_CONCURRENCY * 2)
        gate = asyncio.Semaphore(self._STREAM_CONCURRENCY)
        tasks: set[asyncio.Task] = set()
        last_by_key: dict[object, asyncio.Future] = {}
        metrics = self.dispatch_metrics
        # deferred-reply fan-out (commit fan-out collapse): dispatch gets
        # (fanout, call_id) and may return None — the reply arrives later
        # through the fanout's thread-safe drain into this reply queue
        fanout = (_DeferredStreamFanout(asyncio.get_running_loop(), replies)
                  if defer else None)

        async def run_one(call_id: int, work, prev, done) -> None:
            try:
                if prev is not None:
                    # keyed FIFO: wait out the predecessor chunk's dispatch
                    # (it always completes — set in its finally)
                    metrics["ordered_waits"] += 1
                    try:
                        await prev
                    except Exception:
                        pass
                try:
                    res = await (dispatch(work, (fanout, call_id))
                                 if fanout is not None else dispatch(work))
                    # None = deferred: the waterline fan-out delivers the
                    # reply through this stream's fanout at commit
                    out = (None if res is None
                           else [call_id, _ST_OK, res])
                except RaftException as e:
                    out = [call_id, _ST_RAFT_ERROR, str(e).encode()]
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    LOG.exception("%s: stream rpc failed", self.peer_id)
                    out = [call_id, _ST_INTERNAL, str(e).encode()]
                # unblock the successor BEFORE the (possibly backpressured)
                # reply enqueue: ordering is a dispatch guarantee, not a
                # reply-write guarantee
                if not done.done():
                    done.set_result(None)
                if out is not None:
                    await replies.put(out)
            finally:
                if not done.done():
                    done.set_result(None)
                gate.release()

        loop = asyncio.get_running_loop()

        async def enqueue(call_id: int, payload: bytes) -> None:
            metrics["stream_chunks"] += 1
            await gate.acquire()
            try:
                work, key = (classify(payload) if classify is not None
                             else (payload, None))
            except Exception as e:
                # undecodable chunk: report it on ITS call id instead of
                # killing the whole (shared, multi-group) stream
                await replies.put([call_id, _ST_INTERNAL,
                                   f"undecodable chunk: {e}".encode()])
                gate.release()
                return
            prev = None
            done = loop.create_future()
            if key is not None:
                metrics["keyed_chunks"] += 1
                prev = last_by_key.get(key)
                last_by_key[key] = done
                done.add_done_callback(
                    lambda f, k=key: (last_by_key.pop(k, None)
                                      if last_by_key.get(k) is f else None))
            t = asyncio.create_task(run_one(call_id, work, prev, done))
            tasks.add(t)
            t.add_done_callback(tasks.discard)

        async def pump() -> None:
            try:
                async for chunk in request_iterator:
                    try:
                        decoded = msgpack.unpackb(chunk)
                        if decoded and isinstance(decoded[0], (list, tuple)):
                            # coalesced batch of [call_id, payload] pairs
                            pairs = [(c, p) for c, p in decoded]
                        else:
                            c, p = decoded
                            pairs = [(c, p)]
                    except Exception as e:
                        # peer is garbling the FRAMING: stop reading — the
                        # stream ends and the sender re-dials.  Say WHY on
                        # this side (a bare break would leave both ends
                        # diagnosing a generic 'stream closed').
                        LOG.error("%s: undecodable stream chunk (%s); "
                                  "closing stream", self.peer_id, e)
                        break
                    if len(pairs) > 1:
                        metrics["batched_messages"] += 1
                    for call_id, payload in pairs:
                        await enqueue(call_id, payload)
            finally:
                # all accepted work must flush before the end marker
                for t in list(tasks):
                    try:
                        await t
                    except (asyncio.CancelledError, Exception):
                        pass
                # bounded: if the consumer is gone AND the queue is full
                # (stalled peer disconnect), an unbounded put would leak
                # this task + the reply backlog forever
                try:
                    await asyncio.wait_for(replies.put(None), 30.0)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    pass

        pump_task = asyncio.create_task(pump())
        coalesce_replies = self.flush_micros > 0
        try:
            finished = False
            while not finished:
                item = await replies.get()
                if item is None:
                    break
                if not coalesce_replies:
                    yield msgpack.packb(item)
                    continue
                # batch-what's-ready: fold every already-queued reply into
                # this stream message (no timed wait — zero added latency)
                batch = [item]
                while len(batch) < self.flush_chunks:
                    try:
                        nxt = replies.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is None:
                        finished = True
                        break
                    batch.append(nxt)
                if len(batch) > 1:
                    metrics["reply_batches"] += 1
                yield msgpack.packb(batch if len(batch) > 1 else batch[0])
        finally:
            pump_task.cancel()
            for t in list(tasks):
                t.cancel()

    async def _handle_append_stream(self, request_iterator, context):
        """Server side of the per-peer append stream
        (GrpcServerProtocolService.java:46 appendEntries stream observer).

        Unary (per-group) entry appends are KEYED by group id so same-group
        chunks dispatch in arrival order (scalar mode pipelines a window of
        them concurrently on this stream — the reorder surface ADVICE r5
        flagged).  SEQUENCED envelopes (append-window pipelining,
        raft.tpu.replication.window-depth > 1) are keyed by lane: their
        frames may share groups, and dispatching a lane's frames in stream
        arrival order keeps the server's lane intake on its buffer-free
        happy path.  Unsequenced envelopes stay unkeyed: their sender's
        depth-1 busy latch guarantees a group's items are never split
        across two in-flight envelopes, so those envelopes are
        group-disjoint and safely concurrent."""

        def classify(payload: bytes):
            msg = decode_rpc(payload)
            if isinstance(msg, AppendEntriesRequest) and msg.entries:
                return msg, ("g", msg.header.group_id.to_bytes())
            if isinstance(msg, AppendEnvelope) and msg.seq >= 0:
                return msg, ("l", msg.lane)
            return msg, None

        async def dispatch(msg) -> bytes:
            return encode_rpc(await self.server_handler(msg))

        async for item in self._serve_stream(request_iterator, dispatch,
                                             classify=classify):
            yield item

    async def _handle_client_stream(self, request_iterator, context):
        """Server side of the multiplexed client-request stream (reference
        GrpcClientProtocolService.java ordered stream): same id-matched
        concurrent-chunk shape as the append stream — one HTTP/2 stream per
        (client, server) instead of one per request, which is where
        grpc.aio's per-unary-call overhead was going at 1024 groups.

        With ``defer_replies`` (commit fan-out collapse,
        raft.tpu.replication.reply-fanout) each request gets a deferred
        reply sink into the stream's fan-out batcher: the handler chain
        ends at append time, and the commit waterline delivers the reply
        through one drained burst per stream — gRPC now rides the same
        collapsed reply plane as TCP and sim."""

        async def dispatch(payload: bytes, defer_ctx=None):
            t0 = TRACER.now() if TRACER.enabled else 0
            request = RaftClientRequest.from_bytes(payload)
            if t0 and request.trace_id:
                now = TRACER.now()
                TRACER.record(request.trace_id, STAGE_DECODE, t0,
                              now, tag=len(payload))
                INGRESS_NS.set(now)  # route span starts post-decode
            if defer_ctx is not None:
                fanout, call_id = defer_ctx
                attach_reply_sink(
                    request, fanout.sink_for(call_id, request.trace_id))
            reply = await self.client_handler(request)
            if reply is DEFERRED_REPLY:
                # reply rides the stream's fan-out batcher at commit;
                # this dispatch is done at append time
                return None
            reply_bytes = reply.to_bytes()
            egress = TRACER.pop_egress(request.trace_id)
            if egress:
                TRACER.record(request.trace_id, STAGE_RESPOND, egress,
                              TRACER.now(), tag=len(reply_bytes))
            return reply_bytes

        async for item in self._serve_stream(request_iterator, dispatch,
                                             defer=self.defer_replies):
            yield item

    def _client_handlers(self):
        return grpc.method_handlers_generic_handler(
            CLIENT_SERVICE,
            {"request": grpc.unary_unary_rpc_method_handler(
                self._handle_client, request_deserializer=_identity,
                response_serializer=_identity),
             "requestStream": grpc.stream_stream_rpc_method_handler(
                self._handle_client_stream, request_deserializer=_identity,
                response_serializer=_identity)})

    async def _handle_admin(self, request_bytes: bytes, context) -> bytes:
        """Admin endpoint: serves ONLY the admin request types; data-plane
        requests are rejected so the dedicated port is genuinely an admin
        plane (firewallable separately, like the reference's admin
        server)."""
        from ratis_tpu.protocol.requests import RequestType
        try:
            request = RaftClientRequest.from_bytes(request_bytes)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"undecodable admin request: {e}")
        if request.type.type < RequestType.SET_CONFIGURATION:
            # admin types are the 8..14 block (SET_CONFIGURATION and up)
            await context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"{request.type.type.name} is not an admin operation")
        reply = await self.client_handler(request)
        return reply.to_bytes()

    def _admin_handlers(self):
        return grpc.method_handlers_generic_handler(
            CLIENT_SERVICE,
            {"request": grpc.unary_unary_rpc_method_handler(
                self._handle_admin, request_deserializer=_identity,
                response_serializer=_identity)})

    def _generic_handlers(self):
        server_handlers = grpc.method_handlers_generic_handler(
            SERVER_SERVICE,
            {"rpc": grpc.unary_unary_rpc_method_handler(
                self._handle_rpc, request_deserializer=_identity,
                response_serializer=_identity),
             "appendStream": grpc.stream_stream_rpc_method_handler(
                self._handle_append_stream, request_deserializer=_identity,
                response_serializer=_identity)})
        if self.client_port is not None:
            # dedicated client endpoint configured: the replication port
            # must NOT serve the client plane (that's the point of the
            # split — firewalling / isolation)
            return [server_handlers]
        return [server_handlers, self._client_handlers()]

    def _bind(self, server: grpc.aio.Server, address: str,
              tls: Optional[GrpcTlsConfig] = None) -> int:
        tls = tls if tls is not None else self.tls
        if tls is not None:
            return server.add_secure_port(address,
                                          tls.server_credentials())
        return server.add_insecure_port(address)

    async def start(self) -> None:
        # grpc.aio channels/streams are hard-bound to the loop that created
        # them; with server loop sharding, shard loops hop their sends here
        # (see send_server_rpc) instead of dialing per-loop channels
        self._home_loop = asyncio.get_running_loop()
        self._server = grpc.aio.server(options=_CHANNEL_OPTIONS)
        self._server.add_generic_rpc_handlers(self._generic_handlers())
        self._bound_port = self._bind(self._server, self._address)
        if self._bound_port == 0:
            raise RaftException(f"{self.peer_id}: cannot bind {self._address}")
        await self._server.start()
        if self.client_port is not None:
            # dedicated client/admin endpoint: client traffic cannot starve
            # (or be starved by) the replication plane
            try:
                host = self._address.rsplit(":", 1)[0]
                client_server = grpc.aio.server(options=_CHANNEL_OPTIONS)
                client_server.add_generic_rpc_handlers(
                    [self._client_handlers()])
                self.bound_client_port = self._bind(
                    client_server, f"{host}:{self.client_port}")
                if self.bound_client_port == 0:
                    raise RaftException(
                        f"{self.peer_id}: cannot bind client port "
                        f"{self.client_port}")
                await client_server.start()
                self._client_server = client_server
            except BaseException:
                # don't leak the already-listening servers: the caller's
                # close() is a no-op from the STARTING state, and the client
                # socket binds at add_*_port, before start()
                try:
                    await client_server.stop(grace=0)
                except Exception:
                    pass
                self.bound_client_port = None
                await self._server.stop(grace=0)
                self._server = None
                raise
        if self.admin_port is not None:
            # third endpoint: admin plane with its own TLS config
            try:
                host = self._address.rsplit(":", 1)[0]
                admin_server = grpc.aio.server(options=_CHANNEL_OPTIONS)
                admin_server.add_generic_rpc_handlers(
                    [self._admin_handlers()])
                self.bound_admin_port = self._bind(
                    admin_server, f"{host}:{self.admin_port}",
                    tls=self.admin_tls)
                if self.bound_admin_port == 0:
                    raise RaftException(
                        f"{self.peer_id}: cannot bind admin port "
                        f"{self.admin_port}")
                await admin_server.start()
                self._admin_server = admin_server
            except BaseException:
                try:
                    await admin_server.stop(grace=0)
                except Exception:
                    pass
                self.bound_admin_port = None
                if self._client_server is not None:
                    await self._client_server.stop(grace=0)
                    self._client_server = None
                await self._server.stop(grace=0)
                self._server = None
                raise
        LOG.info("%s: grpc bound %s%s%s%s", self.peer_id, self.address,
                 " (tls)" if self.tls is not None else "",
                 f" client-port {self.bound_client_port}"
                 if self._client_server is not None else "",
                 f" admin-port {self.bound_admin_port}"
                 if self._admin_server is not None else "")

    async def close(self) -> None:
        for stream in list(self._append_streams.values()):
            await stream.close()
        self._append_streams.clear()
        if self._admin_server is not None:
            await self._admin_server.stop(grace=0.2)
            self._admin_server = None
        if self._client_server is not None:
            await self._client_server.stop(grace=0.2)
            self._client_server = None
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None
        await self._pool.close()

    # ----------------------------------------------------------- caller side

    def _resolve(self, to: RaftPeerId) -> str:
        addr = self.peer_resolver(to) if self.peer_resolver is not None else None
        if not addr:
            raise TimeoutIOException(f"{self.peer_id}: no address for peer {to}")
        return addr

    async def send_server_rpc(self, to: RaftPeerId, msg):
        home = getattr(self, "_home_loop", None)
        if home is not None:
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not home:
                # loop-sharded caller: grpc.aio state (channels, the shared
                # bidi append streams, dial gates) lives on the home loop —
                # hop there rather than duplicating C-core channels per
                # shard.  The gRPC transport therefore serializes SENDS
                # through one loop even when divisions are sharded; the TCP
                # transport is the per-shard-pipe one.
                cf = asyncio.run_coroutine_threadsafe(
                    self._send_server_rpc_on_home(to, msg), home)
                return await asyncio.wrap_future(cf)
        return await self._send_server_rpc_on_home(to, msg)

    async def _send_server_rpc_on_home(self, to: RaftPeerId, msg):
        address = self._resolve(to)
        if self.chaos:
            from ratis_tpu.chaos.link import link_faults
            faults = link_faults()
            if faults:
                # one gate covers the round trip on this transport: the
                # unary/stream reply rides the same HTTP/2 connection, and
                # the runner models asymmetric reply loss by faulting the
                # (to, self) direction — which gates the peer's own sends
                # and this sender's next forward hop equally
                await faults.gate(self.peer_id, to)
                await faults.gate(to, self.peer_id)
        # The DATA PLANE — entry-bearing appends and coalesced multi-group
        # envelopes — rides the long-lived per-peer bidi stream: one HTTP/2
        # stream amortizes grpc.aio's per-unary-call setup across every
        # append to that peer (the reference's GrpcLogAppender stream,
        # GrpcLogAppender.java:343; measured here, unary envelopes capped
        # gRPC at ~half of the TCP transport's throughput).  Votes,
        # snapshots and heartbeats stay unary — low-rate, and heartbeats
        # must never queue behind a full append window.
        if (isinstance(msg, AppendEnvelope)
                or (isinstance(msg, AppendEntriesRequest) and msg.entries)):
            return await self._send_via_stream(to, address, msg)
        call = self._pool.unary(address, _RPC_METHOD)
        try:
            reply_bytes = await call(encode_rpc(msg),
                                     timeout=self.request_timeout_s)
        except grpc.aio.AioRpcError as e:
            if e.code() in _TRANSIENT_CODES:
                # Keep the shared channel: grpc.aio reconnects by itself,
                # while close() would cancel concurrent in-flight RPCs to
                # this peer (e.g. a snapshot chunk riding the same channel).
                raise TimeoutIOException(
                    f"{self.peer_id}->{to} {e.code().name}: {e.details()}") \
                    from None
            raise RaftException(
                f"{self.peer_id}->{to} rpc failed {e.code().name}: "
                f"{e.details()}") from None
        return decode_rpc(reply_bytes)

    async def _send_via_stream(self, to: RaftPeerId, address: str, msg):
        stream = self._append_streams.get(address)
        if stream is None or stream.closed:
            if not self._dial_gate.may_dial(address):
                raise TimeoutIOException(
                    f"{self.peer_id}->{to} append stream re-dial pacing")
            if stream is not None:
                # release the dead stream's C-core call before replacing it
                # (it may have failed via _fail without anyone closing it)
                await stream.close()
            stream = _AppendStreamClient(
                lambda: self._pool.stream(address, _APPEND_STREAM_METHOD)(),
                flush_micros=self.flush_micros,
                flush_chunks=self.flush_chunks)
            self._append_streams[address] = stream
        try:
            reply_bytes = await stream.send(encode_rpc(msg),
                                            self.request_timeout_s)
        except (RaftException, TimeoutIOException):
            raise
        except asyncio.TimeoutError:
            # ONE call's deadline elapsed on an otherwise-live stream (busy
            # peer / loaded loop).  Do NOT tear the stream down: it is
            # shared by every in-flight append to this peer, and killing it
            # fails them ALL — measured at 1024 gRPC groups, that turned
            # one slow reply into a redial storm that collapsed bring-up.
            # The reader simply drops the late reply when it arrives.
            # Exception: a MID-WRITE timeout already failed the stream
            # (abandoned core write op — unsafe to reuse); drop it.
            if stream.closed:
                if self._append_streams.get(address) is stream:
                    # guarded: a concurrent sender may have re-dialed a
                    # HEALTHY replacement — evicting that would orphan its
                    # call un-cancelled
                    self._append_streams.pop(address, None)
                await stream.close()
            raise TimeoutIOException(
                f"{self.peer_id}->{to} append stream call timed out"
            ) from None
        except Exception as e:
            # stream-level failure (write error, reader death): drop it so
            # the next send re-dials, surface as transient so the appender
            # resets its window
            if self._append_streams.get(address) is stream:
                self._append_streams.pop(address, None)
            await stream.close()
            raise TimeoutIOException(
                f"{self.peer_id}->{to} append stream: {e}") from None
        return decode_rpc(reply_bytes)

    @property
    def address(self) -> str:
        if self._bound_port and self._address.endswith(":0"):
            host = self._address.rsplit(":", 1)[0]
            return f"{host}:{self._bound_port}"
        return self._address


class GrpcClientTransport(ClientTransport):
    def __init__(self, request_timeout_s: float = 30.0,
                 tls: Optional[GrpcTlsConfig] = None,
                 flush_micros: int = 0, flush_chunks: int = 64):
        self._pool = _ChannelPool(tls)
        self.request_timeout_s = request_timeout_s
        self.flush_micros = flush_micros
        self.flush_chunks = max(1, flush_chunks)
        # address -> shared bidi request stream (one per server)
        self._streams: dict[str, _AppendStreamClient] = {}
        self._dial_gate = _StreamDialGate()

    async def send_request(self, peer_address: str,
                           request: RaftClientRequest) -> RaftClientReply:
        """Requests ride one long-lived bidi stream per server (reference
        GrpcClientProtocolService's ordered stream): the per-unary-call
        setup that dominated client-plane cost at high request rates is
        paid once per (client, server) instead of once per request."""
        timeout = (request.timeout_ms / 1000.0 if request.timeout_ms > 0
                   else self.request_timeout_s)
        from ratis_tpu.protocol.requests import RequestType
        if request.type.type >= RequestType.SET_CONFIGURATION:
            # admin block stays unary: the dedicated admin endpoint serves
            # only the unary method (its filter aborts with grpc status
            # codes), and admin calls are low-rate anyway
            return await self._send_unary(peer_address, request, timeout)
        stream = self._streams.get(peer_address)
        if stream is None or stream.closed:
            if not self._dial_gate.may_dial(peer_address):
                raise TimeoutIOException(
                    f"client->{peer_address} request stream re-dial pacing")
            if stream is not None:
                await stream.close()  # release the dead stream's call
            stream = _AppendStreamClient(
                lambda: self._pool.stream(peer_address,
                                          _REQUEST_STREAM_METHOD)(),
                flush_micros=self.flush_micros,
                flush_chunks=self.flush_chunks)
            self._streams[peer_address] = stream
        tid = request.trace_id if TRACER.enabled else 0
        try:
            t0 = TRACER.now() if tid else 0
            payload = request.to_bytes()
            if tid:
                TRACER.record(tid, STAGE_ENCODE, t0, TRACER.now(),
                              tag=len(payload))
                t0 = TRACER.now()
            reply_bytes = await stream.send(payload, timeout)
            if tid:
                TRACER.record(tid, STAGE_WIRE, t0, TRACER.now(),
                              tag=len(reply_bytes))
        except (RaftException, TimeoutIOException):
            raise
        except asyncio.TimeoutError:
            # per-call deadline on a live stream: fail THIS call only (the
            # stream carries every other in-flight request to this server);
            # a mid-write timeout already failed the stream — drop it
            if stream.closed:
                if self._streams.get(peer_address) is stream:
                    self._streams.pop(peer_address, None)
                await stream.close()
            raise TimeoutIOException(
                f"client->{peer_address} request timed out") from None
        except Exception as e:
            if self._streams.get(peer_address) is stream:
                self._streams.pop(peer_address, None)
            await stream.close()
            raise TimeoutIOException(
                f"client->{peer_address} request stream: {e}") from None
        return RaftClientReply.from_bytes(reply_bytes)

    async def _send_unary(self, peer_address: str,
                          request: RaftClientRequest,
                          timeout: float) -> RaftClientReply:
        call = self._pool.unary(peer_address, _REQUEST_METHOD)
        try:
            reply_bytes = await call(request.to_bytes(), timeout=timeout)
        except grpc.aio.AioRpcError as e:
            if e.code() in _TRANSIENT_CODES:
                raise TimeoutIOException(
                    f"client->{peer_address} {e.code().name}: "
                    f"{e.details()}") from None
            raise RaftException(
                f"client->{peer_address} rpc failed {e.code().name}: "
                f"{e.details()}") from None
        return RaftClientReply.from_bytes(reply_bytes)

    async def close(self) -> None:
        for stream in list(self._streams.values()):
            await stream.close()
        self._streams.clear()
        await self._pool.close()


def _grpc_flush_conf(properties) -> tuple[int, int]:
    """(flush_micros, flush_chunks) for the stream framing; (0, 64) — one
    chunk per stream message — when unconfigured."""
    if properties is None:
        return 0, 64
    from ratis_tpu.conf.keys import WireConfigKeys
    return (WireConfigKeys.Grpc.flush_micros(properties),
            WireConfigKeys.Grpc.flush_chunks(properties))


def _grpc_defer_conf(properties) -> bool:
    """Whether client requests on the bidi stream get a deferred-reply
    sink attached (commit fan-out collapse; same gate as the TCP
    transport's)."""
    if properties is None:
        return False
    from ratis_tpu.conf.keys import RaftServerConfigKeys
    K = RaftServerConfigKeys.Replication
    return K.sweep(properties) and K.reply_fanout(properties)


class GrpcTransportFactory(TransportFactory):
    """The SupportedRpcType.GRPC factory (GrpcFactory.java)."""

    def new_server_transport(self, peer_id, address, server_handler,
                             client_handler, properties=None,
                             peer_resolver=None) -> ServerTransport:
        timeout_s = 3.0
        client_port = None
        if properties is not None:
            from ratis_tpu.conf.keys import (GrpcConfigKeys,
                                             RaftServerConfigKeys)
            timeout_s = properties.get_time_duration(
                RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_KEY,
                RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_DEFAULT).seconds
            client_port = GrpcConfigKeys.client_port(properties)
        admin_port = (GrpcConfigKeys.admin_port(properties)
                      if properties is not None else None)
        fm, fc = _grpc_flush_conf(properties)
        chaos = False
        if properties is not None:
            from ratis_tpu.conf.keys import RaftServerConfigKeys as _K
            chaos = _K.Chaos.enabled(properties)
        return GrpcServerTransport(peer_id, address, server_handler,
                                   client_handler, peer_resolver, timeout_s,
                                   tls=GrpcTlsConfig.from_properties(properties),
                                   client_port=client_port,
                                   admin_port=admin_port,
                                   admin_tls=GrpcTlsConfig.admin_from_properties(
                                       properties),
                                   flush_micros=fm, flush_chunks=fc,
                                   defer_replies=_grpc_defer_conf(properties),
                                   chaos=chaos)

    def new_client_transport(self, properties=None) -> ClientTransport:
        fm, fc = _grpc_flush_conf(properties)
        return GrpcClientTransport(
            tls=GrpcTlsConfig.from_properties(properties),
            flush_micros=fm, flush_chunks=fc)


TransportFactory.register("GRPC", GrpcTransportFactory())
