"""gRPC transport: the real-network RPC backend (asyncio, grpc.aio).

Capability parity with the reference gRPC transport (ratis-grpc/
GrpcFactory.java, server/GrpcServicesImpl.java:56, GrpcServerProtocolService
:46, client/GrpcClientRpc): one server endpoint per RaftServer carrying all
groups' traffic, with

- a server-to-server service (requestVote / appendEntries / installSnapshot
  / readIndex / startLeaderElection),
- a client service (all RaftClientRequest types incl. admin).

Transport-format difference by design: instead of compiled protobuf stubs
the services are grpc *generic* handlers over the framework's tagged msgpack
envelope (protocol.raftrpc.encode_rpc — the same union shape as the
reference's Netty.proto:31-48), so every transport shares one codec and the
wire layer needs no generated code.  Peer channels are cached per address
(reference PeerProxyMap / GrpcServerProtocolClient).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

import grpc
import grpc.aio

from ratis_tpu.protocol.exceptions import RaftException, TimeoutIOException
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.raftrpc import decode_rpc, encode_rpc
from ratis_tpu.protocol.requests import RaftClientReply, RaftClientRequest
from ratis_tpu.transport.base import (ClientRequestHandler, ClientTransport,
                                      ServerRpcHandler, ServerTransport,
                                      TransportFactory)

LOG = logging.getLogger(__name__)

SERVER_SERVICE = "ratis_tpu.RaftServerProtocol"
CLIENT_SERVICE = "ratis_tpu.RaftClientProtocol"
_RPC_METHOD = f"/{SERVER_SERVICE}/rpc"
_REQUEST_METHOD = f"/{CLIENT_SERVICE}/request"

# Generous bounds: appenders batch up to the configured buffer byte limit,
# snapshot chunks up to snapshot.chunk.size.max (16MB default).
_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
]

_identity = lambda b: b  # noqa: E731  (bytes in/out; codecs are ours)

# Status codes that mean "transient — retry/failover"; everything else is a
# deterministic failure surfaced to the caller.
_TRANSIENT_CODES = frozenset((grpc.StatusCode.UNAVAILABLE,
                              grpc.StatusCode.DEADLINE_EXCEEDED,
                              grpc.StatusCode.CANCELLED))


class _ChannelPool:
    """address -> aio channel cache (reference PeerProxyMap)."""

    def __init__(self):
        self._channels: dict[str, grpc.aio.Channel] = {}

    def get(self, address: str) -> grpc.aio.Channel:
        ch = self._channels.get(address)
        if ch is None:
            ch = grpc.aio.insecure_channel(address, options=_CHANNEL_OPTIONS)
            self._channels[address] = ch
        return ch

    async def close(self) -> None:
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()


class GrpcServerTransport(ServerTransport):
    def __init__(self, peer_id: RaftPeerId, address: str,
                 server_handler: ServerRpcHandler,
                 client_handler: ClientRequestHandler,
                 peer_resolver: Optional[Callable[[RaftPeerId], Optional[str]]]
                 = None,
                 request_timeout_s: float = 3.0):
        self.peer_id = peer_id
        self._address = address
        self._bound_port: Optional[int] = None
        self.server_handler = server_handler
        self.client_handler = client_handler
        self.peer_resolver = peer_resolver
        self.request_timeout_s = request_timeout_s
        self._server: Optional[grpc.aio.Server] = None
        self._pool = _ChannelPool()

    # ---------------------------------------------------------- service side

    async def _handle_rpc(self, request_bytes: bytes, context) -> bytes:
        try:
            msg = decode_rpc(request_bytes)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"undecodable rpc: {e}")
        try:
            reply = await self.server_handler(msg)
        except RaftException as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except Exception as e:
            LOG.exception("%s: server rpc failed", self.peer_id)
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return encode_rpc(reply)

    async def _handle_client(self, request_bytes: bytes, context) -> bytes:
        try:
            request = RaftClientRequest.from_bytes(request_bytes)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"undecodable client request: {e}")
        reply = await self.client_handler(request)
        return reply.to_bytes()

    def _generic_handlers(self):
        server_handlers = grpc.method_handlers_generic_handler(
            SERVER_SERVICE,
            {"rpc": grpc.unary_unary_rpc_method_handler(
                self._handle_rpc, request_deserializer=_identity,
                response_serializer=_identity)})
        client_handlers = grpc.method_handlers_generic_handler(
            CLIENT_SERVICE,
            {"request": grpc.unary_unary_rpc_method_handler(
                self._handle_client, request_deserializer=_identity,
                response_serializer=_identity)})
        return [server_handlers, client_handlers]

    async def start(self) -> None:
        self._server = grpc.aio.server(options=_CHANNEL_OPTIONS)
        self._server.add_generic_rpc_handlers(self._generic_handlers())
        self._bound_port = self._server.add_insecure_port(self._address)
        if self._bound_port == 0:
            raise RaftException(f"{self.peer_id}: cannot bind {self._address}")
        await self._server.start()
        LOG.info("%s: grpc bound %s", self.peer_id, self.address)

    async def close(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None
        await self._pool.close()

    # ----------------------------------------------------------- caller side

    def _resolve(self, to: RaftPeerId) -> str:
        addr = self.peer_resolver(to) if self.peer_resolver is not None else None
        if not addr:
            raise TimeoutIOException(f"{self.peer_id}: no address for peer {to}")
        return addr

    async def send_server_rpc(self, to: RaftPeerId, msg):
        address = self._resolve(to)
        channel = self._pool.get(address)
        call = channel.unary_unary(_RPC_METHOD, request_serializer=_identity,
                                   response_deserializer=_identity)
        try:
            reply_bytes = await call(encode_rpc(msg),
                                     timeout=self.request_timeout_s)
        except grpc.aio.AioRpcError as e:
            if e.code() in _TRANSIENT_CODES:
                # Keep the shared channel: grpc.aio reconnects by itself,
                # while close() would cancel concurrent in-flight RPCs to
                # this peer (e.g. a snapshot chunk riding the same channel).
                raise TimeoutIOException(
                    f"{self.peer_id}->{to} {e.code().name}: {e.details()}") \
                    from None
            raise RaftException(
                f"{self.peer_id}->{to} rpc failed {e.code().name}: "
                f"{e.details()}") from None
        return decode_rpc(reply_bytes)

    @property
    def address(self) -> str:
        if self._bound_port and self._address.endswith(":0"):
            host = self._address.rsplit(":", 1)[0]
            return f"{host}:{self._bound_port}"
        return self._address


class GrpcClientTransport(ClientTransport):
    def __init__(self, request_timeout_s: float = 30.0):
        self._pool = _ChannelPool()
        self.request_timeout_s = request_timeout_s

    async def send_request(self, peer_address: str,
                           request: RaftClientRequest) -> RaftClientReply:
        channel = self._pool.get(peer_address)
        call = channel.unary_unary(_REQUEST_METHOD,
                                   request_serializer=_identity,
                                   response_deserializer=_identity)
        timeout = (request.timeout_ms / 1000.0 if request.timeout_ms > 0
                   else self.request_timeout_s)
        try:
            reply_bytes = await call(request.to_bytes(), timeout=timeout)
        except grpc.aio.AioRpcError as e:
            if e.code() in _TRANSIENT_CODES:
                raise TimeoutIOException(
                    f"client->{peer_address} {e.code().name}: "
                    f"{e.details()}") from None
            raise RaftException(
                f"client->{peer_address} rpc failed {e.code().name}: "
                f"{e.details()}") from None
        return RaftClientReply.from_bytes(reply_bytes)

    async def close(self) -> None:
        await self._pool.close()


class GrpcTransportFactory(TransportFactory):
    """The SupportedRpcType.GRPC factory (GrpcFactory.java)."""

    def new_server_transport(self, peer_id, address, server_handler,
                             client_handler, properties=None,
                             peer_resolver=None) -> ServerTransport:
        timeout_s = 3.0
        if properties is not None:
            from ratis_tpu.conf.keys import RaftServerConfigKeys
            timeout_s = properties.get_time_duration(
                RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_KEY,
                RaftServerConfigKeys.Rpc.REQUEST_TIMEOUT_DEFAULT).seconds
        return GrpcServerTransport(peer_id, address, server_handler,
                                   client_handler, peer_resolver, timeout_s)

    def new_client_transport(self, properties=None) -> ClientTransport:
        return GrpcClientTransport()


TransportFactory.register("GRPC", GrpcTransportFactory())
