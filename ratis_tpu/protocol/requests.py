"""Client request/reply value types.

Capability parity with the reference's RaftClientRequest (typed sub-requests
write / read / staleRead / watch / messageStream / dataStream / forward,
Raft.proto:285-313 and
ratis-common/src/main/java/org/apache/ratis/protocol/RaftClientRequest.java)
and RaftClientReply (success/exception/logIndex/commitInfos,
RaftClientReply.java).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import msgpack

from ratis_tpu.protocol.exceptions import (RaftException, exception_from_wire,
                                           exception_to_wire)
from ratis_tpu.protocol.ids import ClientId, RaftGroupId
from ratis_tpu.protocol.ids import RaftPeerId
from ratis_tpu.protocol.message import Message


class ReplicationLevel(enum.IntEnum):
    """Watch replication levels (Raft.proto ReplicationLevel:124-129)."""

    MAJORITY = 0
    ALL = 1
    MAJORITY_COMMITTED = 2
    ALL_COMMITTED = 3


class RequestType(enum.IntEnum):
    WRITE = 1
    READ = 2
    STALE_READ = 3
    WATCH = 4
    MESSAGE_STREAM = 5
    DATA_STREAM = 6
    FORWARD = 7
    # Admin operations (payload msgpack-encoded in the message body; see
    # ratis_tpu.protocol.admin — mirrors Raft.proto admin protos :427-516).
    SET_CONFIGURATION = 8
    TRANSFER_LEADERSHIP = 9
    SNAPSHOT_MANAGEMENT = 10
    LEADER_ELECTION_MANAGEMENT = 11
    GROUP_MANAGEMENT = 12
    GROUP_LIST = 13
    GROUP_INFO = 14


@dataclasses.dataclass(frozen=True)
class TypeCase:
    """The typed sub-request payload union."""

    type: RequestType
    # READ: nonlinearizable reads allowed if read policy permits
    read_nonlinearizable: bool = False
    read_after_write_consistent: bool = False
    # STALE_READ: min applied index the serving peer must have
    stale_read_min_index: int = 0
    # WATCH
    watch_index: int = 0
    watch_replication: ReplicationLevel = ReplicationLevel.MAJORITY
    # MESSAGE_STREAM
    stream_id: int = 0
    message_id: int = 0
    end_of_request: bool = False


def write_request_type() -> TypeCase:
    return TypeCase(RequestType.WRITE)


def read_request_type(nonlinearizable: bool = False,
                      read_after_write_consistent: bool = False) -> TypeCase:
    return TypeCase(RequestType.READ, read_nonlinearizable=nonlinearizable,
                    read_after_write_consistent=read_after_write_consistent)


def stale_read_request_type(min_index: int) -> TypeCase:
    return TypeCase(RequestType.STALE_READ, stale_read_min_index=min_index)


def watch_request_type(index: int, replication: ReplicationLevel) -> TypeCase:
    return TypeCase(RequestType.WATCH, watch_index=index,
                    watch_replication=replication)


def message_stream_request_type(stream_id: int, message_id: int,
                                end_of_request: bool) -> TypeCase:
    return TypeCase(RequestType.MESSAGE_STREAM, stream_id=stream_id,
                    message_id=message_id, end_of_request=end_of_request)


def data_stream_request_type(stream_id: int) -> TypeCase:
    """Marks the header/submit request of a DataStream
    (Raft.proto DataStreamRequestTypeProto:305)."""
    return TypeCase(RequestType.DATA_STREAM, stream_id=stream_id)


def admin_request_type(t: RequestType) -> TypeCase:
    return TypeCase(t)


@dataclasses.dataclass(frozen=True)
class RaftClientRequest:
    client_id: ClientId
    server_id: RaftPeerId
    group_id: RaftGroupId
    call_id: int
    message: Message = Message.EMPTY
    type: TypeCase = dataclasses.field(default_factory=write_request_type)
    slider_seq_num: int = -1  # ordered-async sliding window sequence number
    # First request of a (possibly post-failover) window: tells the server to
    # (re)base its per-client reorder window at this seqNum (reference
    # SlidingWindow.Request.isFirstRequest, SlidingWindow.java:277).
    slider_first: bool = False
    timeout_ms: float = 3000.0
    # Piggybacked already-replied call ids for server retry-cache GC
    # (reference RaftClientImpl.RepliedCallIds, RaftClientImpl.java:128).
    replied_call_ids: tuple[int, ...] = ()
    # Host-path trace context (ratis_tpu.trace): 0 = untraced; a sampled
    # request carries its trace id across the wire so client, transport,
    # server, and apply spans share one id.
    trace_id: int = 0

    def is_write(self) -> bool:
        return self.type.type == RequestType.WRITE

    def is_read(self) -> bool:
        return self.type.type == RequestType.READ

    def is_watch(self) -> bool:
        return self.type.type == RequestType.WATCH

    def to_dict(self) -> dict:
        t = self.type
        d = {
            "cid": self.client_id.to_bytes(), "sid": self.server_id.id,
            "gid": self.group_id.to_bytes(), "call": self.call_id,
            "msg": self.message.content, "seq": self.slider_seq_num,
            "sf": self.slider_first,
            "to": self.timeout_ms, "rcids": list(self.replied_call_ids),
            "t": {"t": int(t.type), "rnl": t.read_nonlinearizable,
                  "raw": t.read_after_write_consistent,
                  "smi": t.stale_read_min_index, "wi": t.watch_index,
                  "wr": int(t.watch_replication), "si": t.stream_id,
                  "mi": t.message_id, "eor": t.end_of_request},
        }
        if self.trace_id:
            d["tr"] = self.trace_id  # only sampled requests pay the byte
        return d

    @staticmethod
    def from_dict(d: dict) -> "RaftClientRequest":
        t = d["t"]
        return RaftClientRequest(
            client_id=ClientId.value_of(d["cid"]),
            server_id=RaftPeerId.value_of(d["sid"]),
            group_id=RaftGroupId.value_of(d["gid"]),
            call_id=d["call"], message=Message(d["msg"]),
            slider_seq_num=d.get("seq", -1),
            slider_first=d.get("sf", False),
            timeout_ms=d.get("to", 3000.0),
            replied_call_ids=tuple(d.get("rcids", ())),
            trace_id=d.get("tr", 0),
            type=TypeCase(RequestType(t["t"]), read_nonlinearizable=t["rnl"],
                          read_after_write_consistent=t.get("raw", False),
                          stale_read_min_index=t["smi"], watch_index=t["wi"],
                          watch_replication=ReplicationLevel(t["wr"]),
                          stream_id=t["si"], message_id=t["mi"],
                          end_of_request=t["eor"]))

    def to_bytes(self) -> bytes:
        return msgpack.packb(self.to_dict(), use_bin_type=True)

    @staticmethod
    def from_bytes(b: bytes) -> "RaftClientRequest":
        return RaftClientRequest.from_dict(msgpack.unpackb(b, raw=False))

    def __str__(self) -> str:
        return (f"{self.client_id}->{self.server_id}@{self.group_id}"
                f"#{self.call_id}:{self.type.type.name}")


class _DeferredReply:
    """Sentinel threaded back through the client-request handler chain when
    the real :class:`RaftClientReply` will be delivered OUT OF BAND through
    the request's attached reply sink (the commit fan-out collapse,
    ``raft.tpu.replication.reply-fanout``): the handler coroutine finishes
    at append time, and the division's waterline fan-out pushes the reply
    straight into the transport's per-connection batcher at commit.  Never
    serialized — transports intercept it before any wire encode."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "<DEFERRED_REPLY>"


DEFERRED_REPLY = _DeferredReply()


def attach_reply_sink(request: "RaftClientRequest", sink) -> None:
    """Attach a transport reply sink to ``request`` (out-of-band attribute;
    the dataclass is frozen but not slotted, and the sink never rides the
    wire).  ``sink(reply)`` must be callable exactly once, synchronously,
    from the owning division's loop; the transport is responsible for any
    cross-loop hand-off back to the connection."""
    object.__setattr__(request, "_reply_sink", sink)


def reply_sink_of(request: "RaftClientRequest"):
    """The attached reply sink, or None (the per-request reply path)."""
    return getattr(request, "_reply_sink", None)


@dataclasses.dataclass(frozen=True)
class CommitInfo:
    """peer -> commitIndex, piggybacked on replies (CommitInfoProto:175)."""

    server: RaftPeerId
    commit_index: int


@dataclasses.dataclass(frozen=True)
class RaftClientReply:
    client_id: ClientId
    server_id: RaftPeerId
    group_id: RaftGroupId
    call_id: int
    success: bool
    message: Message = Message.EMPTY
    exception: Optional[RaftException] = None
    log_index: int = -1
    commit_infos: tuple[CommitInfo, ...] = ()

    def get_not_leader_exception(self):
        from ratis_tpu.protocol.exceptions import NotLeaderException
        return self.exception if isinstance(self.exception, NotLeaderException) else None

    def to_dict(self) -> dict:
        return {
            "cid": self.client_id.to_bytes(), "sid": self.server_id.id,
            "gid": self.group_id.to_bytes(), "call": self.call_id,
            "ok": self.success, "msg": self.message.content,
            "li": self.log_index,
            "exc": None if self.exception is None else exception_to_wire(self.exception),
            "ci": [[c.server.id, c.commit_index] for c in self.commit_infos],
        }

    @staticmethod
    def from_dict(d: dict) -> "RaftClientReply":
        return RaftClientReply(
            client_id=ClientId.value_of(d["cid"]),
            server_id=RaftPeerId.value_of(d["sid"]),
            group_id=RaftGroupId.value_of(d["gid"]),
            call_id=d["call"], success=d["ok"], message=Message(d["msg"]),
            log_index=d.get("li", -1),
            exception=None if d.get("exc") is None else exception_from_wire(d["exc"]),
            commit_infos=tuple(CommitInfo(RaftPeerId.value_of(s), i)
                               for s, i in d.get("ci", ())))

    def to_bytes(self) -> bytes:
        return msgpack.packb(self.to_dict(), use_bin_type=True)

    @staticmethod
    def from_bytes(b: bytes) -> "RaftClientReply":
        return RaftClientReply.from_dict(msgpack.unpackb(b, raw=False))

    @staticmethod
    def success_reply(request: RaftClientRequest, message: Message = Message.EMPTY,
                      log_index: int = -1, commit_infos=()) -> "RaftClientReply":
        return RaftClientReply(request.client_id, request.server_id,
                               request.group_id, request.call_id, True,
                               message=message, log_index=log_index,
                               commit_infos=tuple(commit_infos))

    @staticmethod
    def failure_reply(request: RaftClientRequest, exception: RaftException,
                      commit_infos=()) -> "RaftClientReply":
        return RaftClientReply(request.client_id, request.server_id,
                               request.group_id, request.call_id, False,
                               exception=exception, commit_infos=tuple(commit_infos))

    def __str__(self) -> str:
        status = "OK" if self.success else f"FAIL({type(self.exception).__name__})"
        return f"reply#{self.call_id}:{status}@i{self.log_index}"
