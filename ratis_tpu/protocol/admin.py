"""Admin request/reply payloads (membership, leadership, snapshot, groups).

Capability parity with the reference admin protos
(Raft.proto: SetConfigurationRequestProto:427, TransferLeadershipRequestProto
:442, SnapshotManagementRequestProto:466, LeaderElectionManagementRequest
:478, GroupManagementRequestProto:488-516, GroupListRequest/GroupInfoRequest)
and their client-side wrappers (ratis-client/.../impl/{AdminImpl,
GroupManagementImpl,SnapshotManagementImpl,LeaderElectionManagementImpl}).

Admin operations travel on the ordinary client channel: the typed payload is
msgpack-encoded into the RaftClientRequest message body, with a dedicated
RequestType tag per operation (requests.RequestType.SET_CONFIGURATION etc.).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import msgpack

from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import RaftGroupId
from ratis_tpu.protocol.peer import RaftPeer


class SetConfigurationMode(enum.IntEnum):
    """Raft.proto SetConfigurationRequestProto.Mode."""

    SET_UNCONDITIONALLY = 0
    ADD = 1
    REMOVE = 2
    COMPARE_AND_SET = 3


@dataclasses.dataclass(frozen=True)
class SetConfigurationArguments:
    """New membership for a group (reference SetConfigurationRequest)."""

    peers: tuple[RaftPeer, ...] = ()       # voting servers in the new conf
    listeners: tuple[RaftPeer, ...] = ()
    mode: SetConfigurationMode = SetConfigurationMode.SET_UNCONDITIONALLY
    # COMPARE_AND_SET precondition: the exact current voting membership.
    current_peers: tuple[RaftPeer, ...] = ()

    def to_payload(self) -> bytes:
        return msgpack.packb({
            "p": [p.to_dict() for p in self.peers],
            "l": [p.to_dict() for p in self.listeners],
            "m": int(self.mode),
            "cp": [p.to_dict() for p in self.current_peers],
        }, use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "SetConfigurationArguments":
        d = msgpack.unpackb(b, raw=False)
        return SetConfigurationArguments(
            peers=tuple(RaftPeer.from_dict(x) for x in d["p"]),
            listeners=tuple(RaftPeer.from_dict(x) for x in d["l"]),
            mode=SetConfigurationMode(d["m"]),
            current_peers=tuple(RaftPeer.from_dict(x) for x in d.get("cp", ())))


@dataclasses.dataclass(frozen=True)
class TransferLeadershipArguments:
    """Move leadership to a peer (TransferLeadershipRequestProto:442);
    empty new_leader means 'yield to any higher-priority peer'."""

    new_leader: Optional[str] = None  # peer id string
    timeout_ms: float = 3000.0

    def to_payload(self) -> bytes:
        return msgpack.packb({"nl": self.new_leader, "to": self.timeout_ms},
                             use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "TransferLeadershipArguments":
        d = msgpack.unpackb(b, raw=False)
        return TransferLeadershipArguments(d.get("nl"), d.get("to", 3000.0))


class SnapshotManagementOp(enum.IntEnum):
    CREATE = 1


@dataclasses.dataclass(frozen=True)
class SnapshotManagementArguments:
    """SnapshotManagementRequestProto:466 (create with a creation gap: skip
    if the latest snapshot is within `creation_gap` entries of applied)."""

    op: SnapshotManagementOp = SnapshotManagementOp.CREATE
    creation_gap: int = 0  # 0 = use server default

    def to_payload(self) -> bytes:
        return msgpack.packb({"op": int(self.op), "gap": self.creation_gap},
                             use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "SnapshotManagementArguments":
        d = msgpack.unpackb(b, raw=False)
        return SnapshotManagementArguments(SnapshotManagementOp(d["op"]),
                                           d.get("gap", 0))


class LeaderElectionManagementOp(enum.IntEnum):
    PAUSE = 1
    RESUME = 2


@dataclasses.dataclass(frozen=True)
class LeaderElectionManagementArguments:
    """LeaderElectionManagementRequest (Raft.proto:478)."""

    op: LeaderElectionManagementOp = LeaderElectionManagementOp.PAUSE

    def to_payload(self) -> bytes:
        return msgpack.packb({"op": int(self.op)}, use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "LeaderElectionManagementArguments":
        d = msgpack.unpackb(b, raw=False)
        return LeaderElectionManagementArguments(
            LeaderElectionManagementOp(d["op"]))


class GroupManagementOp(enum.IntEnum):
    ADD = 1
    REMOVE = 2


@dataclasses.dataclass(frozen=True)
class GroupManagementArguments:
    """GroupManagementRequestProto:488 (add carries the full group; remove
    carries the id + directory disposition)."""

    op: GroupManagementOp
    group: Optional[RaftGroup] = None           # ADD
    group_id: Optional[RaftGroupId] = None      # REMOVE
    delete_directory: bool = False
    format_enabled: bool = False  # ADD: reformat existing storage

    def to_payload(self) -> bytes:
        d: dict = {"op": int(self.op), "del": self.delete_directory,
                   "fmt": self.format_enabled}
        if self.group is not None:
            d["g"] = {"gid": self.group.group_id.to_bytes(),
                      "peers": [p.to_dict() for p in self.group.peers]}
        if self.group_id is not None:
            d["gid"] = self.group_id.to_bytes()
        return msgpack.packb(d, use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "GroupManagementArguments":
        d = msgpack.unpackb(b, raw=False)
        group = None
        if "g" in d:
            group = RaftGroup.value_of(
                RaftGroupId.value_of(d["g"]["gid"]),
                [RaftPeer.from_dict(x) for x in d["g"]["peers"]])
        gid = RaftGroupId.value_of(d["gid"]) if "gid" in d else None
        return GroupManagementArguments(
            GroupManagementOp(d["op"]), group=group, group_id=gid,
            delete_directory=d.get("del", False),
            format_enabled=d.get("fmt", False))


@dataclasses.dataclass(frozen=True)
class GroupInfoReplyData:
    """GroupInfoReply payload (reference GroupInfoReply + RoleInfoProto:537)."""

    group: RaftGroup
    role: str
    term: int
    leader_id: Optional[str]
    commit_index: int
    applied_index: int
    is_leader_ready: bool

    def to_payload(self) -> bytes:
        return msgpack.packb({
            "gid": self.group.group_id.to_bytes(),
            "peers": [p.to_dict() for p in self.group.peers],
            "role": self.role, "term": self.term,
            "leader": self.leader_id, "ci": self.commit_index,
            "ai": self.applied_index, "ready": self.is_leader_ready,
        }, use_bin_type=True)

    @staticmethod
    def from_payload(b: bytes) -> "GroupInfoReplyData":
        d = msgpack.unpackb(b, raw=False)
        return GroupInfoReplyData(
            group=RaftGroup.value_of(
                RaftGroupId.value_of(d["gid"]),
                [RaftPeer.from_dict(x) for x in d["peers"]]),
            role=d["role"], term=d["term"], leader_id=d.get("leader"),
            commit_index=d["ci"], applied_index=d["ai"],
            is_leader_ready=d["ready"])


def encode_group_list(group_ids: list[RaftGroupId]) -> bytes:
    return msgpack.packb([g.to_bytes() for g in group_ids], use_bin_type=True)


def decode_group_list(b: bytes) -> list[RaftGroupId]:
    return [RaftGroupId.value_of(x) for x in msgpack.unpackb(b, raw=False)]
