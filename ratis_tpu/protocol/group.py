"""RaftGroup and RaftGroupMemberId value types.

Capability parity with the reference
(ratis-common/src/main/java/org/apache/ratis/protocol/RaftGroup.java,
RaftGroupMemberId.java): a group = groupId + the peer set; a member id =
(peerId, groupId) naming one division of a multi-Raft server.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer


@dataclasses.dataclass(frozen=True)
class RaftGroup:
    group_id: RaftGroupId
    peers: tuple[RaftPeer, ...] = ()

    @staticmethod
    def value_of(group_id: RaftGroupId, peers: Iterable[RaftPeer] = ()) -> "RaftGroup":
        return RaftGroup(group_id, tuple(peers))

    @staticmethod
    def empty_group(group_id: Optional[RaftGroupId] = None) -> "RaftGroup":
        return RaftGroup(group_id or RaftGroupId.empty_id(), ())

    def get_peer(self, peer_id: RaftPeerId) -> Optional[RaftPeer]:
        for p in self.peers:
            if p.id == peer_id:
                return p
        return None

    def peer_ids(self) -> tuple[RaftPeerId, ...]:
        return tuple(p.id for p in self.peers)

    def to_dict(self) -> dict:
        return {"group_id": self.group_id.to_bytes().hex(),
                "peers": [p.to_dict() for p in self.peers]}

    @staticmethod
    def from_dict(d: dict) -> "RaftGroup":
        return RaftGroup(
            RaftGroupId.value_of(bytes.fromhex(d["group_id"])),
            tuple(RaftPeer.from_dict(p) for p in d.get("peers", ())),
        )

    def __str__(self) -> str:
        return f"{self.group_id}:[{', '.join(str(p) for p in self.peers)}]"


@dataclasses.dataclass(frozen=True, order=True)
class RaftGroupMemberId:
    peer_id: RaftPeerId
    group_id: RaftGroupId

    @staticmethod
    def value_of(peer_id: RaftPeerId, group_id: RaftGroupId) -> "RaftGroupMemberId":
        return RaftGroupMemberId(RaftPeerId.value_of(peer_id), group_id)

    def __str__(self) -> str:
        return f"{self.peer_id}@{self.group_id}"
