from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftId, RaftPeerId
from ratis_tpu.protocol.peer import RaftPeer, RaftPeerRole
from ratis_tpu.protocol.group import RaftGroup, RaftGroupMemberId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.termindex import TermIndex, INVALID_LOG_INDEX, INVALID_TERM
