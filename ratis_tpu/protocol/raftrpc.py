"""Server-to-server Raft RPC messages.

Capability parity with the reference wire format (Raft.proto):
RequestVoteRequestProto:161 (with preVote flag), AppendEntriesRequestProto:180
(batched entries + leaderCommit + commitInfos), AppendEntriesReplyProto with
SUCCESS/NOT_LEADER/INCONSISTENCY results, InstallSnapshotRequestProto:208
(chunked SnapshotChunkProto mode and notification mode),
ReadIndexRequestProto:245, StartLeaderElectionRequestProto (leader transfer).
All messages carry (requestorId, replyId, groupId) routing like
RaftRpcRequestProto:140.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import msgpack

from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.trace.tracer import STAGE_DECODE, STAGE_ENCODE, TRACER


@dataclasses.dataclass(frozen=True)
class RaftRpcHeader:
    """(requestor, reply-to, group) routing triple on every server RPC."""

    requestor_id: RaftPeerId
    reply_id: RaftPeerId
    group_id: RaftGroupId
    call_id: int = 0

    def to_dict(self) -> dict:
        return {"rq": self.requestor_id.id, "rp": self.reply_id.id,
                "g": self.group_id.to_bytes(), "c": self.call_id}

    @staticmethod
    def from_dict(d: dict) -> "RaftRpcHeader":
        return RaftRpcHeader(RaftPeerId.value_of(d["rq"]),
                             RaftPeerId.value_of(d["rp"]),
                             RaftGroupId.value_of(d["g"]), d.get("c", 0))


@dataclasses.dataclass(frozen=True)
class RequestVoteRequest:
    header: RaftRpcHeader
    candidate_term: int
    candidate_last_entry: TermIndex
    pre_vote: bool = False
    # Leadership-transfer election (startLeaderElection target): voters skip
    # the live-leader stickiness check, as the transfer was initiated by the
    # current leader itself (Raft §3.10 TimeoutNow semantics).
    force: bool = False

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.candidate_term,
                "lt": self.candidate_last_entry.term,
                "li": self.candidate_last_entry.index, "pv": self.pre_vote,
                "f": self.force}

    @staticmethod
    def from_dict(d: dict) -> "RequestVoteRequest":
        return RequestVoteRequest(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                  TermIndex(d["lt"], d["li"]),
                                  d.get("pv", False), d.get("f", False))


@dataclasses.dataclass(frozen=True)
class RequestVoteReply:
    header: RaftRpcHeader
    term: int
    granted: bool
    should_shutdown: bool = False
    # Replier's log-up-to-dateness hint used by the candidate's priority logic.
    last_entry: TermIndex = TermIndex.INITIAL_VALUE

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "g": self.granted,
                "sd": self.should_shutdown,
                "lt": self.last_entry.term, "li": self.last_entry.index}

    @staticmethod
    def from_dict(d: dict) -> "RequestVoteReply":
        return RequestVoteReply(RaftRpcHeader.from_dict(d["h"]), d["t"], d["g"],
                                d.get("sd", False),
                                TermIndex(d.get("lt", -1), d.get("li", -1)))


class AppendResult(enum.IntEnum):
    """AppendEntriesReplyProto.AppendResult (Raft.proto:189-193)."""

    SUCCESS = 0
    NOT_LEADER = 1
    INCONSISTENCY = 2


@dataclasses.dataclass(frozen=True)
class AppendEntriesRequest:
    header: RaftRpcHeader
    leader_term: int
    previous: Optional[TermIndex]
    entries: tuple[LogEntry, ...]
    leader_commit: int
    initializing: bool = False  # bootstrapping a newly-staged peer
    commit_infos: tuple[tuple[str, int], ...] = ()

    def is_heartbeat(self) -> bool:
        return not self.entries

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.leader_term,
                "pt": -1 if self.previous is None else self.previous.term,
                "pi": -1 if self.previous is None else self.previous.index,
                "e": [e.to_dict() for e in self.entries],
                "lc": self.leader_commit, "init": self.initializing,
                "ci": [list(x) for x in self.commit_infos]}

    @staticmethod
    def from_dict(d: dict) -> "AppendEntriesRequest":
        prev = None if d["pi"] < 0 and d["pt"] < 0 else TermIndex(d["pt"], d["pi"])
        return AppendEntriesRequest(
            RaftRpcHeader.from_dict(d["h"]), d["t"], prev,
            tuple(LogEntry.from_dict(e) for e in d["e"]), d["lc"],
            d.get("init", False),
            tuple(tuple(x) for x in d.get("ci", ())))


@dataclasses.dataclass(frozen=True)
class AppendEntriesReply:
    header: RaftRpcHeader
    term: int
    result: AppendResult
    next_index: int
    follower_commit: int
    match_index: int
    is_heartbeat: bool = False

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "r": int(self.result),
                "ni": self.next_index, "fc": self.follower_commit,
                "mi": self.match_index, "hb": self.is_heartbeat}

    @staticmethod
    def from_dict(d: dict) -> "AppendEntriesReply":
        return AppendEntriesReply(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                  AppendResult(d["r"]), d["ni"], d["fc"],
                                  d["mi"], d.get("hb", False))


class InstallSnapshotResult(enum.IntEnum):
    """InstallSnapshotReplyProto.InstallSnapshotResult (Raft.proto:225-233)."""

    SUCCESS = 0
    NOT_LEADER = 1
    IN_PROGRESS = 2
    ALREADY_INSTALLED = 3
    CONF_MISMATCH = 4
    SNAPSHOT_INSTALLED = 5
    SNAPSHOT_UNAVAILABLE = 6
    SNAPSHOT_EXPIRED = 7


@dataclasses.dataclass(frozen=True)
class FileChunk:
    """One chunk of one snapshot file (FileChunkProto:150-158)."""

    filename: str
    total_size: int
    file_digest: bytes
    chunk_index: int
    offset: int
    data: bytes
    done: bool

    def to_dict(self) -> dict:
        return {"f": self.filename, "ts": self.total_size, "dg": self.file_digest,
                "ci": self.chunk_index, "o": self.offset, "d": self.data,
                "dn": self.done}

    @staticmethod
    def from_dict(d: dict) -> "FileChunk":
        return FileChunk(d["f"], d["ts"], d["dg"], d["ci"], d["o"], d["d"], d["dn"])


@dataclasses.dataclass(frozen=True)
class InstallSnapshotRequest:
    header: RaftRpcHeader
    leader_term: int
    # chunked mode (SnapshotChunkProto:214-221)
    request_id: str = ""
    request_index: int = 0
    snapshot_term_index: Optional[TermIndex] = None
    chunks: tuple[FileChunk, ...] = ()
    total_size: int = 0
    done: bool = False
    # notification mode (NotificationProto:222-224): leader log purged; the
    # StateMachine fetches state out-of-band.
    notification_first_available: Optional[TermIndex] = None
    last_included: Optional[TermIndex] = None

    def is_notification(self) -> bool:
        return self.notification_first_available is not None

    def to_dict(self) -> dict:
        def ti(x):
            return None if x is None else [x.term, x.index]
        return {"h": self.header.to_dict(), "t": self.leader_term,
                "rid": self.request_id, "ridx": self.request_index,
                "sti": ti(self.snapshot_term_index),
                "ch": [c.to_dict() for c in self.chunks], "ts": self.total_size,
                "dn": self.done, "nfa": ti(self.notification_first_available),
                "lin": ti(self.last_included)}

    @staticmethod
    def from_dict(d: dict) -> "InstallSnapshotRequest":
        def ti(x):
            return None if x is None else TermIndex(x[0], x[1])
        return InstallSnapshotRequest(
            RaftRpcHeader.from_dict(d["h"]), d["t"], d.get("rid", ""),
            d.get("ridx", 0), ti(d.get("sti")),
            tuple(FileChunk.from_dict(c) for c in d.get("ch", ())),
            d.get("ts", 0), d.get("dn", False), ti(d.get("nfa")), ti(d.get("lin")))


@dataclasses.dataclass(frozen=True)
class InstallSnapshotReply:
    header: RaftRpcHeader
    term: int
    result: InstallSnapshotResult
    request_index: int = 0
    snapshot_index: int = -1

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "r": int(self.result),
                "ri": self.request_index, "si": self.snapshot_index}

    @staticmethod
    def from_dict(d: dict) -> "InstallSnapshotReply":
        return InstallSnapshotReply(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                    InstallSnapshotResult(d["r"]),
                                    d.get("ri", 0), d.get("si", -1))


@dataclasses.dataclass(frozen=True)
class ReadIndexRequest:
    header: RaftRpcHeader

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "ReadIndexRequest":
        return ReadIndexRequest(RaftRpcHeader.from_dict(d["h"]))


@dataclasses.dataclass(frozen=True)
class ReadIndexReply:
    header: RaftRpcHeader
    ok: bool
    read_index: int = -1

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "ok": self.ok, "ri": self.read_index}

    @staticmethod
    def from_dict(d: dict) -> "ReadIndexReply":
        return ReadIndexReply(RaftRpcHeader.from_dict(d["h"]), d["ok"],
                              d.get("ri", -1))


@dataclasses.dataclass(frozen=True)
class StartLeaderElectionRequest:
    """Leader -> chosen follower during transfer leadership
    (StartLeaderElectionRequestProto)."""

    header: RaftRpcHeader
    leader_last_entry: TermIndex

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "lt": self.leader_last_entry.term,
                "li": self.leader_last_entry.index}

    @staticmethod
    def from_dict(d: dict) -> "StartLeaderElectionRequest":
        return StartLeaderElectionRequest(RaftRpcHeader.from_dict(d["h"]),
                                          TermIndex(d["lt"], d["li"]))


@dataclasses.dataclass(frozen=True)
class StartLeaderElectionReply:
    header: RaftRpcHeader
    accepted: bool

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "ok": self.accepted}

    @staticmethod
    def from_dict(d: dict) -> "StartLeaderElectionReply":
        return StartLeaderElectionReply(RaftRpcHeader.from_dict(d["h"]), d["ok"])


@dataclasses.dataclass(frozen=True)
class AppendEnvelope:
    """Multi-raft AppendEntries envelope: append traffic from EVERY group a
    server leads toward one destination server, folded into a single RPC —
    both idle heartbeats and pipelined entry batches.

    No reference analog — the reference runs one stream per (group,
    follower) (GrpcLogAppender.java:356) plus one heartbeat per group per
    interval, which is the O(groups) RPC wall this framework's multi-raft
    axis removes.  The envelope carries ordinary AppendEntriesRequests, so
    each group's semantics are exactly the unary path's; the receiver
    processes a group's items sequentially in order (RaftServer
    _handle_append_envelope), which preserves per-group FIFO."""

    items: tuple[AppendEntriesRequest, ...]

    def to_dict(self) -> dict:
        return {"i": [r.to_dict() for r in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "AppendEnvelope":
        return AppendEnvelope(
            tuple(AppendEntriesRequest.from_dict(x) for x in d["i"]))


@dataclasses.dataclass(frozen=True)
class AppendEnvelopeReply:
    """Per-item replies; None where the peer failed that group (e.g. it does
    not serve it) — the sender treats those as per-follower RPC errors."""

    items: tuple[Optional[AppendEntriesReply], ...]

    def to_dict(self) -> dict:
        return {"i": [None if r is None else r.to_dict()
                      for r in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "AppendEnvelopeReply":
        return AppendEnvelopeReply(
            tuple(None if x is None else AppendEntriesReply.from_dict(x)
                  for x in d["i"]))


@dataclasses.dataclass(frozen=True)
class BulkHeartbeat:
    """Compact multi-raft heartbeat: ONE small message per server pair per
    interval carrying a fixed-width tuple per led group, instead of one full
    AppendEntries per (group, follower).

    No reference analog — the reference's per-group heartbeat volume
    (GrpcLogAppender heartbeat channel) is an O(groups) event-loop wall at
    thousands of co-hosted groups even when the RPCs are folded, because
    each heartbeat still costs a full AppendEntries build + handle + reply.
    The bulk item carries exactly what the idle happy path needs: leadership
    assertion (term), and safe commit propagation (leader commit + the term
    of the entry at that index, so the follower advances commit only when
    its own entry matches — the Log Matching property makes that
    sufficient).  Any anomaly (behind follower, term conflict) falls back to
    a full AppendEntries probe on the data path, with prev-check fidelity.

    items: (group_id_bytes, leader_term, leader_commit, commit_entry_term)
    """

    requestor_id: RaftPeerId
    reply_id: RaftPeerId
    items: tuple[tuple[bytes, int, int, int], ...]

    def to_dict(self) -> dict:
        return {"rq": self.requestor_id.id, "rp": self.reply_id.id,
                "i": [list(x) for x in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "BulkHeartbeat":
        return BulkHeartbeat(RaftPeerId.value_of(d["rq"]),
                             RaftPeerId.value_of(d["rp"]),
                             tuple(tuple(x) for x in d["i"]))


# BulkHeartbeatReply item result codes
BULK_HB_OK = 0
BULK_HB_NOT_LEADER = 1
BULK_HB_UNKNOWN_GROUP = 2
# Receiver skipped the item because the division's append lock was held by
# an in-flight AppendEntries: that append itself resets the follower's
# election deadline, and the leader simply retries next sweep — so the
# sweep never waits on a contended division (no head-of-line blocking).
BULK_HB_BUSY = 3
# Follower accepted a hibernate request (a normal bulk item with a 5th
# flag field set): its election timer is DISARMED and the leader may stop
# heartbeating the group (idle-group quiescence,
# RaftServerConfigKeys.Hibernate).
BULK_HB_HIBERNATED = 4


@dataclasses.dataclass(frozen=True)
class BulkHeartbeatReply:
    """Aligned 1:1 with the request's items.

    items: (result_code, term, next_index, follower_commit, flush_index)
    """

    items: tuple[tuple[int, int, int, int, int], ...]

    def to_dict(self) -> dict:
        return {"i": [list(x) for x in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "BulkHeartbeatReply":
        return BulkHeartbeatReply(tuple(tuple(x) for x in d["i"]))


# --- generic envelope for transports ---------------------------------------

_MSG_TYPES: dict[str, type] = {
    "vote_req": RequestVoteRequest, "vote_rep": RequestVoteReply,
    "append_req": AppendEntriesRequest, "append_rep": AppendEntriesReply,
    "snap_req": InstallSnapshotRequest, "snap_rep": InstallSnapshotReply,
    "readidx_req": ReadIndexRequest, "readidx_rep": ReadIndexReply,
    "sle_req": StartLeaderElectionRequest, "sle_rep": StartLeaderElectionReply,
    "env_req": AppendEnvelope, "env_rep": AppendEnvelopeReply,
    "bulkhb_req": BulkHeartbeat, "bulkhb_rep": BulkHeartbeatReply,
}
_TYPE_TAGS = {v: k for k, v in _MSG_TYPES.items()}


def encode_rpc(msg) -> bytes:
    """Tagged msgpack envelope (cf. Netty.proto's request/reply union:31-48).

    Host-path tracing samples the encode here (process-level span,
    ratis_tpu.trace STAGE_ENCODE, tag = wire bytes): the per-commit msgpack
    cost of the server-to-server plane, measured where it is paid."""
    if TRACER.enabled and TRACER.sample():
        t0 = TRACER.now()
        b = msgpack.packb({"_": _TYPE_TAGS[type(msg)], "b": msg.to_dict()},
                          use_bin_type=True)
        TRACER.record(0, STAGE_ENCODE, t0, TRACER.now(), tag=len(b))
        return b
    return msgpack.packb({"_": _TYPE_TAGS[type(msg)], "b": msg.to_dict()},
                         use_bin_type=True)


def decode_rpc(b: bytes):
    if TRACER.enabled and TRACER.sample():
        t0 = TRACER.now()
        d = msgpack.unpackb(b, raw=False)
        out = _MSG_TYPES[d["_"]].from_dict(d["b"])
        TRACER.record(0, STAGE_DECODE, t0, TRACER.now(), tag=len(b))
        return out
    d = msgpack.unpackb(b, raw=False)
    return _MSG_TYPES[d["_"]].from_dict(d["b"])
