"""Server-to-server Raft RPC messages.

Capability parity with the reference wire format (Raft.proto):
RequestVoteRequestProto:161 (with preVote flag), AppendEntriesRequestProto:180
(batched entries + leaderCommit + commitInfos), AppendEntriesReplyProto with
SUCCESS/NOT_LEADER/INCONSISTENCY results, InstallSnapshotRequestProto:208
(chunked SnapshotChunkProto mode and notification mode),
ReadIndexRequestProto:245, StartLeaderElectionRequestProto (leader transfer).
All messages carry (requestorId, replyId, groupId) routing like
RaftRpcRequestProto:140.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import msgpack

from ratis_tpu.protocol.ids import RaftGroupId, RaftPeerId
from ratis_tpu.protocol.logentry import LogEntry
from ratis_tpu.protocol.termindex import TermIndex
from ratis_tpu.trace.tracer import STAGE_DECODE, STAGE_ENCODE, TRACER


@dataclasses.dataclass(frozen=True)
class RaftRpcHeader:
    """(requestor, reply-to, group) routing triple on every server RPC."""

    requestor_id: RaftPeerId
    reply_id: RaftPeerId
    group_id: RaftGroupId
    call_id: int = 0

    def to_dict(self) -> dict:
        return {"rq": self.requestor_id.id, "rp": self.reply_id.id,
                "g": self.group_id.to_bytes(), "c": self.call_id}

    @staticmethod
    def from_dict(d: dict) -> "RaftRpcHeader":
        return RaftRpcHeader(RaftPeerId.value_of(d["rq"]),
                             RaftPeerId.value_of(d["rp"]),
                             RaftGroupId.value_of(d["g"]), d.get("c", 0))


@dataclasses.dataclass(frozen=True)
class RequestVoteRequest:
    header: RaftRpcHeader
    candidate_term: int
    candidate_last_entry: TermIndex
    pre_vote: bool = False
    # Leadership-transfer election (startLeaderElection target): voters skip
    # the live-leader stickiness check, as the transfer was initiated by the
    # current leader itself (Raft §3.10 TimeoutNow semantics).
    force: bool = False

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.candidate_term,
                "lt": self.candidate_last_entry.term,
                "li": self.candidate_last_entry.index, "pv": self.pre_vote,
                "f": self.force}

    @staticmethod
    def from_dict(d: dict) -> "RequestVoteRequest":
        return RequestVoteRequest(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                  TermIndex(d["lt"], d["li"]),
                                  d.get("pv", False), d.get("f", False))


@dataclasses.dataclass(frozen=True)
class RequestVoteReply:
    header: RaftRpcHeader
    term: int
    granted: bool
    should_shutdown: bool = False
    # Replier's log-up-to-dateness hint used by the candidate's priority logic.
    last_entry: TermIndex = TermIndex.INITIAL_VALUE

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "g": self.granted,
                "sd": self.should_shutdown,
                "lt": self.last_entry.term, "li": self.last_entry.index}

    @staticmethod
    def from_dict(d: dict) -> "RequestVoteReply":
        return RequestVoteReply(RaftRpcHeader.from_dict(d["h"]), d["t"], d["g"],
                                d.get("sd", False),
                                TermIndex(d.get("lt", -1), d.get("li", -1)))


class AppendResult(enum.IntEnum):
    """AppendEntriesReplyProto.AppendResult (Raft.proto:189-193)."""

    SUCCESS = 0
    NOT_LEADER = 1
    INCONSISTENCY = 2


@dataclasses.dataclass(frozen=True)
class AppendEntriesRequest:
    header: RaftRpcHeader
    leader_term: int
    previous: Optional[TermIndex]
    entries: tuple[LogEntry, ...]
    leader_commit: int
    initializing: bool = False  # bootstrapping a newly-staged peer
    commit_infos: tuple[tuple[str, int], ...] = ()

    def is_heartbeat(self) -> bool:
        return not self.entries

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.leader_term,
                "pt": -1 if self.previous is None else self.previous.term,
                "pi": -1 if self.previous is None else self.previous.index,
                "e": [e.to_dict() for e in self.entries],
                "lc": self.leader_commit, "init": self.initializing,
                "ci": [list(x) for x in self.commit_infos]}

    @staticmethod
    def from_dict(d: dict) -> "AppendEntriesRequest":
        prev = None if d["pi"] < 0 and d["pt"] < 0 else TermIndex(d["pt"], d["pi"])
        return AppendEntriesRequest(
            RaftRpcHeader.from_dict(d["h"]), d["t"], prev,
            tuple(LogEntry.from_dict(e) for e in d["e"]), d["lc"],
            d.get("init", False),
            tuple(tuple(x) for x in d.get("ci", ())))


@dataclasses.dataclass(frozen=True)
class AppendEntriesReply:
    header: RaftRpcHeader
    term: int
    result: AppendResult
    next_index: int
    follower_commit: int
    match_index: int
    is_heartbeat: bool = False

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "r": int(self.result),
                "ni": self.next_index, "fc": self.follower_commit,
                "mi": self.match_index, "hb": self.is_heartbeat}

    @staticmethod
    def from_dict(d: dict) -> "AppendEntriesReply":
        return AppendEntriesReply(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                  AppendResult(d["r"]), d["ni"], d["fc"],
                                  d["mi"], d.get("hb", False))


class InstallSnapshotResult(enum.IntEnum):
    """InstallSnapshotReplyProto.InstallSnapshotResult (Raft.proto:225-233)."""

    SUCCESS = 0
    NOT_LEADER = 1
    IN_PROGRESS = 2
    ALREADY_INSTALLED = 3
    CONF_MISMATCH = 4
    SNAPSHOT_INSTALLED = 5
    SNAPSHOT_UNAVAILABLE = 6
    SNAPSHOT_EXPIRED = 7


@dataclasses.dataclass(frozen=True)
class FileChunk:
    """One chunk of one snapshot file (FileChunkProto:150-158)."""

    filename: str
    total_size: int
    file_digest: bytes
    chunk_index: int
    offset: int
    data: bytes
    done: bool

    def to_dict(self) -> dict:
        return {"f": self.filename, "ts": self.total_size, "dg": self.file_digest,
                "ci": self.chunk_index, "o": self.offset, "d": self.data,
                "dn": self.done}

    @staticmethod
    def from_dict(d: dict) -> "FileChunk":
        return FileChunk(d["f"], d["ts"], d["dg"], d["ci"], d["o"], d["d"], d["dn"])


@dataclasses.dataclass(frozen=True)
class InstallSnapshotRequest:
    header: RaftRpcHeader
    leader_term: int
    # chunked mode (SnapshotChunkProto:214-221)
    request_id: str = ""
    request_index: int = 0
    snapshot_term_index: Optional[TermIndex] = None
    chunks: tuple[FileChunk, ...] = ()
    total_size: int = 0
    done: bool = False
    # notification mode (NotificationProto:222-224): leader log purged; the
    # StateMachine fetches state out-of-band.
    notification_first_available: Optional[TermIndex] = None
    last_included: Optional[TermIndex] = None

    def is_notification(self) -> bool:
        return self.notification_first_available is not None

    def to_dict(self) -> dict:
        def ti(x):
            return None if x is None else [x.term, x.index]
        return {"h": self.header.to_dict(), "t": self.leader_term,
                "rid": self.request_id, "ridx": self.request_index,
                "sti": ti(self.snapshot_term_index),
                "ch": [c.to_dict() for c in self.chunks], "ts": self.total_size,
                "dn": self.done, "nfa": ti(self.notification_first_available),
                "lin": ti(self.last_included)}

    @staticmethod
    def from_dict(d: dict) -> "InstallSnapshotRequest":
        def ti(x):
            return None if x is None else TermIndex(x[0], x[1])
        return InstallSnapshotRequest(
            RaftRpcHeader.from_dict(d["h"]), d["t"], d.get("rid", ""),
            d.get("ridx", 0), ti(d.get("sti")),
            tuple(FileChunk.from_dict(c) for c in d.get("ch", ())),
            d.get("ts", 0), d.get("dn", False), ti(d.get("nfa")), ti(d.get("lin")))


@dataclasses.dataclass(frozen=True)
class InstallSnapshotReply:
    header: RaftRpcHeader
    term: int
    result: InstallSnapshotResult
    request_index: int = 0
    snapshot_index: int = -1

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "t": self.term, "r": int(self.result),
                "ri": self.request_index, "si": self.snapshot_index}

    @staticmethod
    def from_dict(d: dict) -> "InstallSnapshotReply":
        return InstallSnapshotReply(RaftRpcHeader.from_dict(d["h"]), d["t"],
                                    InstallSnapshotResult(d["r"]),
                                    d.get("ri", 0), d.get("si", -1))


@dataclasses.dataclass(frozen=True)
class ReadIndexRequest:
    header: RaftRpcHeader

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "ReadIndexRequest":
        return ReadIndexRequest(RaftRpcHeader.from_dict(d["h"]))


@dataclasses.dataclass(frozen=True)
class ReadIndexReply:
    header: RaftRpcHeader
    ok: bool
    read_index: int = -1

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "ok": self.ok, "ri": self.read_index}

    @staticmethod
    def from_dict(d: dict) -> "ReadIndexReply":
        return ReadIndexReply(RaftRpcHeader.from_dict(d["h"]), d["ok"],
                              d.get("ri", -1))


@dataclasses.dataclass(frozen=True)
class StartLeaderElectionRequest:
    """Leader -> chosen follower during transfer leadership
    (StartLeaderElectionRequestProto)."""

    header: RaftRpcHeader
    leader_last_entry: TermIndex

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "lt": self.leader_last_entry.term,
                "li": self.leader_last_entry.index}

    @staticmethod
    def from_dict(d: dict) -> "StartLeaderElectionRequest":
        return StartLeaderElectionRequest(RaftRpcHeader.from_dict(d["h"]),
                                          TermIndex(d["lt"], d["li"]))


@dataclasses.dataclass(frozen=True)
class StartLeaderElectionReply:
    header: RaftRpcHeader
    accepted: bool

    def to_dict(self) -> dict:
        return {"h": self.header.to_dict(), "ok": self.accepted}

    @staticmethod
    def from_dict(d: dict) -> "StartLeaderElectionReply":
        return StartLeaderElectionReply(RaftRpcHeader.from_dict(d["h"]), d["ok"])


@dataclasses.dataclass(frozen=True)
class AppendEnvelope:
    """Multi-raft AppendEntries envelope: append traffic from EVERY group a
    server leads toward one destination server, folded into a single RPC —
    both idle heartbeats and pipelined entry batches.

    No reference analog — the reference runs one stream per (group,
    follower) (GrpcLogAppender.java:356) plus one heartbeat per group per
    interval, which is the O(groups) RPC wall this framework's multi-raft
    axis removes.  The envelope carries ordinary AppendEntriesRequests, so
    each group's semantics are exactly the unary path's; the receiver
    processes a group's items sequentially in order (RaftServer
    _handle_append_envelope), which preserves per-group FIFO.

    Sequenced append windows (round 9, raft.tpu.replication.window-depth):
    with per-group frame pipelining a group's items MAY be split across
    consecutive in-flight envelopes, so FIFO moves from the sender's busy
    latch to the wire — ``lane`` names one (sender, destination,
    loop-shard) lane instance (a fresh id per sender lifetime, so a
    restarted sender never collides with its predecessor's sequence
    space) and ``seq`` numbers the lane's frames from 0.  The receiver
    processes a lane's frames strictly in sequence (out-of-order arrivals
    briefly buffered, gaps rejected with a rewind hint — RaftServer's
    lane intake).  ``seq < 0`` = unsequenced legacy frame, processed
    immediately; a depth-1 sender emits only those, with bit-identical
    wire bytes to the pre-window protocol."""

    items: tuple[AppendEntriesRequest, ...]
    lane: int = 0
    seq: int = -1

    def to_dict(self) -> dict:
        d: dict = {"i": [r.to_dict() for r in self.items]}
        if self.seq >= 0:
            d["ln"] = self.lane
            d["sq"] = self.seq
        return d

    @staticmethod
    def from_dict(d: dict) -> "AppendEnvelope":
        return AppendEnvelope(
            tuple(AppendEntriesRequest.from_dict(x) for x in d["i"]),
            d.get("ln", 0), d.get("sq", -1))


# AppendEnvelopeReply.status codes (sequenced lanes)
ENV_OK = 0
# the frame broke the lane's sequence (gap past the reorder buffer, a
# duplicate, or a buffered wait that timed out): nothing was processed;
# ``hint`` carries the sequence the receiver expects next — the sender
# drops the lane's unacked frames and re-cuts on a fresh lane
ENV_OUT_OF_SEQUENCE = 1


@dataclasses.dataclass(frozen=True)
class AppendEnvelopeReply:
    """Per-item replies; None where the peer failed that group (e.g. it does
    not serve it) — the sender treats those as per-follower RPC errors.
    ``status != ENV_OK`` means the whole frame was refused unprocessed by
    the receiver's lane intake (items is empty then)."""

    items: tuple[Optional[AppendEntriesReply], ...]
    status: int = ENV_OK
    hint: int = -1

    def to_dict(self) -> dict:
        d: dict = {"i": [None if r is None else r.to_dict()
                         for r in self.items]}
        if self.status != ENV_OK:
            d["st"] = self.status
            d["hn"] = self.hint
        return d

    @staticmethod
    def from_dict(d: dict) -> "AppendEnvelopeReply":
        return AppendEnvelopeReply(
            tuple(None if x is None else AppendEntriesReply.from_dict(x)
                  for x in d["i"]),
            d.get("st", ENV_OK), d.get("hn", -1))


@dataclasses.dataclass(frozen=True)
class BulkHeartbeat:
    """Compact multi-raft heartbeat: ONE small message per server pair per
    interval carrying a fixed-width tuple per led group, instead of one full
    AppendEntries per (group, follower).

    No reference analog — the reference's per-group heartbeat volume
    (GrpcLogAppender heartbeat channel) is an O(groups) event-loop wall at
    thousands of co-hosted groups even when the RPCs are folded, because
    each heartbeat still costs a full AppendEntries build + handle + reply.
    The bulk item carries exactly what the idle happy path needs: leadership
    assertion (term), and safe commit propagation (leader commit + the term
    of the entry at that index, so the follower advances commit only when
    its own entry matches — the Log Matching property makes that
    sufficient).  Any anomaly (behind follower, term conflict) falls back to
    a full AppendEntries probe on the data path, with prev-check fidelity.

    items: (group_id_bytes, leader_term, leader_commit, commit_entry_term)
    """

    requestor_id: RaftPeerId
    reply_id: RaftPeerId
    items: tuple[tuple[bytes, int, int, int], ...]

    def to_dict(self) -> dict:
        return {"rq": self.requestor_id.id, "rp": self.reply_id.id,
                "i": [list(x) for x in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "BulkHeartbeat":
        return BulkHeartbeat(RaftPeerId.value_of(d["rq"]),
                             RaftPeerId.value_of(d["rp"]),
                             tuple(tuple(x) for x in d["i"]))


# BulkHeartbeatReply item result codes
BULK_HB_OK = 0
BULK_HB_NOT_LEADER = 1
BULK_HB_UNKNOWN_GROUP = 2
# Receiver skipped the item because the division's append lock was held by
# an in-flight AppendEntries: that append itself resets the follower's
# election deadline, and the leader simply retries next sweep — so the
# sweep never waits on a contended division (no head-of-line blocking).
BULK_HB_BUSY = 3
# Follower accepted a hibernate request (a normal bulk item with a 5th
# flag field set): its election timer is DISARMED and the leader may stop
# heartbeating the group (idle-group quiescence,
# RaftServerConfigKeys.Hibernate).
BULK_HB_HIBERNATED = 4


@dataclasses.dataclass(frozen=True)
class BulkHeartbeatReply:
    """Aligned 1:1 with the request's items.

    items: (result_code, term, next_index, follower_commit, flush_index)
    """

    items: tuple[tuple[int, int, int, int, int], ...]

    def to_dict(self) -> dict:
        return {"i": [list(x) for x in self.items]}

    @staticmethod
    def from_dict(d: dict) -> "BulkHeartbeatReply":
        return BulkHeartbeatReply(tuple(tuple(x) for x in d["i"]))


# --- encode-once fast path ---------------------------------------------------
#
# The leader fans near-identical AppendEntries payloads to N followers (and
# re-sends them on window refills): at 5-peer x 10240 groups every entry's
# msgpack bytes were produced four times per replication round.  The fast
# path below serializes each piece ONCE and splices:
#
# - per-ENTRY wire bytes are memoized on the LogEntry object itself (frozen
#   dataclass, attribute set via object.__setattr__) — the dominant bytes of
#   any append, encoded once per entry lifetime, shared across followers,
#   envelopes, and resends;
# - the per-request SUFFIX (everything after the routing header — term,
#   prev, entries, commit, infos) is cached in a small LRU keyed by the
#   request's non-header fields, so fanning one batch to N followers packs
#   the suffix once and re-packs only the ~30-byte header per destination;
# - scaffolding (map/array headers, keys, ints) is written by a
#   msgpack-bit-compatible mini-packer into a POOLED bytearray, so the
#   output is byte-identical to ``msgpack.packb({"_": tag, "b": to_dict()},
#   use_bin_type=True)`` (asserted in tests/test_wire_fastpath.py) and no
#   per-call buffer is allocated.
#
# Any unexpected shape falls back to the generic packer (counted in
# FANOUT_STATS["fallback"]) — the fast path is an optimization, never a
# second wire format.

FANOUT_STATS = {"fast": 0, "suffix_hits": 0, "fallback": 0}

_SUFFIX_LRU: "dict[tuple, tuple[tuple, bytes]]" = {}
_SUFFIX_LRU_MAX = 512


def _pk_int(out: bytearray, v: int) -> None:
    if v >= 0:
        if v < 0x80:
            out.append(v)
        elif v <= 0xff:
            out.append(0xcc); out.append(v)  # noqa: E702
        elif v <= 0xffff:
            out.append(0xcd); out += v.to_bytes(2, "big")  # noqa: E702
        elif v <= 0xffffffff:
            out.append(0xce); out += v.to_bytes(4, "big")  # noqa: E702
        else:
            out.append(0xcf); out += v.to_bytes(8, "big")  # noqa: E702
    else:
        if v >= -32:
            out.append(0x100 + v)
        elif v >= -0x80:
            out.append(0xd0); out += v.to_bytes(1, "big", signed=True)  # noqa: E702
        elif v >= -0x8000:
            out.append(0xd1); out += v.to_bytes(2, "big", signed=True)  # noqa: E702
        elif v >= -0x80000000:
            out.append(0xd2); out += v.to_bytes(4, "big", signed=True)  # noqa: E702
        else:
            out.append(0xd3); out += v.to_bytes(8, "big", signed=True)  # noqa: E702


def _pk_str(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    n = len(b)
    if n < 32:
        out.append(0xa0 | n)
    elif n <= 0xff:
        out.append(0xd9); out.append(n)  # noqa: E702
    elif n <= 0xffff:
        out.append(0xda); out += n.to_bytes(2, "big")  # noqa: E702
    else:
        out.append(0xdb); out += n.to_bytes(4, "big")  # noqa: E702
    out += b


def _pk_bin(out: bytearray, b: bytes) -> None:
    n = len(b)
    if n <= 0xff:
        out.append(0xc4); out.append(n)  # noqa: E702
    elif n <= 0xffff:
        out.append(0xc5); out += n.to_bytes(2, "big")  # noqa: E702
    else:
        out.append(0xc6); out += n.to_bytes(4, "big")  # noqa: E702
    out += b


def _pk_arr(out: bytearray, n: int) -> None:
    if n < 16:
        out.append(0x90 | n)
    elif n <= 0xffff:
        out.append(0xdc); out += n.to_bytes(2, "big")  # noqa: E702
    else:
        out.append(0xdd); out += n.to_bytes(4, "big")  # noqa: E702


def _pk_obj(out: bytearray, v) -> None:
    """Generic scalar/sequence packer (msgpack-bit-compatible) for the few
    loosely-typed fields (commit-info pairs, header ids)."""
    if v is None:
        out.append(0xc0)
    elif v is True:
        out.append(0xc3)
    elif v is False:
        out.append(0xc2)
    elif isinstance(v, int):
        _pk_int(out, v)
    elif isinstance(v, str):
        _pk_str(out, v)
    elif isinstance(v, (bytes, bytearray)):
        _pk_bin(out, bytes(v))
    elif isinstance(v, (list, tuple)):
        _pk_arr(out, len(v))
        for x in v:
            _pk_obj(out, x)
    else:
        raise TypeError(f"no fast packer for {type(v)}")


def entry_wire_bytes(e) -> bytes:
    """Wire bytes of one log entry (``msgpack.packb(e.to_dict())``),
    memoized ON the entry — encode-once across followers and resends."""
    w = e.__dict__.get("_wire")
    if w is None:
        w = msgpack.packb(e.to_dict(), use_bin_type=True)
        object.__setattr__(e, "_wire", w)
    return w


def _append_suffix(req: "AppendEntriesRequest") -> bytes:
    """The request body AFTER the "h" key/value: identical across the
    per-follower fan-out, cacheable."""
    out = bytearray()
    _pk_str(out, "t"); _pk_int(out, req.leader_term)  # noqa: E702
    prev = req.previous
    _pk_str(out, "pt"); _pk_int(out, -1 if prev is None else prev.term)  # noqa: E702
    _pk_str(out, "pi"); _pk_int(out, -1 if prev is None else prev.index)  # noqa: E702
    _pk_str(out, "e"); _pk_arr(out, len(req.entries))  # noqa: E702
    for e in req.entries:
        out += entry_wire_bytes(e)
    _pk_str(out, "lc"); _pk_int(out, req.leader_commit)  # noqa: E702
    _pk_str(out, "init")
    out.append(0xc3 if req.initializing else 0xc2)
    _pk_str(out, "ci"); _pk_arr(out, len(req.commit_infos))  # noqa: E702
    for pair in req.commit_infos:
        _pk_obj(out, list(pair))
    return bytes(out)


def _suffix_for(req: "AppendEntriesRequest") -> bytes:
    prev = req.previous
    key = (req.leader_term,
           -1 if prev is None else prev.term,
           -1 if prev is None else prev.index,
           req.leader_commit, req.initializing, req.commit_infos,
           tuple(map(id, req.entries)))
    hit = _SUFFIX_LRU.get(key)
    if hit is not None:
        FANOUT_STATS["suffix_hits"] += 1
        return hit[1]
    suf = _append_suffix(req)
    # The value PINS the entry objects, so the id()-based key stays valid
    # for exactly as long as it is in the cache.  Multi-MB suffixes are
    # not cached: 512 pinned 4MB batches would be ~2GB of heap, and a big
    # batch's encode is already amortized by the per-entry memo — the
    # cache's marginal win there is one memcpy.
    if len(suf) <= (256 << 10):
        _SUFFIX_LRU[key] = (req.entries, suf)
        if len(_SUFFIX_LRU) > _SUFFIX_LRU_MAX:
            _SUFFIX_LRU.pop(next(iter(_SUFFIX_LRU)))
    return suf


def _pk_append_request_body(out: bytearray,
                            req: "AppendEntriesRequest") -> None:
    out.append(0x88)  # fixmap(8): h t pt pi e lc init ci
    _pk_str(out, "h")
    h = req.header
    out.append(0x84)  # fixmap(4): rq rp g c
    _pk_str(out, "rq"); _pk_obj(out, h.requestor_id.id)  # noqa: E702
    _pk_str(out, "rp"); _pk_obj(out, h.reply_id.id)  # noqa: E702
    _pk_str(out, "g"); _pk_bin(out, h.group_id.to_bytes())  # noqa: E702
    _pk_str(out, "c"); _pk_int(out, h.call_id)  # noqa: E702
    out += _suffix_for(req)


_BUF_POOL: list[bytearray] = []


def _encode_append_fast(msg) -> bytes:
    buf = _BUF_POOL.pop() if _BUF_POOL else bytearray()
    try:
        buf.append(0x82)  # fixmap(2): _ b
        _pk_str(buf, "_")
        if type(msg) is AppendEnvelope:
            _pk_str(buf, "env_req")
            _pk_str(buf, "b")
            sequenced = msg.seq >= 0
            # fixmap(3): i ln sq (sequenced lane frame) / fixmap(1): i
            # (legacy frame — byte-identical to the pre-window protocol)
            buf.append(0x83 if sequenced else 0x81)
            _pk_str(buf, "i")
            _pk_arr(buf, len(msg.items))
            for req in msg.items:
                _pk_append_request_body(buf, req)
            if sequenced:
                _pk_str(buf, "ln"); _pk_int(buf, msg.lane)  # noqa: E702
                _pk_str(buf, "sq"); _pk_int(buf, msg.seq)  # noqa: E702
        else:
            _pk_str(buf, "append_req")
            _pk_str(buf, "b")
            _pk_append_request_body(buf, msg)
        FANOUT_STATS["fast"] += 1
        return bytes(buf)
    finally:
        buf.clear()
        if len(_BUF_POOL) < 8:
            _BUF_POOL.append(buf)


def _encode(msg) -> bytes:
    t = type(msg)
    if t is AppendEnvelope or t is AppendEntriesRequest:
        try:
            return _encode_append_fast(msg)
        except Exception:
            FANOUT_STATS["fallback"] += 1
    return msgpack.packb({"_": _TYPE_TAGS[t], "b": msg.to_dict()},
                         use_bin_type=True)


# --- generic envelope for transports ---------------------------------------

_MSG_TYPES: dict[str, type] = {
    "vote_req": RequestVoteRequest, "vote_rep": RequestVoteReply,
    "append_req": AppendEntriesRequest, "append_rep": AppendEntriesReply,
    "snap_req": InstallSnapshotRequest, "snap_rep": InstallSnapshotReply,
    "readidx_req": ReadIndexRequest, "readidx_rep": ReadIndexReply,
    "sle_req": StartLeaderElectionRequest, "sle_rep": StartLeaderElectionReply,
    "env_req": AppendEnvelope, "env_rep": AppendEnvelopeReply,
    "bulkhb_req": BulkHeartbeat, "bulkhb_rep": BulkHeartbeatReply,
}
_TYPE_TAGS = {v: k for k, v in _MSG_TYPES.items()}


def encode_rpc(msg) -> bytes:
    """Tagged msgpack envelope (cf. Netty.proto's request/reply union:31-48).

    Append traffic (AppendEntriesRequest / AppendEnvelope) takes the
    encode-once fast path above — bit-identical output, entry bytes and
    fan-out suffixes serialized once.  Host-path tracing samples the encode
    here (process-level span, ratis_tpu.trace STAGE_ENCODE, tag = wire
    bytes): the per-commit msgpack cost of the server-to-server plane,
    measured where it is paid — fast-path encodes record through the same
    stage, so coalesced/spliced frames stay attributed."""
    if TRACER.enabled and TRACER.sample():
        t0 = TRACER.now()
        b = _encode(msg)
        TRACER.record(0, STAGE_ENCODE, t0, TRACER.now(), tag=len(b))
        return b
    return _encode(msg)


def decode_rpc(b: bytes):
    if TRACER.enabled and TRACER.sample():
        t0 = TRACER.now()
        d = msgpack.unpackb(b, raw=False)
        out = _MSG_TYPES[d["_"]].from_dict(d["b"])
        TRACER.record(0, STAGE_DECODE, t0, TRACER.now(), tag=len(b))
        return out
    d = msgpack.unpackb(b, raw=False)
    return _MSG_TYPES[d["_"]].from_dict(d["b"])
