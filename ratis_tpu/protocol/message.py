"""Message: the opaque byte payload handed to/returned by a StateMachine.

Capability parity with the reference's Message
(ratis-common/src/main/java/org/apache/ratis/protocol/Message.java).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar


@dataclasses.dataclass(frozen=True)
class Message:
    content: bytes = b""

    EMPTY: ClassVar["Message"]

    @staticmethod
    def value_of(content: "bytes | str | Message") -> "Message":
        if isinstance(content, Message):
            return content
        if isinstance(content, str):
            return Message(content.encode("utf-8"))
        return Message(bytes(content))

    def size(self) -> int:
        return len(self.content)

    def __str__(self) -> str:
        if len(self.content) <= 32:
            try:
                return f"Message({self.content.decode('utf-8')!r})"
            except UnicodeDecodeError:
                pass
        return f"Message({len(self.content)}B)"


Message.EMPTY = Message(b"")
