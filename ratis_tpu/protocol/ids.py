"""UUID-backed protocol identifiers.

Capability parity with the reference's id hierarchy
(ratis-common/src/main/java/org/apache/ratis/protocol/RaftId.java,
RaftPeerId.java, RaftGroupId.java, ClientId.java): RaftGroupId and ClientId
are 16-byte UUIDs; RaftPeerId is an arbitrary UTF-8 string (host-chosen,
e.g. "s0").  All are immutable and hashable, usable as dict keys and in wire
messages.
"""

from __future__ import annotations

import dataclasses
import typing
import uuid


@dataclasses.dataclass(frozen=True, order=True)
class RaftId:
    """Base: a 16-byte UUID identity."""

    uuid: uuid.UUID

    @classmethod
    def random_id(cls):
        return cls(uuid.uuid4())

    @classmethod
    def value_of(cls, value: "str | bytes | uuid.UUID | RaftId"):
        if isinstance(value, RaftId):
            return cls(value.uuid)
        if isinstance(value, uuid.UUID):
            return cls(value)
        if isinstance(value, bytes):
            return cls(uuid.UUID(bytes=value))
        return cls(uuid.UUID(value))

    @classmethod
    def empty_id(cls):
        return cls(uuid.UUID(int=0))

    def to_bytes(self) -> bytes:
        return self.uuid.bytes

    def is_empty(self) -> bool:
        return self.uuid.int == 0

    def shorten(self) -> str:
        return str(self.uuid)[:8]

    def __str__(self) -> str:
        return str(self.uuid)


class RaftGroupId(RaftId):
    """Identifies one Raft group hosted by a (multi-Raft) server."""

    # Wire decode interning: every RPC header carries a group id, and a
    # multi-raft server decodes thousands per second — the UUID-object
    # construction cost shows up in profiles.  Bounded: ids arrive off the
    # wire BEFORE membership validation, so an unbounded cache would let a
    # buggy/malicious peer grow process memory with novel ids; past the cap
    # we simply stop caching (construction still works, just uncached).
    _intern: dict = {}
    _INTERN_MAX = 1 << 17

    @classmethod
    def value_of(cls, value):
        if isinstance(value, bytes):
            cached = cls._intern.get(value)
            if cached is None:
                cached = cls(uuid.UUID(bytes=value))
                if len(cls._intern) < cls._INTERN_MAX:
                    cls._intern[value] = cached
            return cached
        return super().value_of(value)

    def __str__(self) -> str:  # group-<uuid> like the reference's display form
        return f"group-{self.shorten()}"


class ClientId(RaftId):
    # Same bounded wire-decode interning as RaftGroupId: every client
    # request decode re-built a UUID object for a client id the server has
    # almost certainly seen before (profiles showed ~3 uuid constructions
    # per committed write at 1024 groups).
    _intern: dict = {}
    _INTERN_MAX = 1 << 17

    @classmethod
    def value_of(cls, value):
        if isinstance(value, bytes):
            cached = cls._intern.get(value)
            if cached is None:
                cached = cls(uuid.UUID(bytes=value))
                if len(cls._intern) < cls._INTERN_MAX:
                    cls._intern[value] = cached
            return cached
        return super().value_of(value)

    def __str__(self) -> str:
        return f"client-{self.shorten()}"


@dataclasses.dataclass(frozen=True, order=True)
class RaftPeerId:
    """String id of one peer (reference RaftPeerId.java:30 stores UTF-8 bytes)."""

    id: str

    # peer ids are few; bounded decode interning (see RaftGroupId)
    _intern: typing.ClassVar[dict] = {}
    _INTERN_MAX: typing.ClassVar[int] = 1 << 17

    @staticmethod
    def value_of(value: "str | bytes | RaftPeerId") -> "RaftPeerId":
        if isinstance(value, RaftPeerId):
            return value
        if isinstance(value, bytes):
            value = value.decode("utf-8")
        cached = RaftPeerId._intern.get(value)
        if cached is None:
            cached = RaftPeerId(value)
            if len(RaftPeerId._intern) < RaftPeerId._INTERN_MAX:
                RaftPeerId._intern[value] = cached
        return cached

    def to_bytes(self) -> bytes:
        return self.id.encode("utf-8")

    def __str__(self) -> str:
        return self.id
