"""The framework exception hierarchy.

Capability parity with the reference's 24 exception types under
ratis-common/src/main/java/org/apache/ratis/protocol/exceptions/.  These are
wire-marshallable: a RaftClientReply carries at most one of them, and the
client's failover/retry logic dispatches on the concrete type (reference
RaftClientImpl.handleIOException, ratis-client RaftClientImpl.java:412).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:
    from ratis_tpu.protocol.group import RaftGroupMemberId
    from ratis_tpu.protocol.peer import RaftPeer


class RaftException(Exception):
    """Base of every framework-level failure."""


class GroupMismatchException(RaftException):
    """Request's groupId is not served by this server (RaftServerProxy routing)."""


class AlreadyExistsException(RaftException):
    """Group add for a group already hosted."""


class AlreadyClosedException(RaftException):
    """Operation on a closed server/client/log."""


class ServerNotReadyException(RaftException):
    """Server is still initializing (replaying log / installing snapshot)."""


class LeaderNotReadyException(RaftException):
    """Peer is leader but has not yet committed its startup entry
    (reference LeaderNotReadyException.java; retried transparently)."""

    member_id = None

    def __init__(self, member_id=None, msg: Optional[str] = None):
        super().__init__(msg or f"{member_id} is in LEADER state but not ready yet")
        self.member_id = member_id


class NotLeaderException(RaftException):
    """Request hit a non-leader peer; carries the leader hint + current peers
    for client failover (reference NotLeaderException.java)."""

    def __init__(self, member_id=None, suggested_leader: "Optional[RaftPeer]" = None,
                 peers: tuple = ()):
        hint = f", suggested leader: {suggested_leader}" if suggested_leader else ""
        super().__init__(f"{member_id} is not the leader{hint}")
        self.member_id = member_id
        self.suggested_leader = suggested_leader
        self.peers = tuple(peers)


class LeaderSteppingDownException(RaftException):
    """Leader rejects new writes while stepping down (transfer leadership)."""


class TransferLeadershipException(RaftException):
    pass


class NotReplicatedException(RaftException):
    """Watch request's desired replication level not reached in time
    (reference NotReplicatedException.java); carries call id + level + index."""

    def __init__(self, call_id: int = 0, replication=None, log_index: int = -1):
        super().__init__(
            f"Request #{call_id} not yet replicated to {replication} (logIndex={log_index})")
        self.call_id = call_id
        self.replication = replication
        self.log_index = log_index


class ReconfigurationInProgressException(RaftException):
    pass


class ReconfigurationTimeoutException(RaftException):
    pass


class SetConfigurationException(RaftException):
    pass


class StateMachineException(RaftException):
    """Application StateMachine raised during startTransaction/apply; leader
    replies with it (and the entry may still commit) — reference
    StateMachineException.java."""

    cause = None
    leader_should_step_down = False

    def __init__(self, msg: str = "", cause: Optional[BaseException] = None,
                 leader_should_step_down: bool = False):
        super().__init__(msg or (str(cause) if cause else ""))
        self.cause = cause
        self.leader_should_step_down = leader_should_step_down


class RaftRetryFailureException(RaftException):
    """Client exhausted its RetryPolicy."""

    attempt_count = 0
    cause = None

    def __init__(self, request=None, attempt_count: int = 0, policy=None,
                 cause: Optional[BaseException] = None):
        super().__init__(
            f"Failed {request} for {attempt_count} attempts with {policy}")
        self.attempt_count = attempt_count
        self.cause = cause


class TimeoutIOException(RaftException):
    pass


class ResourceUnavailableException(RaftException):
    """Server resource limits hit (pending-request permits, retry-cache size);
    client backs off (reference ResourceUnavailableException.java).

    Carries an optional retry-after hint (milliseconds) set by the serving
    plane's admission controller so shed clients back off for at least the
    server-suggested interval instead of hammering a saturated shard."""

    retry_after_ms = 0

    def __init__(self, msg: str = "", retry_after_ms: int = 0):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class ReadException(RaftException):
    pass


class ReadIndexException(RaftException):
    pass


class StaleReadException(RaftException):
    """StaleRead's minIndex is beyond this peer's applied index."""


class StreamException(RaftException):
    pass


class DataStreamException(RaftException):
    pass


class ChecksumException(RaftException):
    """CRC mismatch reading a log record (reference ChecksumException.java)."""

    position = -1

    def __init__(self, msg: str, position: int = -1):
        super().__init__(msg)
        self.position = position


class CorruptedFileException(RaftException):
    pass


class LogCorruptedException(RaftException):
    pass


class RaftLogIOException(RaftException):
    """The backing log failed a write and is latched dead (reference
    raftlog.RaftLogIOException; the worker terminates on IO failure)."""


class InstallSnapshotException(RaftException):
    pass


class LeaderElectionException(RaftException):
    pass


# --- wire marshalling -------------------------------------------------------
# Exceptions cross the network inside replies; map type name <-> class.

_WIRE_TYPES: dict[str, type] = {
    cls.__name__: cls
    for cls in [
        RaftException, GroupMismatchException, AlreadyExistsException,
        AlreadyClosedException, ServerNotReadyException, LeaderNotReadyException,
        NotLeaderException, LeaderSteppingDownException, TransferLeadershipException,
        NotReplicatedException, ReconfigurationInProgressException,
        ReconfigurationTimeoutException, SetConfigurationException,
        StateMachineException, RaftRetryFailureException, TimeoutIOException,
        ResourceUnavailableException, ReadException, ReadIndexException,
        StaleReadException, StreamException, DataStreamException,
        ChecksumException, CorruptedFileException, LogCorruptedException,
        InstallSnapshotException, LeaderElectionException,
    ]
}


def exception_to_wire(e: BaseException) -> dict:
    d: dict = {"type": type(e).__name__ if type(e).__name__ in _WIRE_TYPES else "RaftException",
               "msg": str(e)}
    if isinstance(e, NotLeaderException):
        from ratis_tpu.protocol.peer import RaftPeer
        if e.suggested_leader is not None:
            d["suggested_leader"] = e.suggested_leader.to_dict()
        d["peers"] = [p.to_dict() for p in e.peers]
    if isinstance(e, NotReplicatedException):
        d.update(call_id=e.call_id,
                 replication=None if e.replication is None else int(e.replication),
                 log_index=e.log_index)
    if isinstance(e, ResourceUnavailableException) and e.retry_after_ms:
        d["retry_after_ms"] = e.retry_after_ms
    return d


def exception_from_wire(d: dict) -> RaftException:
    cls = _WIRE_TYPES.get(d.get("type", ""), RaftException)
    msg = d.get("msg", "")
    if cls is NotLeaderException:
        from ratis_tpu.protocol.peer import RaftPeer
        leader = d.get("suggested_leader")
        e: RaftException = NotLeaderException(
            suggested_leader=RaftPeer.from_dict(leader) if leader else None,
            peers=tuple(RaftPeer.from_dict(p) for p in d.get("peers", ())))
        e.args = (msg,)
        return e
    if cls is NotReplicatedException:
        from ratis_tpu.protocol.requests import ReplicationLevel
        repl = d.get("replication")
        e = NotReplicatedException(
            call_id=d.get("call_id", 0),
            replication=None if repl is None else ReplicationLevel(repl),
            log_index=d.get("log_index", -1))
        e.args = (msg,)
        return e
    if cls is ResourceUnavailableException:
        e = ResourceUnavailableException(msg, retry_after_ms=d.get("retry_after_ms", 0))
        return e
    # Generic path: never route msg through a typed first parameter (e.g.
    # LeaderNotReadyException(member_id), RaftRetryFailureException(request)).
    e = cls.__new__(cls)
    Exception.__init__(e, msg)
    return e
