"""RoutingTable: DataStream fan-out topology.

Capability parity with the reference RoutingTable
(ratis-common/src/main/java/org/apache/ratis/protocol/RoutingTable.java,
wire form RoutingTableProto, Raft.proto:320): for one stream, which peer
the client talks to (the *primary*) and, per peer, the successors each
peer forwards packets to — a chain, star, or tree over the group.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ratis_tpu.protocol.ids import RaftPeerId


@dataclasses.dataclass(frozen=True)
class RoutingTable:
    """peer -> successors; empty means "primary forwards to everyone else"."""

    routes: Tuple[Tuple[RaftPeerId, Tuple[RaftPeerId, ...]], ...] = ()

    def get_successors(self, peer_id: RaftPeerId) -> Tuple[RaftPeerId, ...]:
        for pid, successors in self.routes:
            if pid == peer_id:
                return successors
        return ()

    def is_empty(self) -> bool:
        return not self.routes

    @staticmethod
    def chain(peers: Sequence[RaftPeerId]) -> "RoutingTable":
        """primary -> p1 -> p2 -> ... (the reference chain topology)."""
        routes = tuple((peers[i], (peers[i + 1],))
                       for i in range(len(peers) - 1))
        return RoutingTable(routes)

    @staticmethod
    def star(primary: RaftPeerId,
             others: Iterable[RaftPeerId]) -> "RoutingTable":
        """primary fans out to every other peer directly."""
        return RoutingTable(((primary, tuple(others)),))

    class Builder:
        def __init__(self) -> None:
            self._routes: Dict[RaftPeerId, list] = {}

        def add_successor(self, peer: RaftPeerId,
                          successor: RaftPeerId) -> "RoutingTable.Builder":
            self._routes.setdefault(RaftPeerId.value_of(peer), []).append(
                RaftPeerId.value_of(successor))
            return self

        def build(self) -> "RoutingTable":
            return RoutingTable(tuple(
                (pid, tuple(succ)) for pid, succ in self._routes.items()))

    def to_dict(self) -> list:
        return [[pid.id, [s.id for s in successors]]
                for pid, successors in self.routes]

    @staticmethod
    def from_dict(data: Optional[list]) -> "RoutingTable":
        if not data:
            return RoutingTable()
        return RoutingTable(tuple(
            (RaftPeerId.value_of(pid),
             tuple(RaftPeerId.value_of(s) for s in successors))
            for pid, successors in data))
