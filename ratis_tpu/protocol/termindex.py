"""TermIndex: (term, index) pair ordering log positions.

Capability parity with the reference's TermIndex
(ratis-server-api/src/main/java/org/apache/ratis/server/protocol/TermIndex.java).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar

INVALID_LOG_INDEX = -1  # reference RaftLog.INVALID_LOG_INDEX (RaftLog.java:44)
INVALID_TERM = -1


@dataclasses.dataclass(frozen=True, order=True)
class TermIndex:
    term: int
    index: int

    INITIAL_VALUE: ClassVar["TermIndex"]

    @staticmethod
    def value_of(term: int, index: int) -> "TermIndex":
        return TermIndex(term, index)

    def __str__(self) -> str:
        return f"(t:{self.term}, i:{self.index})"


TermIndex.INITIAL_VALUE = TermIndex(INVALID_TERM, INVALID_LOG_INDEX)
