"""Log entry wire/storage representation.

Capability parity with the reference's LogEntryProto (Raft.proto:97-107) and
its three body cases: StateMachineLogEntryProto (client transaction,
Raft.proto:72-91), ConfigurationEntryProto (membership change, including the
joint-consensus oldPeers list), and MetadataProto (persisted commitIndex,
Raft.proto:93-95).  Serialization is msgpack (compact, schema-stable dicts)
rather than protobuf-java; the gRPC transport wraps the same bytes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import msgpack

from ratis_tpu.protocol.ids import ClientId
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.protocol.termindex import TermIndex


class LogEntryKind(enum.IntEnum):
    STATE_MACHINE = 1
    CONFIGURATION = 2
    METADATA = 3


@dataclasses.dataclass(frozen=True)
class StateMachineLogEntry:
    """A client transaction: the logged payload plus the (clientId, callId)
    pair that keys the retry cache (reference StateMachineLogEntryProto)."""

    client_id: bytes = b""
    call_id: int = 0
    log_data: bytes = b""
    # State-machine data held OUTSIDE the log file when the StateMachine
    # provides a DataApi (reference SegmentedRaftLog stateMachineCachingEnabled,
    # SegmentedRaftLog.java:203).  Not serialized into segment files.
    sm_data: Optional[bytes] = None
    # True when this transaction was submitted by a DataStream CLOSE: every
    # replica must data_link the entry at apply, passing None when it holds
    # no local stream so the StateMachine can detect/repair the missing bytes
    # (reference passes a null stream for exactly this).
    is_datastream: bool = False


@dataclasses.dataclass(frozen=True)
class ConfigurationEntry:
    peers: tuple[RaftPeer, ...] = ()
    old_peers: tuple[RaftPeer, ...] = ()  # non-empty == joint consensus phase
    listeners: tuple[RaftPeer, ...] = ()
    old_listeners: tuple[RaftPeer, ...] = ()


@dataclasses.dataclass(frozen=True)
class LogEntry:
    term: int
    index: int
    kind: LogEntryKind
    smlog: Optional[StateMachineLogEntry] = None
    conf: Optional[ConfigurationEntry] = None
    commit_index: int = -1  # METADATA body

    def term_index(self) -> TermIndex:
        return TermIndex(self.term, self.index)

    def is_config(self) -> bool:
        return self.kind == LogEntryKind.CONFIGURATION

    def is_metadata(self) -> bool:
        return self.kind == LogEntryKind.METADATA

    def serialized_size(self) -> int:
        return len(self.to_bytes())

    # -- codec ---------------------------------------------------------------

    def to_dict(self, include_sm_data: bool = True) -> dict:
        d: dict = {"t": self.term, "i": self.index, "k": int(self.kind)}
        if self.smlog is not None:
            s: dict = {"c": self.smlog.client_id, "id": self.smlog.call_id,
                       "d": self.smlog.log_data}
            if include_sm_data and self.smlog.sm_data is not None:
                s["sd"] = self.smlog.sm_data
            if self.smlog.is_datastream:
                s["ds"] = True
            d["s"] = s
        if self.conf is not None:
            d["cf"] = {
                "p": [p.to_dict() for p in self.conf.peers],
                "op": [p.to_dict() for p in self.conf.old_peers],
                "l": [p.to_dict() for p in self.conf.listeners],
                "ol": [p.to_dict() for p in self.conf.old_listeners],
            }
        if self.kind == LogEntryKind.METADATA:
            d["ci"] = self.commit_index
        return d

    @staticmethod
    def from_dict(d: dict) -> "LogEntry":
        smlog = None
        if "s" in d:
            s = d["s"]
            smlog = StateMachineLogEntry(
                client_id=s.get("c", b""), call_id=s.get("id", 0),
                log_data=s.get("d", b""), sm_data=s.get("sd"),
                is_datastream=s.get("ds", False))
        conf = None
        if "cf" in d:
            c = d["cf"]
            conf = ConfigurationEntry(
                peers=tuple(RaftPeer.from_dict(p) for p in c.get("p", ())),
                old_peers=tuple(RaftPeer.from_dict(p) for p in c.get("op", ())),
                listeners=tuple(RaftPeer.from_dict(p) for p in c.get("l", ())),
                old_listeners=tuple(RaftPeer.from_dict(p) for p in c.get("ol", ())))
        return LogEntry(term=d["t"], index=d["i"], kind=LogEntryKind(d["k"]),
                        smlog=smlog, conf=conf, commit_index=d.get("ci", -1))

    def to_bytes(self, include_sm_data: bool = True) -> bytes:
        return msgpack.packb(self.to_dict(include_sm_data), use_bin_type=True)

    @staticmethod
    def from_bytes(b: bytes) -> "LogEntry":
        return LogEntry.from_dict(msgpack.unpackb(b, raw=False))

    def __str__(self) -> str:
        body = self.kind.name
        if self.smlog is not None:
            body += f"[{len(self.smlog.log_data)}B]"
        return f"{self.term_index()}:{body}"


def make_transaction_entry(term: int, index: int, client_id: ClientId | bytes,
                           call_id: int, data: bytes,
                           sm_data: Optional[bytes] = None,
                           is_datastream: bool = False) -> LogEntry:
    cid = client_id.to_bytes() if isinstance(client_id, ClientId) else bytes(client_id)
    return LogEntry(term, index, LogEntryKind.STATE_MACHINE,
                    smlog=StateMachineLogEntry(cid, call_id, data, sm_data,
                                               is_datastream))


def make_config_entry(term: int, index: int, peers, old_peers=(),
                      listeners=(), old_listeners=()) -> LogEntry:
    return LogEntry(term, index, LogEntryKind.CONFIGURATION,
                    conf=ConfigurationEntry(tuple(peers), tuple(old_peers),
                                            tuple(listeners), tuple(old_listeners)))


def make_metadata_entry(term: int, index: int, commit_index: int) -> LogEntry:
    return LogEntry(term, index, LogEntryKind.METADATA, commit_index=commit_index)
