"""RaftPeer: one cluster member's identity, address, priority and role.

Capability parity with the reference's RaftPeer
(ratis-common/src/main/java/org/apache/ratis/protocol/RaftPeer.java): id +
RPC address (+ optional admin/client/dataStream addresses), an election
priority, and a startup role (FOLLOWER or LISTENER — listeners replicate but
never vote nor count toward quorum, RaftPeerRole in Raft.proto:131-137).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from ratis_tpu.protocol.ids import RaftPeerId


class RaftPeerRole(enum.IntEnum):
    """Wire-stable role enum (values mirror Raft.proto RaftPeerRole)."""

    LEADER = 1
    CANDIDATE = 2
    FOLLOWER = 3
    LISTENER = 4


@dataclasses.dataclass(frozen=True)
class RaftPeer:
    id: RaftPeerId
    address: str = ""
    admin_address: Optional[str] = None
    client_address: Optional[str] = None
    datastream_address: Optional[str] = None
    priority: int = 0
    startup_role: RaftPeerRole = RaftPeerRole.FOLLOWER

    DEFAULT_PRIORITY = 0

    def __post_init__(self):
        object.__setattr__(self, "id", RaftPeerId.value_of(self.id))

    def is_listener(self) -> bool:
        return self.startup_role == RaftPeerRole.LISTENER

    def get_admin_address(self) -> str:
        return self.admin_address or self.address

    def get_client_address(self) -> str:
        return self.client_address or self.address

    def with_priority(self, priority: int) -> "RaftPeer":
        return dataclasses.replace(self, priority=priority)

    def to_dict(self) -> dict:
        d = {"id": self.id.id, "address": self.address}
        if self.priority:
            d["priority"] = self.priority
        if self.startup_role != RaftPeerRole.FOLLOWER:
            d["startup_role"] = int(self.startup_role)
        for k in ("admin_address", "client_address", "datastream_address"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @staticmethod
    def from_dict(d: dict) -> "RaftPeer":
        return RaftPeer(
            id=RaftPeerId.value_of(d["id"]),
            address=d.get("address", ""),
            admin_address=d.get("admin_address"),
            client_address=d.get("client_address"),
            datastream_address=d.get("datastream_address"),
            priority=d.get("priority", 0),
            startup_role=RaftPeerRole(d.get("startup_role", int(RaftPeerRole.FOLLOWER))),
        )

    def __str__(self) -> str:
        s = f"{self.id}|{self.address or '-'}"
        if self.priority:
            s += f"|priority={self.priority}"
        if self.startup_role == RaftPeerRole.LISTENER:
            s += "|listener"
        return s
