"""RaftClient: the user-facing client with failover, retry, and sub-APIs.

Capability parity with the reference ratis-client
(ratis-client/.../impl/RaftClientImpl.java:78): leader tracking with
failover on NotLeaderException (handleIOException:412), retry-policy-driven
resend (BlockingImpl.sendRequestWithRetry), replied-call-id piggybacking for
server retry-cache GC (RepliedCallIds:128), and the sub-API suppliers
(:182-191): io (ordered writes/reads), admin, group management, snapshot
management, leader-election management.

All APIs are asyncio coroutines — the framework is a single-event-loop
runtime end-to-end; there is no blocking thread API to mirror because there
are no threads to block (the reference's BlockingImpl exists to bridge
Java's thread-per-request model).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Iterable, Optional

from ratis_tpu.protocol.admin import (GroupInfoReplyData,
                                      GroupManagementArguments,
                                      GroupManagementOp,
                                      LeaderElectionManagementArguments,
                                      LeaderElectionManagementOp,
                                      SetConfigurationArguments,
                                      SetConfigurationMode,
                                      SnapshotManagementArguments,
                                      SnapshotManagementOp,
                                      TransferLeadershipArguments,
                                      decode_group_list)
from ratis_tpu.protocol.exceptions import (LeaderNotReadyException,
                                           LeaderSteppingDownException,
                                           NotLeaderException, RaftException,
                                           RaftRetryFailureException,
                                           ReconfigurationInProgressException,
                                           TimeoutIOException)
from ratis_tpu.protocol.group import RaftGroup
from ratis_tpu.protocol.ids import ClientId, RaftGroupId, RaftPeerId
from ratis_tpu.protocol.message import Message
from ratis_tpu.protocol.peer import RaftPeer
from ratis_tpu.protocol.requests import (RaftClientReply, RaftClientRequest,
                                         ReplicationLevel, RequestType,
                                         TypeCase, admin_request_type,
                                         message_stream_request_type,
                                         read_request_type,
                                         stale_read_request_type,
                                         watch_request_type,
                                         write_request_type)
from ratis_tpu.retry.policies import (ClientRetryEvent, RetryPolicies,
                                      RetryPolicy)
from ratis_tpu.trace.tracer import STAGE_CLIENT, TRACER
from ratis_tpu.transport.base import ClientTransport
from ratis_tpu.util.timeduration import TimeDuration

LOG = logging.getLogger(__name__)

# Exceptions that mean "same leader, try again shortly".
# (ReconfigurationInProgressException is NOT here: the reference surfaces it
# to the caller rather than spinning until the other change completes.)
_RETRY_SAME = (LeaderNotReadyException, LeaderSteppingDownException)


class RaftClient:
    """Build with :meth:`builder` (mirrors RaftClient.Builder)."""

    def __init__(self, group: RaftGroup, transport: ClientTransport,
                 client_id: Optional[ClientId] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 leader_id: Optional[RaftPeerId] = None,
                 properties=None):
        self.client_id = client_id or ClientId.random_id()
        self.group = group
        self.group_id: RaftGroupId = group.group_id
        self.transport = transport
        self.properties = properties  # e.g. datastream TLS config
        self.retry_policy = retry_policy or \
            RetryPolicies.retry_up_to_maximum_count_with_fixed_sleep(
                50, TimeDuration.millis(100))
        self._peers: dict[RaftPeerId, RaftPeer] = {p.id: p for p in group.peers}
        self._leader_id = leader_id or (next(iter(self._peers)) if self._peers
                                        else None)
        self._call_ids = itertools.count(1)
        # Completed call ids awaiting piggyback to the server's retry cache
        # (reference RepliedCallIds, RaftClientImpl.java:128).
        self._replied_call_ids: set[int] = set()
        self._ordered = OrderedApi(self)
        self._message_stream = MessageStreamApi(self)
        self._data_stream = DataStreamApi(self)
        self._admin = AdminApi(self)
        self._group_mgmt = GroupManagementApi(self)
        self._snapshot_mgmt = SnapshotManagementApi(self)
        self._election_mgmt = LeaderElectionManagementApi(self)

    @staticmethod
    def builder() -> "RaftClientBuilder":
        return RaftClientBuilder()

    # ------------------------------------------------------------- sub-APIs

    def io(self) -> "OrderedApi":
        return self._ordered

    def async_api(self) -> "OrderedApi":
        return self._ordered  # one asyncio-native API serves both roles

    def message_stream(self) -> "MessageStreamApi":
        return self._message_stream

    def data_stream(self) -> "DataStreamApi":
        return self._data_stream

    def admin(self) -> "AdminApi":
        return self._admin

    def group_management(self) -> "GroupManagementApi":
        return self._group_mgmt

    def snapshot_management(self) -> "SnapshotManagementApi":
        return self._snapshot_mgmt

    def leader_election_management(self) -> "LeaderElectionManagementApi":
        return self._election_mgmt

    async def close(self) -> None:
        await self.transport.close()

    async def __aenter__(self) -> "RaftClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # ------------------------------------------------------------- plumbing

    def _address_of(self, peer_id: RaftPeerId) -> Optional[str]:
        p = self._peers.get(peer_id)
        return p.get_client_address() if p is not None else None

    def resolve_server(self, server: "RaftPeer | RaftPeerId | None"
                       ) -> Optional[RaftPeerId]:
        """Accept a RaftPeer (registering its address — needed to reach a
        brand-new server outside the group) or a bare id."""
        if isinstance(server, RaftPeer):
            self._peers.setdefault(server.id, server)
            return server.id
        return server

    def _next_peer(self, after: Optional[RaftPeerId]) -> RaftPeerId:
        ids = list(self._peers)
        if not ids:
            raise RaftException("client has no peers to contact")
        if after is None or after not in ids:
            return ids[0]
        return ids[(ids.index(after) + 1) % len(ids)]

    def _update_peers(self, peers: Iterable[RaftPeer]) -> None:
        """Refresh the peer book from a NotLeaderException's conf."""
        fresh = {p.id: p for p in peers}
        if fresh:
            self._peers = fresh
            if self._leader_id not in fresh:
                self._leader_id = next(iter(fresh))

    def _on_not_leader(self, exc: NotLeaderException) -> None:
        if exc.peers:
            self._update_peers(exc.peers)
        sug = exc.suggested_leader
        if sug is not None:
            self._peers.setdefault(sug.id, sug)
            self._leader_id = sug.id
        else:
            self._leader_id = self._next_peer(self._leader_id)

    def _new_request(self, message: Message, type_case: TypeCase,
                     server_id: Optional[RaftPeerId] = None,
                     timeout_ms: float = 3000.0,
                     group_id: Optional[RaftGroupId] = None,
                     trace_id: int = 0) -> RaftClientRequest:
        replied = tuple(self._replied_call_ids)
        self._replied_call_ids.clear()
        return RaftClientRequest(
            self.client_id,
            server_id or self._leader_id or self._next_peer(None),
            group_id or self.group_id, next(self._call_ids), message,
            type=type_case, timeout_ms=timeout_ms, replied_call_ids=replied,
            trace_id=trace_id)

    async def send_request_with_retry(self, message: Message,
                                      type_case: TypeCase,
                                      server_id: Optional[RaftPeerId] = None,
                                      timeout_ms: float = 3000.0,
                                      group_id: Optional[RaftGroupId] = None,
                                      ordering: Optional[tuple] = None
                                      ) -> RaftClientReply:
        """The failover loop (reference BlockingImpl.sendRequestWithRetry +
        RaftClientImpl.handleIOException).  ``ordering`` is the OrderedApi's
        (SlidingWindowClient, seqNum): each attempt carries the seqNum and a
        per-attempt recomputed isFirst flag, and failover resets the window's
        first marker (reference OrderedAsync.java:59 resetSlidingWindow)."""
        trace_id = TRACER.begin_trace()
        req = self._new_request(message, type_case, server_id, timeout_ms,
                                group_id, trace_id=trace_id)
        sticky = server_id is not None  # explicit target: no failover
        t0 = TRACER.now() if trace_id else 0
        try:
            return await self._retry_loop(req, sticky, ordering)
        except BaseException:
            # the piggybacked ids never reached a server that replied OK:
            # requeue them for the next request (reference RepliedCallIds
            # returns ids to the pending set on failure)
            self._replied_call_ids.update(req.replied_call_ids)
            raise
        finally:
            if trace_id:
                TRACER.record(trace_id, STAGE_CLIENT, t0, TRACER.now())

    async def _retry_loop(self, req: RaftClientRequest, sticky: bool,
                          ordering: Optional[tuple] = None
                          ) -> RaftClientReply:
        from ratis_tpu.protocol.exceptions import ResourceUnavailableException
        window, seq = ordering if ordering is not None else (None, -1)
        attempt = 0
        while True:
            retry_after_s = 0.0
            attempt += 1
            target = req.server_id if sticky else \
                (self._leader_id or self._next_peer(None))
            address = self._address_of(target)
            cause: Optional[Exception] = None
            reply: Optional[RaftClientReply] = None
            if address is None:
                cause = RaftException(f"unknown peer {target}")
                if not sticky:
                    self._leader_id = self._next_peer(target)
            else:
                try:
                    # Same call id on every attempt: the server retry cache
                    # dedupes re-executions of a write across failover.
                    attempt_req = RaftClientRequest(
                        req.client_id, target, req.group_id, req.call_id,
                        req.message, type=req.type, timeout_ms=req.timeout_ms,
                        slider_seq_num=seq,
                        slider_first=(window.is_first(seq)
                                      if window is not None else False),
                        replied_call_ids=req.replied_call_ids,
                        trace_id=req.trace_id)
                    reply = await self.transport.send_request(
                        address, attempt_req)
                except (TimeoutIOException, asyncio.TimeoutError,
                        ConnectionError, OSError) as e:
                    cause = e
                    if not sticky:
                        self._leader_id = self._next_peer(target)
                    if window is not None:
                        window.reset_first_seq()

            if reply is not None:
                if reply.success:
                    if req.type.type == RequestType.WRITE:
                        self._replied_call_ids.add(req.call_id)
                    return reply
                exc = reply.exception
                nle = reply.get_not_leader_exception()
                if nle is not None and not sticky:
                    self._on_not_leader(nle)
                    cause = nle
                    if window is not None:
                        # new server, new reorder window: the lowest
                        # outstanding seq becomes "first" again
                        window.reset_first_seq()
                elif isinstance(exc, _RETRY_SAME):
                    cause = exc
                elif isinstance(exc, ResourceUnavailableException):
                    # shed by admission control: retry the same server, but
                    # back off at least the server's retry-after hint
                    cause = exc
                    retry_after_s = exc.retry_after_ms / 1000.0
                else:
                    return reply  # a real failure: surface to the caller

            action = self.retry_policy.handle_attempt_failure(
                ClientRetryEvent(attempt, cause, req))
            if not action.should_retry:
                raise RaftRetryFailureException(
                    f"{req} failed after {attempt} attempts "
                    f"(policy {self.retry_policy}): {cause}")
            sleep = max(action.sleep_time.seconds, retry_after_s)
            if sleep > 0:
                await asyncio.sleep(sleep)


class RaftClientBuilder:
    """Reference RaftClient.Builder (ratis-client/.../RaftClient.java)."""

    def __init__(self):
        self._group: Optional[RaftGroup] = None
        self._transport: Optional[ClientTransport] = None
        self._client_id: Optional[ClientId] = None
        self._retry_policy: Optional[RetryPolicy] = None
        self._leader_id: Optional[RaftPeerId] = None
        self._properties = None
        self._transport_factory = None

    def set_raft_group(self, group: RaftGroup) -> "RaftClientBuilder":
        self._group = group
        return self

    def set_client_id(self, client_id: ClientId) -> "RaftClientBuilder":
        self._client_id = client_id
        return self

    def set_retry_policy(self, policy: RetryPolicy) -> "RaftClientBuilder":
        self._retry_policy = policy
        return self

    def set_leader_id(self, leader_id: RaftPeerId) -> "RaftClientBuilder":
        self._leader_id = leader_id
        return self

    def set_properties(self, properties) -> "RaftClientBuilder":
        self._properties = properties
        return self

    def set_transport(self, transport: ClientTransport) -> "RaftClientBuilder":
        self._transport = transport
        return self

    def set_transport_factory(self, factory) -> "RaftClientBuilder":
        self._transport_factory = factory
        return self

    def build(self) -> RaftClient:
        if self._group is None:
            raise ValueError("raft group is required")
        transport = self._transport
        if transport is None:
            if self._transport_factory is None:
                from ratis_tpu.conf.keys import RaftConfigKeys
                from ratis_tpu.transport.base import TransportFactory
                rpc_type = (RaftConfigKeys.Rpc.type(self._properties)
                            if self._properties is not None
                            else RaftConfigKeys.Rpc.TYPE_DEFAULT)
                self._transport_factory = TransportFactory.get(rpc_type)
            transport = self._transport_factory.new_client_transport(
                self._properties)
        return RaftClient(self._group, transport, self._client_id,
                          self._retry_policy, self._leader_id,
                          self._properties)


class OrderedApi:
    """Writes with seqNum-ordered pipelining (reference OrderedAsync.java:59):
    up to ``max_outstanding`` concurrent sends, each stamped with a
    consecutive seqNum from a SlidingWindowClient; the leader's per-client
    reorder window (division._write_ordered) appends them to the raft log in
    seqNum order even when the transport delivers them out of order, so two
    concurrent ``send()``s always commit in submission order."""

    def __init__(self, client: RaftClient,
                 max_outstanding: Optional[int] = None):
        from ratis_tpu.util.sliding_window import SlidingWindowClient
        if max_outstanding is None:
            # raft.client.async.outstanding-requests.max: one connection
            # carries this many pipelined ordered requests — set it in the
            # thousands for fleet-scale pipelining
            from ratis_tpu.conf.keys import RaftClientConfigKeys
            if client.properties is not None:
                max_outstanding = \
                    RaftClientConfigKeys.Async.outstanding_requests_max(
                        client.properties)
            else:
                max_outstanding = 128
        self.client = client
        self.max_outstanding = max_outstanding
        self._sem = asyncio.Semaphore(max_outstanding)
        self._window = SlidingWindowClient(name=str(client.client_id))

    async def send(self, message: "Message | bytes") -> RaftClientReply:
        """Ordered write (reference OrderedAsync.send)."""
        msg = message if isinstance(message, Message) else Message(message)
        async with self._sem:
            seq = self._window.submit_new_request(lambda s: s)
            try:
                return await self.client.send_request_with_retry(
                    msg, write_request_type(),
                    ordering=(self._window, seq))
            finally:
                self._window.receive_reply(seq)

    async def send_read_only(self, message: "Message | bytes",
                             nonlinearizable: bool = False,
                             read_after_write_consistent: bool = False,
                             server_id: Optional[RaftPeerId] = None
                             ) -> RaftClientReply:
        msg = message if isinstance(message, Message) else Message(message)
        return await self.client.send_request_with_retry(
            msg, read_request_type(nonlinearizable,
                                   read_after_write_consistent),
            server_id=server_id)

    async def send_stale_read(self, message: "Message | bytes",
                              min_index: int, server_id: RaftPeerId
                              ) -> RaftClientReply:
        msg = message if isinstance(message, Message) else Message(message)
        return await self.client.send_request_with_retry(
            msg, stale_read_request_type(min_index), server_id=server_id)

    async def watch(self, index: int,
                    replication: ReplicationLevel = ReplicationLevel.MAJORITY
                    ) -> RaftClientReply:
        return await self.client.send_request_with_retry(
            Message.EMPTY, watch_request_type(index, replication),
            timeout_ms=30_000.0)


class MessageStreamApi:
    """Split one large Message into ordered sub-requests sharing a stream id
    (reference MessageStreamImpl + RaftOutputStream,
    ratis-client/.../impl/MessageStreamImpl.java).  All chunks but the last
    must land before end_of_request replays the assembled write, so chunks
    are sent strictly in order through the same failover-aware retry loop.
    """

    DEFAULT_SUBMESSAGE_SIZE = 1 << 20

    def __init__(self, client: RaftClient,
                 submessage_size: int = DEFAULT_SUBMESSAGE_SIZE):
        self.client = client
        self.submessage_size = submessage_size
        self._stream_ids = itertools.count(1)

    async def stream_async(self, message: "Message | bytes",
                           submessage_size: Optional[int] = None
                           ) -> RaftClientReply:
        """Send ``message`` as one stream; returns the final write reply."""
        data = message.content if isinstance(message, Message) else message
        size = submessage_size or self.submessage_size
        if size <= 0:
            raise ValueError(f"submessage_size must be positive, got {size}")
        stream_id = next(self._stream_ids)
        chunks = [data[i:i + size] for i in range(0, len(data), size)] or [b""]
        for message_id, chunk in enumerate(chunks[:-1]):
            reply = await self.client.send_request_with_retry(
                Message(chunk),
                message_stream_request_type(stream_id, message_id, False))
            if not reply.success:
                return reply
        return await self.client.send_request_with_retry(
            Message(chunks[-1]),
            message_stream_request_type(stream_id, len(chunks) - 1, True))


class DataStreamOutput:
    """One open client stream (reference DataStreamOutputImpl +
    OrderedStreamAsync): header first, then pipelined data packets with a
    bounded outstanding window; ``close_async`` returns the final
    RaftClientReply of the raft write the primary submitted."""

    def __init__(self, client: "RaftClient", request: RaftClientRequest,
                 primary_address: str, routing, window: int = 16):
        from ratis_tpu.transport.datastream import DataStreamConnection
        self.client = client
        self.request = request
        self.routing = routing
        from ratis_tpu.conf.keys import NettyConfigKeys
        tls = NettyConfigKeys.DataStreamTls.tls_config(
            getattr(client, "properties", None))
        self._conn = DataStreamConnection(primary_address, tls=tls)
        self._stream_id = request.type.stream_id
        self._offset = 0
        self._sem = asyncio.Semaphore(window)
        self._acks: list[asyncio.Future] = []
        self._closed = False

    async def _open(self) -> None:
        from ratis_tpu.transport.datastream import (FLAG_PRIMARY, KIND_HEADER,
                                                    Packet, encode_header)
        await self._conn.connect()
        try:
            header = Packet(KIND_HEADER, self._stream_id, 0, FLAG_PRIMARY,
                            encode_header(self.request, self.routing))
            ack = await (await self._conn.send(header))
            if not ack.success:
                raise RaftException("datastream header rejected by primary")
        except BaseException:
            await self._conn.close()
            raise

    async def write_async(self, data: bytes, sync: bool = False) -> None:
        from ratis_tpu.transport.datastream import (FLAG_SYNC, KIND_DATA,
                                                    Packet)
        if self._closed:
            raise RaftException("stream already closed")
        if not data:
            return  # zero-length write: nothing to send, and the ack would
            # collide with the next packet's (stream, offset) key
        await self._sem.acquire()
        packet = Packet(KIND_DATA, self._stream_id, self._offset,
                        FLAG_SYNC if sync else 0, data)
        self._offset += len(data)
        fut = await self._conn.send(packet)
        fut.add_done_callback(lambda _f: self._sem.release())
        self._acks.append(fut)

    async def close_async(self) -> RaftClientReply:
        from ratis_tpu.transport.datastream import (FLAG_CLOSE, KIND_DATA,
                                                    Packet)
        if self._closed:
            raise RaftException("stream already closed")
        self._closed = True
        # Bound the whole drain+close (including the close packet's socket
        # write, which can block on a stalled primary's full receive buffer)
        # on ONE deadline derived from the header request's timeout.
        timeout_s = (self.request.timeout_ms or 30_000.0) / 1000.0
        deadline = asyncio.get_running_loop().time() + timeout_s

        def remaining() -> float:
            return max(0.001, deadline - asyncio.get_running_loop().time())

        async def _send_close_and_wait(pkt):
            return await (await self._conn.send(pkt))

        try:
            acks = await asyncio.wait_for(
                asyncio.gather(*self._acks), remaining())
            for ack in acks:
                if not ack.success:
                    raise RaftException(
                        f"datastream packet at offset {ack.offset} failed")
            close_pkt = Packet(KIND_DATA, self._stream_id, self._offset,
                               FLAG_CLOSE, b"")
            final = await asyncio.wait_for(
                _send_close_and_wait(close_pkt), remaining())
            if not final.success or not final.data:
                raise RaftException("datastream close rejected")
            return RaftClientReply.from_bytes(final.data)
        except asyncio.TimeoutError:
            raise RaftException(
                f"datastream close timed out after {timeout_s}s") from None
        finally:
            await self._conn.close()


class DataStreamApi:
    """Bulk bytes around the raft log (reference DataStreamApi /
    DataStreamClientImpl, ratis-client/.../impl/DataStreamClientImpl.java):
    stream to a primary peer which fans out per the RoutingTable, then the
    close submits one raft entry linking the data."""

    def __init__(self, client: "RaftClient"):
        self.client = client

    async def stream(self, header_message: "Message | bytes",
                     routing_table=None,
                     primary: "RaftPeer | None" = None,
                     window: int = 16) -> DataStreamOutput:
        import random

        from ratis_tpu.protocol.requests import data_stream_request_type
        from ratis_tpu.protocol.routing import RoutingTable
        msg = (header_message if isinstance(header_message, Message)
               else Message(header_message))
        c = self.client
        if primary is None:
            candidates = [p for p in c._peers.values()
                          if p.datastream_address]
            if not candidates:
                # the caller's peer list may carry RPC addresses only (e.g.
                # a CLI -peers spec); learn the full peer records — incl.
                # datastream addresses — from the group like the reference
                # client does via GroupInfo
                info = await c.group_management().group_info(
                    next(iter(c._peers)), c.group_id)
                c._update_peers(info.group.peers)
                candidates = [p for p in c._peers.values()
                              if p.datastream_address]
            if not candidates:
                raise RaftException("no peer has a datastream address")
            leader = c._peers.get(c._leader_id) if c._leader_id else None
            primary = (leader if leader is not None
                       and leader.datastream_address else candidates[0])
        if routing_table is None:
            others = [p.id for p in c._peers.values()
                      if p.id != primary.id and p.datastream_address]
            routing_table = RoutingTable.star(primary.id, others)
        stream_id = random.getrandbits(63)
        req = c._new_request(msg, data_stream_request_type(stream_id),
                             server_id=primary.id, timeout_ms=30_000.0)
        out = DataStreamOutput(c, req, primary.datastream_address,
                               routing_table, window=window)
        await out._open()
        return out


class AdminApi:
    """setConfiguration + transferLeadership (reference AdminImpl)."""

    def __init__(self, client: RaftClient):
        self.client = client

    async def set_configuration(
            self, peers: Iterable[RaftPeer],
            listeners: Iterable[RaftPeer] = (),
            mode: SetConfigurationMode = SetConfigurationMode.SET_UNCONDITIONALLY,
            current_peers: Iterable[RaftPeer] = (),
            timeout_ms: float = 30_000.0) -> RaftClientReply:
        args = SetConfigurationArguments(
            tuple(peers), tuple(listeners), mode, tuple(current_peers))
        reply = await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.SET_CONFIGURATION),
            timeout_ms=timeout_ms)
        if reply.success and mode in (SetConfigurationMode.SET_UNCONDITIONALLY,
                                      SetConfigurationMode.COMPARE_AND_SET):
            # adopt the new membership for future routing
            self.client._update_peers([*args.peers, *args.listeners])
        return reply

    async def transfer_leadership(self, new_leader: Optional[RaftPeerId],
                                  timeout_ms: float = 3000.0
                                  ) -> RaftClientReply:
        args = TransferLeadershipArguments(
            str(new_leader) if new_leader is not None else None, timeout_ms)
        reply = await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.TRANSFER_LEADERSHIP),
            timeout_ms=timeout_ms + 2000.0)
        if reply.success and new_leader is not None:
            self.client._leader_id = new_leader
        return reply


class GroupManagementApi:
    """Reference GroupManagementApi (per-server: always takes a server id)."""

    def __init__(self, client: RaftClient):
        self.client = client

    async def group_add(self, group: RaftGroup,
                        server_id: "RaftPeerId | RaftPeer"
                        ) -> RaftClientReply:
        server_id = self.client.resolve_server(server_id)
        args = GroupManagementArguments(GroupManagementOp.ADD, group=group)
        return await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.GROUP_MANAGEMENT),
            server_id=server_id)

    async def group_remove(self, group_id: RaftGroupId,
                           server_id: "RaftPeerId | RaftPeer",
                           delete_directory: bool = False) -> RaftClientReply:
        server_id = self.client.resolve_server(server_id)
        args = GroupManagementArguments(GroupManagementOp.REMOVE,
                                        group_id=group_id,
                                        delete_directory=delete_directory)
        return await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.GROUP_MANAGEMENT),
            server_id=server_id)

    async def group_list(self, server_id: "RaftPeerId | RaftPeer"
                         ) -> list[RaftGroupId]:
        server_id = self.client.resolve_server(server_id)
        reply = await self.client.send_request_with_retry(
            Message.EMPTY, admin_request_type(RequestType.GROUP_LIST),
            server_id=server_id)
        if not reply.success:
            raise reply.exception or RaftException("group list failed")
        return decode_group_list(reply.message.content)

    async def group_info(self, server_id: "RaftPeerId | RaftPeer",
                         group_id: Optional[RaftGroupId] = None
                         ) -> GroupInfoReplyData:
        server_id = self.client.resolve_server(server_id)
        reply = await self.client.send_request_with_retry(
            Message.EMPTY, admin_request_type(RequestType.GROUP_INFO),
            server_id=server_id, group_id=group_id)
        if not reply.success:
            raise reply.exception or RaftException("group info failed")
        return GroupInfoReplyData.from_payload(reply.message.content)


class SnapshotManagementApi:
    """Reference SnapshotManagementApi (create)."""

    def __init__(self, client: RaftClient):
        self.client = client

    async def create(self, creation_gap: int = 0,
                     server_id: "RaftPeerId | RaftPeer | None" = None
                     ) -> RaftClientReply:
        server_id = self.client.resolve_server(server_id)
        args = SnapshotManagementArguments(SnapshotManagementOp.CREATE,
                                           creation_gap)
        return await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.SNAPSHOT_MANAGEMENT),
            server_id=server_id)


class LeaderElectionManagementApi:
    """Reference LeaderElectionManagementApi (pause/resume candidacy)."""

    def __init__(self, client: RaftClient):
        self.client = client

    async def pause(self, server_id: "RaftPeerId | RaftPeer"
                    ) -> RaftClientReply:
        server_id = self.client.resolve_server(server_id)
        args = LeaderElectionManagementArguments(
            LeaderElectionManagementOp.PAUSE)
        return await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.LEADER_ELECTION_MANAGEMENT),
            server_id=server_id)

    async def resume(self, server_id: "RaftPeerId | RaftPeer"
                     ) -> RaftClientReply:
        server_id = self.client.resolve_server(server_id)
        args = LeaderElectionManagementArguments(
            LeaderElectionManagementOp.RESUME)
        return await self.client.send_request_with_retry(
            Message(args.to_payload()),
            admin_request_type(RequestType.LEADER_ELECTION_MANAGEMENT),
            server_id=server_id)
