"""Client package: RaftClient and sub-APIs (reference ratis-client)."""

from ratis_tpu.client.client import (AdminApi, GroupManagementApi,
                                     LeaderElectionManagementApi, OrderedApi,
                                     RaftClient, RaftClientBuilder,
                                     SnapshotManagementApi)

__all__ = ["RaftClient", "RaftClientBuilder", "OrderedApi", "AdminApi",
           "GroupManagementApi", "SnapshotManagementApi",
           "LeaderElectionManagementApi"]
