"""Time duration value type with unit parsing.

Capability parity with the reference's TimeDuration
(ratis-common/src/main/java/org/apache/ratis/util/TimeDuration.java): a
comparable, arithmetic-friendly duration parsed from strings like "150ms",
"3s", "1min".  Internally a float number of seconds (Python-idiomatic rather
than (long, TimeUnit) pairs).
"""

from __future__ import annotations

import dataclasses
import re
from typing import ClassVar

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "sec": 1.0,
    "m": 60.0,
    "min": 60.0,
    "h": 3600.0,
    "hr": 3600.0,
    "d": 86400.0,
    "day": 86400.0,
}

_PATTERN = re.compile(r"^\s*(-?\d+(?:\.\d+)?)\s*([a-zμ]*)\s*$")


@dataclasses.dataclass(frozen=True, order=True)
class TimeDuration:
    """An immutable duration; ``seconds`` is the single canonical field."""

    seconds: float

    ZERO: ClassVar["TimeDuration"]
    ONE_SECOND: ClassVar["TimeDuration"]

    @staticmethod
    def valueOf(value: "TimeDuration | str | int | float") -> "TimeDuration":
        if isinstance(value, TimeDuration):
            return value
        if isinstance(value, (int, float)):
            return TimeDuration(float(value))
        m = _PATTERN.match(value.lower())
        if not m:
            raise ValueError(f"cannot parse time duration {value!r}")
        num, unit = m.groups()
        if unit and unit not in _UNITS:
            raise ValueError(f"unknown time unit {unit!r} in {value!r}")
        return TimeDuration(float(num) * (_UNITS[unit] if unit else 1.0))

    @staticmethod
    def millis(ms: float) -> "TimeDuration":
        return TimeDuration(ms / 1e3)

    def to_ms(self) -> float:
        return self.seconds * 1e3

    def is_positive(self) -> bool:
        return self.seconds > 0

    def is_non_negative(self) -> bool:
        return self.seconds >= 0

    def multiply(self, factor: float) -> "TimeDuration":
        return TimeDuration(self.seconds * factor)

    def add(self, other: "TimeDuration | float") -> "TimeDuration":
        return TimeDuration(self.seconds + TimeDuration.valueOf(other).seconds)

    def subtract(self, other: "TimeDuration | float") -> "TimeDuration":
        return TimeDuration(self.seconds - TimeDuration.valueOf(other).seconds)

    def __str__(self) -> str:
        s = self.seconds
        if s == 0:
            return "0s"
        if abs(s) >= 1:
            return f"{s:g}s"
        if abs(s) >= 1e-3:
            return f"{s * 1e3:g}ms"
        return f"{s * 1e6:g}us"


TimeDuration.ZERO = TimeDuration(0.0)
TimeDuration.ONE_SECOND = TimeDuration(1.0)
