"""Heap discipline for multi-raft hosts (opt-in, ``raft.tpu.gc.*``).

A host carrying thousands of divisions holds millions of long-lived Python
objects.  CPython's automatic gen-2 collection walks ALL of them: measured
on this machine, a single gen-2 pass over a 10k-group heap took 52s — far
past the pause-monitor step-down threshold, so one background GC pass can
depose every leader on the server (the reference documents the identical
JVM failure mode and answers it with JvmPauseMonitor,
ratis-common/.../util/JvmPauseMonitor.java:38; this module removes the
pause instead of just detecting it).

The discipline, applied by ``RaftServer.start()`` when
``raft.tpu.gc.discipline`` is set:

- **Thresholds**: slow the gen1->gen2 promotion cascade
  (``gc.set_threshold(700, 1000, 1000)``) so automatic full collections
  become rare while the division fleet is being built.
- **Seal**: once the group set has been idle for ``raft.tpu.gc.freeze-idle``
  (i.e. bring-up is over), run ONE deliberate full collection and
  ``gc.freeze()`` the surviving heap into the permanent generation.  Frozen
  objects are never traversed again, so later gen-2 passes only walk the
  (small) post-bring-up allocation frontier.  The seal re-runs after any
  later group add/remove burst, keeping new divisions frozen too.

Everything is process-global (CPython has one collector), so multiple
in-process servers share one janitor; the module keeps refcounts and
restores the original thresholds when the last disciplined server closes.
"""

from __future__ import annotations

import gc
import logging
import time

LOG = logging.getLogger(__name__)

_DISCIPLINE_THRESHOLDS = (700, 1000, 1000)

_active = 0                 # servers with discipline enabled
_saved_thresholds = None    # thresholds to restore when _active drops to 0
_mutation_clock = 0.0       # monotonic time of the last group-set mutation
_sealed_at = -1.0           # _mutation_clock value covered by the last seal
_last_seal_s = 0.0          # monotonic time of the last seal (any cause)
seal_count = 0              # total seals this process (observable for tests)


def enable() -> None:
    """Apply the thresholds (idempotent; refcounted across servers)."""
    global _active, _saved_thresholds, _last_seal_s
    if _active == 0:
        _saved_thresholds = gc.get_threshold()
        gc.set_threshold(*_DISCIPLINE_THRESHOLDS)
        # the refreeze cadence counts from server start, not process
        # start — otherwise the first interval is already elapsed and the
        # re-seal fires mid-bring-up, the exact window it must avoid
        _last_seal_s = time.monotonic()
    _active += 1


def disable() -> None:
    global _active
    if _active == 0:
        return
    _active -= 1
    if _active == 0:
        if _saved_thresholds is not None:
            gc.set_threshold(*_saved_thresholds)
        # Thaw everything the seals froze: a closed server's division fleet
        # is cycle-rich garbage now, and a permanently-frozen heap would
        # leak it for the rest of the process.
        gc.unfreeze()


def note_mutation() -> None:
    """A group was added/removed: the heap grew, a (re-)seal is due once
    the burst settles."""
    global _mutation_clock
    _mutation_clock = time.monotonic()


def seal_due(idle_s: float) -> bool:
    if _mutation_clock <= _sealed_at:
        return False  # nothing new since the last seal
    return time.monotonic() - _mutation_clock >= idle_s


def refreeze_due(interval_s: float) -> bool:
    """Process-global steady-state cadence gate: several in-process
    servers' janitors share one collector, so one seal serves them all."""
    return time.monotonic() - _last_seal_s >= interval_s


def seal() -> float:
    """One deliberate full collection + freeze; returns its duration so
    callers can log/assert the pause they chose to take now instead of
    letting the collector take it mid-consensus later."""
    global _sealed_at, _last_seal_s, seal_count
    _sealed_at = _mutation_clock
    _last_seal_s = time.monotonic()
    seal_count += 1
    t0 = time.monotonic()
    gc.collect()
    gc.freeze()
    took = time.monotonic() - t0
    LOG.info("heap sealed: %d objects frozen in %.2fs",
             gc.get_freeze_count(), took)
    return took
